// C predict API — embeddable inference ABI.
//
// Capability parity with the reference's predict-only C API
// (include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc:334 and
// the amalgamation build that ships it as one self-contained unit):
// create a predictor from a symbol JSON + parameter blob, set inputs,
// forward, read outputs — from C/C++, no Python in the caller's code.
//
// TPU-native twist: the compute path is XLA via jax, which lives in
// Python; this library embeds a CPython interpreter (one per process,
// lazily) and drives mxnet_tpu.predictor.Predictor through the C API.
// The reference's amalgamated libmxnet_predict.so played the same
// role: one .so, flat C symbols, runtime inside.
//
// Build (see mxnet_tpu/native.py get_lib_predict):
//   g++ -O2 -std=c++17 -shared -fPIC capi_predict.cc \
//       $(python3-config --includes --ldflags --embed) -o libmxtpu_predict.so

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

std::once_flag g_init_once;
std::string g_last_error;

void EnsurePython() {
  std::call_once(g_init_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // release the GIL acquired by initialization so callers on any
      // thread can take it with PyGILState_Ensure
      PyEval_SaveThread();
    }
  });
}

struct Predictor {
  PyObject* obj = nullptr;  // mxnet_tpu.predictor.Predictor
  std::vector<float> out_buf;
};

void SetError(const char* where) {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  g_last_error = where;
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      g_last_error += ": ";
      g_last_error += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

}  // namespace

extern "C" {

const char* MXTpuGetLastError() { return g_last_error.c_str(); }

// Create a predictor.
//   symbol_json : NUL-terminated symbol JSON
//   param_bytes / param_size : NDArray container blob (nd.save format)
//   input_keys / shapes: num_input names; shape_data holds the dims of
//   input i in [shape_ind[i], shape_ind[i+1])
// Returns 0 on success.
int MXTpuPredCreate(const char* symbol_json, const void* param_bytes,
                    int param_size, int num_input,
                    const char** input_keys,
                    const unsigned* shape_ind,
                    const unsigned* shape_data, void** out) {
  EnsurePython();
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* mod = nullptr;
  PyObject* shapes = nullptr;
  PyObject* params = nullptr;
  do {
    mod = PyImport_ImportModule("mxnet_tpu.predictor");
    if (mod == nullptr) {
      SetError("import mxnet_tpu.predictor");
      break;
    }
    shapes = PyDict_New();
    for (int i = 0; i < num_input; ++i) {
      PyObject* tup = PyTuple_New(shape_ind[i + 1] - shape_ind[i]);
      for (unsigned j = shape_ind[i]; j < shape_ind[i + 1]; ++j) {
        PyTuple_SET_ITEM(tup, j - shape_ind[i],
                         PyLong_FromUnsignedLong(shape_data[j]));
      }
      PyDict_SetItemString(shapes, input_keys[i], tup);
      Py_DECREF(tup);
    }
    params = PyBytes_FromStringAndSize(
        static_cast<const char*>(param_bytes), param_size);
    PyObject* cls = PyObject_GetAttrString(mod, "Predictor");
    PyObject* obj = PyObject_CallFunction(
        cls, "sOO", symbol_json, params, shapes);
    Py_DECREF(cls);
    if (obj == nullptr) {
      SetError("Predictor()");
      break;
    }
    auto* p = new Predictor();
    p->obj = obj;
    *out = p;
    rc = 0;
  } while (false);
  Py_XDECREF(mod);
  Py_XDECREF(shapes);
  Py_XDECREF(params);
  PyGILState_Release(gil);
  return rc;
}

int MXTpuPredSetInput(void* handle, const char* key,
                      const float* data, int size) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  // route through numpy: build a list (slow but dependency-free at the
  // C level), reshape happens inside set_input via the bound shape
  PyObject* np = PyImport_ImportModule("numpy");
  if (np != nullptr) {
    PyObject* lst = PyList_New(size);
    for (int i = 0; i < size; ++i) {
      PyList_SET_ITEM(lst, i, PyFloat_FromDouble(data[i]));
    }
    PyObject* arr = PyObject_CallMethod(
        np, "asarray", "Os", lst, "float32");
    Py_DECREF(lst);
    if (arr != nullptr) {
      // reshape to the declared input shape
      PyObject* shaped = PyObject_CallMethod(
          p->obj, "_reshape_input", "sO", key, arr);
      if (shaped == nullptr) {
        PyErr_Clear();
        shaped = arr;
        Py_INCREF(shaped);
      }
      PyObject* r = PyObject_CallMethod(
          p->obj, "set_input", "sO", key, shaped);
      Py_DECREF(shaped);
      Py_DECREF(arr);
      if (r != nullptr) {
        Py_DECREF(r);
        rc = 0;
      } else {
        SetError("set_input");
      }
    } else {
      SetError("numpy.asarray");
    }
    Py_DECREF(np);
  } else {
    SetError("import numpy");
  }
  PyGILState_Release(gil);
  return rc;
}

int MXTpuPredForward(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* r = PyObject_CallMethod(p->obj, "forward", nullptr);
  if (r != nullptr) {
    Py_DECREF(r);
    rc = 0;
  } else {
    SetError("forward");
  }
  PyGILState_Release(gil);
  return rc;
}

// Copies output `index` into caller buffer (cap floats); returns the
// number of floats in the output, or -1 on error. Call with buf=NULL
// to query the size.
int MXTpuPredGetOutput(void* handle, int index, float* buf, int cap) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject* out = PyObject_CallMethod(
      p->obj, "get_output", "i", index);
  if (out != nullptr) {
    PyObject* flat = PyObject_CallMethod(out, "ravel", nullptr);
    PyObject* lst = flat
        ? PyObject_CallMethod(flat, "tolist", nullptr) : nullptr;
    if (lst != nullptr) {
      Py_ssize_t n = PyList_Size(lst);
      if (buf != nullptr) {
        for (Py_ssize_t i = 0; i < n && i < cap; ++i) {
          buf[i] = static_cast<float>(
              PyFloat_AsDouble(PyList_GET_ITEM(lst, i)));
        }
      }
      rc = static_cast<int>(n);
      Py_DECREF(lst);
    } else {
      SetError("get_output tolist");
    }
    Py_XDECREF(flat);
    Py_DECREF(out);
  } else {
    SetError("get_output");
  }
  PyGILState_Release(gil);
  return rc;
}

void MXTpuPredFree(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
}

}  // extern "C"

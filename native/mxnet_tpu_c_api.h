/* mxnet_tpu C API — the embeddable core ABI.
 *
 * Capability parity with the reference include/mxnet/c_api.h surface
 * (NDArray / imperative invoke / Symbol / Executor tiers) plus the
 * predict-only ABI in capi_predict.cc (c_predict_api.h analog).
 *
 * Conventions:
 *   - all functions return 0 on success, nonzero on failure;
 *     MXTpuGetLastError() returns the calling THREAD's last error
 *     (reference src/c_api/c_api_error.cc TLS semantics).
 *   - void* handles are opaque; release with MXTpuHandleFree.
 *   - "list" outputs (names, handles, shapes) point into per-thread
 *     storage owned by the library, valid until the same thread's next
 *     API call — copy before calling again.
 *   - shape packing: entity i's dims occupy
 *     shape_data[shape_ind[i] .. shape_ind[i+1]).
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

const char* MXTpuGetLastError(void);
int MXTpuHandleFree(void* handle);

/* ---- NDArray ---- */
int MXTpuNDArrayCreate(const int* shape, int ndim, const float* data,
                       void** out);
int MXTpuNDArrayZeros(const int* shape, int ndim, void** out);
int MXTpuNDArrayGetShape(void* handle, int* shape, int cap, int* ndim);
long MXTpuNDArrayCopyOut(void* handle, float* buf, long cap);
int MXTpuNDArrayCopyIn(void* handle, const float* data, long size);
int MXTpuNDArraySave(const char* fname, int num, void** handles,
                     const char** keys);
int MXTpuNDArrayLoad(const char* fname, int* num_out, void*** out,
                     int* num_keys, const char*** keys);

/* ---- imperative op invocation ---- */
int MXTpuImperativeInvoke(const char* op, int num_in, void** inputs,
                          int num_params, const char** keys,
                          const char** vals, int* num_out,
                          void*** outputs);
int MXTpuImperativeInvokeInto(const char* op, int num_in, void** inputs,
                              int num_params, const char** keys,
                              const char** vals, int num_out,
                              void** outputs);

/* ---- Symbol ---- */
int MXTpuSymbolCreateVariable(const char* name, void** out);
int MXTpuSymbolCreate(const char* op, int num_params,
                      const char** param_keys, const char** param_vals,
                      const char* name, int num_in,
                      const char** input_keys, void** input_syms,
                      void** out);
int MXTpuSymbolFromJSON(const char* json, void** out);
int MXTpuSymbolToJSON(void* sym, const char** out_json);
int MXTpuSymbolList(void* sym, const char* kind /* arg|out|aux */,
                    int* num, const char*** out);
int MXTpuSymbolInferShape(void* sym, int num_in, const char** names,
                          const int* shape_ind, const int* shape_data,
                          int* num_arg, const int** arg_ind,
                          const int** arg_data);

/* ---- Executor ---- */
int MXTpuExecutorSimpleBind(void* sym, const char* ctx_type,
                            int dev_id, const char* grad_req,
                            int num_in, const char** names,
                            const int* shape_ind,
                            const int* shape_data, void** out);
int MXTpuExecutorForward(void* ex, int is_train);
int MXTpuExecutorBackward(void* ex);
int MXTpuExecutorOutputs(void* ex, int* num, void*** out);
int MXTpuExecutorArray(void* ex, const char* name,
                       const char* kind /* arg|grad|aux */, void** out);

/* ---- predict-only ABI (capi_predict.cc) ---- */
int MXTpuPredCreate(const char* symbol_json, const void* param_bytes,
                    int param_size, int num_input,
                    const char** input_keys, const unsigned* shape_ind,
                    const unsigned* shape_data, void** out);
int MXTpuPredSetInput(void* handle, const char* key, const float* data,
                      int size);
int MXTpuPredForward(void* handle);
int MXTpuPredGetOutput(void* handle, int index, float* buf, int cap);
void MXTpuPredFree(void* handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_API_H_ */

/* mxnet_tpu C API — the embeddable core ABI.
 *
 * Capability parity with the reference include/mxnet/c_api.h surface
 * (NDArray / imperative invoke / Symbol / Executor tiers) plus the
 * predict-only ABI in capi_predict.cc (c_predict_api.h analog).
 *
 * Conventions:
 *   - all functions return 0 on success, nonzero on failure;
 *     MXTpuGetLastError() returns the calling THREAD's last error
 *     (reference src/c_api/c_api_error.cc TLS semantics).
 *   - void* handles are opaque; release with MXTpuHandleFree.
 *   - "list" outputs (names, handles, shapes) point into per-thread
 *     storage owned by the library, valid until the same thread's next
 *     API call — copy before calling again.
 *   - shape packing: entity i's dims occupy
 *     shape_data[shape_ind[i] .. shape_ind[i+1]).
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

const char* MXTpuGetLastError(void);
int MXTpuHandleFree(void* handle);

/* Callback ABI: handles passed to callbacks are BORROWED and valid
 * only for the duration of the call (do not free them). */
typedef void (*MXTpuKVUpdater)(int key, void* recv, void* local,
                               void* payload);
typedef void (*MXTpuMonitorCallback)(const char* name, void* arr,
                                     void* payload);

/* ---- NDArray ---- */
int MXTpuNDArrayCreate(const int* shape, int ndim, const float* data,
                       void** out);
int MXTpuNDArrayZeros(const int* shape, int ndim, void** out);
int MXTpuNDArrayGetShape(void* handle, int* shape, int cap, int* ndim);
long MXTpuNDArrayCopyOut(void* handle, float* buf, long cap);
int MXTpuNDArrayCopyIn(void* handle, const float* data, long size);
int MXTpuNDArraySave(const char* fname, int num, void** handles,
                     const char** keys);
int MXTpuNDArrayLoad(const char* fname, int* num_out, void*** out,
                     int* num_keys, const char*** keys);

/* ---- imperative op invocation ---- */
int MXTpuImperativeInvoke(const char* op, int num_in, void** inputs,
                          int num_params, const char** keys,
                          const char** vals, int* num_out,
                          void*** outputs);
int MXTpuImperativeInvokeInto(const char* op, int num_in, void** inputs,
                              int num_params, const char** keys,
                              const char** vals, int num_out,
                              void** outputs);

/* ---- Symbol ---- */
int MXTpuSymbolCreateVariable(const char* name, void** out);
int MXTpuSymbolCreate(const char* op, int num_params,
                      const char** param_keys, const char** param_vals,
                      const char* name, int num_in,
                      const char** input_keys, void** input_syms,
                      void** out);
int MXTpuSymbolFromJSON(const char* json, void** out);
int MXTpuSymbolToJSON(void* sym, const char** out_json);
int MXTpuSymbolList(void* sym, const char* kind /* arg|out|aux */,
                    int* num, const char*** out);
int MXTpuSymbolInferShape(void* sym, int num_in, const char** names,
                          const int* shape_ind, const int* shape_data,
                          int* num_arg, const int** arg_ind,
                          const int** arg_data);

/* ---- Executor ---- */
int MXTpuExecutorSimpleBind(void* sym, const char* ctx_type,
                            int dev_id, const char* grad_req,
                            int num_in, const char** names,
                            const int* shape_ind,
                            const int* shape_data, void** out);
int MXTpuExecutorForward(void* ex, int is_train);
int MXTpuExecutorBackward(void* ex);
int MXTpuExecutorOutputs(void* ex, int* num, void*** out);
int MXTpuExecutorArray(void* ex, const char* name,
                       const char* kind /* arg|grad|aux */, void** out);

int MXTpuExecutorSetMonitorCallback(void* ex,
                                    MXTpuMonitorCallback cb,
                                    void* payload);

/* ---- DataIter (reference c_api.h:1096-1185) ---- */
int MXTpuListDataIters(int* num, const char*** names);
int MXTpuDataIterCreate(const char* name, int num_params,
                        const char** keys, const char** vals,
                        void** out);
int MXTpuDataIterNext(void* it, int* out /* 1=batch, 0=end */);
int MXTpuDataIterBeforeFirst(void* it);
int MXTpuDataIterGetData(void* it, void** out);
int MXTpuDataIterGetLabel(void* it, void** out);
int MXTpuDataIterGetPadNum(void* it, int* pad);
/* *num = 0 when the iterator doesn't track indices. */
int MXTpuDataIterGetIndex(void* it, int* num, const int** out);
int MXTpuDataIterGetIterInfo(const char* name,
                             const char** description,
                             int* num_params,
                             const char*** param_names);

/* ---- KVStore (reference c_api.h:1207-1397) ---- */
int MXTpuKVStoreCreate(const char* type, void** out);
int MXTpuKVStoreInit(void* kv, int num, const int* keys, void** vals);
int MXTpuKVStorePush(void* kv, int num, const int* keys, void** vals);
int MXTpuKVStorePull(void* kv, int num, const int* keys, void** outs);
int MXTpuKVStoreSetUpdater(void* kv, MXTpuKVUpdater cb, void* payload);
int MXTpuKVStoreGetType(void* kv, const char** out);
int MXTpuKVStoreGetRank(void* kv, int* rank);
int MXTpuKVStoreGetGroupSize(void* kv, int* size);
int MXTpuKVStoreBarrier(void* kv);
int MXTpuKVStoreGetNumDeadNode(void* kv, int node_id, int timeout,
                               int* dead);
int MXTpuKVStoreSetOptimizer(void* kv, const char* opt_name,
                             int num_params, const char** keys,
                             const char** vals);
int MXTpuKVStoreRunServer(void* kv);
int MXTpuKVStoreSetBarrierBeforeExit(void* kv, int flag);

/* ---- Executor extras (reference MXExecutorReshape, copy-params,
 * MXExecutorPrint) ---- */
int MXTpuExecutorReshape(void* ex, int num_in, const char** names,
                         const int* shape_ind, const int* shape_data,
                         void** out);
int MXTpuExecutorCopyParamsFrom(void* ex, int num, const char** names,
                                void** handles, int allow_extra);
int MXTpuExecutorPrint(void* ex, const char** out);

/* ---- Autograd (reference c_api.h:529-546) ---- */
int MXTpuAutogradSetIsTraining(int is_training, int* prev);
int MXTpuAutogradMarkVariables(int num, void** var_handles,
                               void** grad_handles);
int MXTpuAutogradComputeGradient(int num, void** output_handles);

/* ---- NDArray views / introspection (reference c_api.h MXNDArraySlice,
 * MXNDArrayAt, MXNDArrayReshape, MXNDArrayGetDType, MXNDArrayGetContext,
 * MXNDArrayWaitToRead, MXNDArrayWaitAll, MXNDArraySaveRawBytes,
 * MXNDArrayLoadFromRawBytes) ---- */
int MXTpuNDArraySlice(void* handle, int start, int stop, void** out);
int MXTpuNDArrayAt(void* handle, int idx, void** out);
int MXTpuNDArrayReshape(void* handle, int ndim, const int* dims,
                        void** out);
int MXTpuNDArrayGetDType(void* handle, int* dtype);
int MXTpuNDArrayGetContext(void* handle, const char** dev_type,
                           int* dev_id);
int MXTpuNDArrayWaitToRead(void* handle);
int MXTpuNDArrayWaitAll(void);
/* Serialized single-array blob; buffer lives in per-thread storage. */
int MXTpuNDArraySaveRawBytes(void* handle, const char** buf,
                             long* size);
int MXTpuNDArrayLoadFromRawBytes(const void* buf, long size, void** out);

/* ---- Symbol attributes / structure (reference c_api.h MXSymbolGetAttr,
 * MXSymbolSetAttr, MXSymbolListAttr, MXSymbolGetInternals,
 * MXSymbolGetOutput, MXSymbolGetChildren, MXSymbolGetName, MXSymbolCopy,
 * MXSymbolInferType) ---- */
int MXTpuSymbolGetAttr(void* sym, const char* key, const char** out,
                       int* success);
int MXTpuSymbolSetAttr(void* sym, const char* key, const char* value);
/* out = flattened [k0, v0, k1, v1, ...]; num = pair count. */
int MXTpuSymbolListAttr(void* sym, int* num, const char*** out);
int MXTpuSymbolGetInternals(void* sym, void** out);
int MXTpuSymbolGetOutput(void* sym, int index, void** out);
int MXTpuSymbolGetChildren(void* sym, void** out);
int MXTpuSymbolGetName(void* sym, const char** out, int* success);
int MXTpuSymbolCopy(void* sym, void** out);
/* dtype codes follow the NDArray save format (0=f32 1=f64 2=f16 ...). */
int MXTpuSymbolInferType(void* sym, int num_in, const char** names,
                         const int* dtypes, int* num_arg,
                         const int** arg_dtypes);

int MXTpuSymbolCreateFromFile(const char* fname, void** out);
int MXTpuSymbolSaveToFile(void* sym, const char* fname);
int MXTpuSymbolCreateGroup(int num, void** syms, void** out);
int MXTpuSymbolInferShapePartial(void* sym, int num_in,
                                 const char** names,
                                 const int* shape_ind,
                                 const int* shape_data, int* num_arg,
                                 const int** arg_ind,
                                 const int** arg_data);

/* ---- Custom ops from C (reference MXCustomOpRegister) ----
 * Callback handles are BORROWED NDArrays; mutate outputs through the
 * NDArray ABI. backward may be NULL (zero input gradients). */
typedef void (*MXTpuCustomOpCB)(int num_in, void** ins, int num_out,
                                void** outs, void* payload);
int MXTpuCustomOpRegister(const char* op_type, int num_inputs,
                          int num_outputs, MXTpuCustomOpCB forward,
                          MXTpuCustomOpCB backward, void* payload);

/* ---- RTC (reference MXRtcCreate/Push/Free; source text defines a
 * Pallas kernel function instead of CUDA) ---- */
int MXTpuRtcCreate(const char* name, const char* py_source,
                   const char* kernel_fn_name, void** out);
int MXTpuRtcPush(void* handle, int num_in, void** ins, int num_out,
                 void** outs);
int MXTpuRtcFree(void* handle);

/* ---- Op listing / docs (reference MXListAllOpNames,
 * MXSymbolGetAtomicSymbolInfo) ---- */
int MXTpuListAllOpNames(int* num, const char*** names);
/* description + input names + param keys for one op; all outputs live
 * in per-thread storage. */
int MXTpuOpGetInfo(const char* op, const char** description,
                   int* num_args, const char*** arg_names,
                   int* num_params, const char*** param_keys);

/* ---- RecordIO (reference c_api.h MXRecordIO*) ---- */
int MXTpuRecordIOWriterCreate(const char* path, void** out);
int MXTpuRecordIOWriterWriteRecord(void* handle, const char* buf,
                                   long size);
int MXTpuRecordIOWriterTell(void* handle, long* pos);
int MXTpuRecordIOWriterFree(void* handle);
int MXTpuRecordIOReaderCreate(const char* path, void** out);
/* *buf = NULL at end of file (a 0-length record keeps *buf non-NULL);
 * record bytes live in per-thread storage. */
int MXTpuRecordIOReaderReadRecord(void* handle, const char** buf,
                                  long* size);
int MXTpuRecordIOReaderSeek(void* handle, long pos);
int MXTpuRecordIOReaderFree(void* handle);

/* ---- Profiler (reference MXSetProfilerConfig/State, MXDumpProfile) */
int MXTpuSetProfilerConfig(int mode /* 0=symbolic 1=all */,
                           const char* filename);
int MXTpuSetProfilerState(int state /* 0=stop 1=run */);
int MXTpuDumpProfile(void);

/* ---- runtime (reference MXRandomSeed, MXNotifyShutdown, MXInitPSEnv,
 * MXKVStoreIsWorkerNode/IsServerNode/IsSchedulerNode) ---- */
int MXTpuRandomSeed(int seed);
int MXTpuNotifyShutdown(void);
int MXTpuInitPSEnv(int num, const char** keys, const char** vals);
int MXTpuKVStoreIsWorkerNode(int* out);
int MXTpuKVStoreIsServerNode(int* out);
int MXTpuKVStoreIsSchedulerNode(int* out);

/* ---- predict-only ABI (capi_predict.cc) ---- */
int MXTpuPredCreate(const char* symbol_json, const void* param_bytes,
                    int param_size, int num_input,
                    const char** input_keys, const unsigned* shape_ind,
                    const unsigned* shape_data, void** out);
int MXTpuPredSetInput(void* handle, const char* key, const float* data,
                      int size);
int MXTpuPredForward(void* handle);
int MXTpuPredGetOutput(void* handle, int index, float* buf, int cap);
void MXTpuPredFree(void* handle);
/* outputs = named INTERNAL layer heads (MXPredCreatePartialOut) */
int MXTpuPredCreatePartialOut(const char* symbol_json,
                              const void* param_bytes, int param_size,
                              int num_input, const char** input_keys,
                              const unsigned* shape_ind,
                              const unsigned* shape_data,
                              int num_output, const char** output_keys,
                              void** out);
/* new handle at new input shapes, sharing weights (MXPredReshape) */
int MXTpuPredReshape(int num_input, const char** input_keys,
                     const unsigned* shape_ind,
                     const unsigned* shape_data, void* handle,
                     void** out);
/* step-wise forward; outputs valid once *step_left == 0
   (MXPredPartialForward; emulated under XLA — one fused program) */
int MXTpuPredPartialForward(void* handle, int step, int* step_left);
/* writes up to cap dims, returns ndim (MXPredGetOutputShape; caller
   owns the buffer — no valid-until-next-call aliasing) */
int MXTpuPredGetOutputShape(void* handle, int index, unsigned* dims,
                            int cap);
/* NDArray container blob -> named float32 arrays readable from C
   (MXNDListCreate/Get/Free); Get pointers live until Free */
int MXTpuNDListCreate(const char* nd_file_bytes, int nd_file_size,
                      void** out, int* out_len);
int MXTpuNDListGet(void* handle, int index, const char** out_key,
                   const float** out_data, const unsigned** out_shape,
                   unsigned* out_ndim);
void MXTpuNDListFree(void* handle);

/* ------------------------------------------------------------------
 * Deliberately-dropped reference ABI tail (so completeness is
 * auditable by diffing names against include/mxnet/c_api.h):
 *
 *   MXListFunctions / MXGetFunction / MXFuncGetInfo / MXFuncDescribe /
 *   MXFuncInvoke / MXFuncInvokeEx (c_api.h:383-497)
 *     The deprecated pre-NNVM "Function" registry tier. The reference
 *     itself superseded it with the atomic-symbol/imperative-invoke
 *     path; this build has ONE op registry surfaced through
 *     MXTpuListAllOpNames/MXTpuImperativeInvoke, so a second legacy
 *     enumeration of the same ops would be dead weight.
 *
 *   MXKVStoreSendCommmandToServers (c_api.h:1383)  [sic]
 *     Ships a pickled optimizer to parameter-server processes. There
 *     are NO server processes in the TPU design — the optimizer runs
 *     in the fused step on every worker (sync) or in the co-hosted
 *     async server thread (kvstore_async.py), both configured
 *     in-process; a cross-process command channel has nothing to
 *     command.
 *
 *   MXRecordIOWriterTell / MXRecordIOReaderSeek  and the cython
 *     MXNDArray* duplicates of ctypes entry points are subsumed by
 *     the Python recordio/ndarray layers (native/recordio_core.cc
 *     carries the IO hot path).
 * ------------------------------------------------------------------ */

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_API_H_ */

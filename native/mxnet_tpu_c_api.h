/* mxnet_tpu C API — the embeddable core ABI.
 *
 * Capability parity with the reference include/mxnet/c_api.h surface
 * (NDArray / imperative invoke / Symbol / Executor tiers) plus the
 * predict-only ABI in capi_predict.cc (c_predict_api.h analog).
 *
 * Conventions:
 *   - all functions return 0 on success, nonzero on failure;
 *     MXTpuGetLastError() returns the calling THREAD's last error
 *     (reference src/c_api/c_api_error.cc TLS semantics).
 *   - void* handles are opaque; release with MXTpuHandleFree.
 *   - "list" outputs (names, handles, shapes) point into per-thread
 *     storage owned by the library, valid until the same thread's next
 *     API call — copy before calling again.
 *   - shape packing: entity i's dims occupy
 *     shape_data[shape_ind[i] .. shape_ind[i+1]).
 */
#ifndef MXNET_TPU_C_API_H_
#define MXNET_TPU_C_API_H_

#ifdef __cplusplus
extern "C" {
#endif

const char* MXTpuGetLastError(void);
int MXTpuHandleFree(void* handle);

/* Callback ABI: handles passed to callbacks are BORROWED and valid
 * only for the duration of the call (do not free them). */
typedef void (*MXTpuKVUpdater)(int key, void* recv, void* local,
                               void* payload);
typedef void (*MXTpuMonitorCallback)(const char* name, void* arr,
                                     void* payload);

/* ---- NDArray ---- */
int MXTpuNDArrayCreate(const int* shape, int ndim, const float* data,
                       void** out);
int MXTpuNDArrayZeros(const int* shape, int ndim, void** out);
int MXTpuNDArrayGetShape(void* handle, int* shape, int cap, int* ndim);
long MXTpuNDArrayCopyOut(void* handle, float* buf, long cap);
int MXTpuNDArrayCopyIn(void* handle, const float* data, long size);
int MXTpuNDArraySave(const char* fname, int num, void** handles,
                     const char** keys);
int MXTpuNDArrayLoad(const char* fname, int* num_out, void*** out,
                     int* num_keys, const char*** keys);

/* ---- imperative op invocation ---- */
int MXTpuImperativeInvoke(const char* op, int num_in, void** inputs,
                          int num_params, const char** keys,
                          const char** vals, int* num_out,
                          void*** outputs);
int MXTpuImperativeInvokeInto(const char* op, int num_in, void** inputs,
                              int num_params, const char** keys,
                              const char** vals, int num_out,
                              void** outputs);

/* ---- Symbol ---- */
int MXTpuSymbolCreateVariable(const char* name, void** out);
int MXTpuSymbolCreate(const char* op, int num_params,
                      const char** param_keys, const char** param_vals,
                      const char* name, int num_in,
                      const char** input_keys, void** input_syms,
                      void** out);
int MXTpuSymbolFromJSON(const char* json, void** out);
int MXTpuSymbolToJSON(void* sym, const char** out_json);
int MXTpuSymbolList(void* sym, const char* kind /* arg|out|aux */,
                    int* num, const char*** out);
int MXTpuSymbolInferShape(void* sym, int num_in, const char** names,
                          const int* shape_ind, const int* shape_data,
                          int* num_arg, const int** arg_ind,
                          const int** arg_data);

/* ---- Executor ---- */
int MXTpuExecutorSimpleBind(void* sym, const char* ctx_type,
                            int dev_id, const char* grad_req,
                            int num_in, const char** names,
                            const int* shape_ind,
                            const int* shape_data, void** out);
int MXTpuExecutorForward(void* ex, int is_train);
int MXTpuExecutorBackward(void* ex);
int MXTpuExecutorOutputs(void* ex, int* num, void*** out);
int MXTpuExecutorArray(void* ex, const char* name,
                       const char* kind /* arg|grad|aux */, void** out);

int MXTpuExecutorSetMonitorCallback(void* ex,
                                    MXTpuMonitorCallback cb,
                                    void* payload);

/* ---- DataIter (reference c_api.h:1096-1185) ---- */
int MXTpuListDataIters(int* num, const char*** names);
int MXTpuDataIterCreate(const char* name, int num_params,
                        const char** keys, const char** vals,
                        void** out);
int MXTpuDataIterNext(void* it, int* out /* 1=batch, 0=end */);
int MXTpuDataIterBeforeFirst(void* it);
int MXTpuDataIterGetData(void* it, void** out);
int MXTpuDataIterGetLabel(void* it, void** out);
int MXTpuDataIterGetPadNum(void* it, int* pad);

/* ---- KVStore (reference c_api.h:1207-1397) ---- */
int MXTpuKVStoreCreate(const char* type, void** out);
int MXTpuKVStoreInit(void* kv, int num, const int* keys, void** vals);
int MXTpuKVStorePush(void* kv, int num, const int* keys, void** vals);
int MXTpuKVStorePull(void* kv, int num, const int* keys, void** outs);
int MXTpuKVStoreSetUpdater(void* kv, MXTpuKVUpdater cb, void* payload);
int MXTpuKVStoreGetType(void* kv, const char** out);
int MXTpuKVStoreGetRank(void* kv, int* rank);
int MXTpuKVStoreGetGroupSize(void* kv, int* size);
int MXTpuKVStoreBarrier(void* kv);
int MXTpuKVStoreGetNumDeadNode(void* kv, int node_id, int timeout,
                               int* dead);

/* ---- Autograd (reference c_api.h:529-546) ---- */
int MXTpuAutogradSetIsTraining(int is_training, int* prev);
int MXTpuAutogradMarkVariables(int num, void** var_handles,
                               void** grad_handles);
int MXTpuAutogradComputeGradient(int num, void** output_handles);

/* ---- predict-only ABI (capi_predict.cc) ---- */
int MXTpuPredCreate(const char* symbol_json, const void* param_bytes,
                    int param_size, int num_input,
                    const char** input_keys, const unsigned* shape_ind,
                    const unsigned* shape_data, void** out);
int MXTpuPredSetInput(void* handle, const char* key, const float* data,
                      int size);
int MXTpuPredForward(void* handle);
int MXTpuPredGetOutput(void* handle, int index, float* buf, int cap);
void MXTpuPredFree(void* handle);

#ifdef __cplusplus
}
#endif

#endif  /* MXNET_TPU_C_API_H_ */

// Threaded JPEG decode + augment + batch-layout worker pool.
//
// TPU-native analog of the reference's fused OMP parser
// (src/io/iter_image_recordio_2.cc ImageRecordIOParser2): one native
// call turns a batch of JPEG blobs into the final training tensor —
// decode (libjpeg, DCT-scaled to the smallest sufficient size),
// resize-shorter-side, random/center crop (scale_down semantics),
// horizontal mirror, mean/std normalize, CHW float32 write — with a
// persistent pthread pool so no per-batch thread spawn and no Python
// in the per-image loop.
//
// Plain C ABI consumed via ctypes (mxnet_tpu/native.py); pybind11 is
// deliberately not used (not in the image).

#include <cstdio>  // jpeglib.h needs FILE declared first

#include <jpeglib.h>

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- jpeg
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// Decode a JPEG blob to RGB. Picks libjpeg's M/8 DCT scaling so the
// decoded image is the smallest one still >= min_side on its shorter
// edge (the cheap first resize the reference gets from
// cv::IMREAD_REDUCED). Returns false on any decode error.
bool decode_jpeg(const uint8_t* buf, size_t len, int min_side,
                 std::vector<uint8_t>* out, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  // choose num/8 scaling: smallest output whose shorter side >= min_side
  if (min_side > 0) {
    const int shorter = cinfo.image_width < cinfo.image_height
                            ? cinfo.image_width
                            : cinfo.image_height;
    int num = 8;
    while (num > 1 && shorter * (num - 1) / 8 >= min_side) --num;
    cinfo.scale_num = num;
    cinfo.scale_denom = 8;
  }
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  if (cinfo.output_components != 3) {
    // grayscale/CMYK: decode then expand below via libjpeg's own
    // conversion was requested (JCS_RGB), so components==3 normally;
    // anything else is unsupported here
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  out->resize(static_cast<size_t>(*w) * *h * 3);
  const int stride = *w * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
                   static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ------------------------------------------------------------- resize
// Bilinear RGB resize (uint8), matching PIL/cv2 half-pixel sampling.
void resize_bilinear(const uint8_t* src, int sw, int sh, uint8_t* dst,
                     int dw, int dh) {
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    const float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      const float wx = fx - x0;
      const uint8_t* p00 = src + (static_cast<size_t>(y0) * sw + x0) * 3;
      const uint8_t* p01 = src + (static_cast<size_t>(y0) * sw + x1) * 3;
      const uint8_t* p10 = src + (static_cast<size_t>(y1) * sw + x0) * 3;
      const uint8_t* p11 = src + (static_cast<size_t>(y1) * sw + x1) * 3;
      uint8_t* d = dst + (static_cast<size_t>(y) * dw + x) * 3;
      for (int c = 0; c < 3; ++c) {
        const float top = p00[c] + (p01[c] - p00[c]) * wx;
        const float bot = p10[c] + (p11[c] - p10[c]) * wx;
        d[c] = static_cast<uint8_t>(top + (bot - top) * wy + 0.5f);
      }
    }
  }
}

// ---------------------------------------------------------------- rng
// splitmix64: deterministic per (seed, image index) — reproducible
// augmentation independent of thread scheduling.
uint64_t splitmix(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Job {
  const uint8_t* blob = nullptr;
  const int64_t* offs = nullptr;
  const int64_t* lens = nullptr;
  int n = 0;
  int out_h = 0, out_w = 0;
  int resize_short = 0;
  int rand_crop = 0;
  int rand_mirror = 0;
  int chw = 1;                  // 1 = (3,H,W) planes, 0 = (H,W,3)
  uint64_t seed = 0;
  const float* mean = nullptr;  // len 3 or null
  const float* stdv = nullptr;  // len 3 or null
  // color jitter + PCA lighting (reference image_aug_default.cc and
  // python ColorJitterAug/LightingAug): 0 disables each
  float brightness = 0.f;
  float contrast = 0.f;
  float saturation = 0.f;
  float pca_noise = 0.f;
  float* out = nullptr;         // (n, 3, out_h, out_w) or (n,H,W,3)
  uint8_t* out_u8 = nullptr;    // uint8 variant (reference
                                // ImageRecordIter2 uint8 registration,
                                // iter_image_recordio_2.cc:579): raw
                                // pixels, no normalize — host->device
                                // transfer is 4x smaller, normalize
                                // runs on device
  uint8_t* ok = nullptr;        // per-image success
};

// uniform [0,1) from one splitmix draw
inline double u01(uint64_t r) {
  return static_cast<double>(r >> 11) / 9007199254740992.0;
}

// ImageNet PCA lighting basis (python CreateAugmenter image.py:270)
const float kEigval[3] = {55.46f, 4.794f, 1.148f};
const float kEigvec[3][3] = {{-0.5675f, 0.7192f, 0.4009f},
                             {-0.5808f, -0.0045f, -0.8140f},
                             {-0.5836f, -0.6948f, 0.4203f}};

// Apply color jitter (random order, matching RandomOrderAug) and PCA
// lighting to a float RGB buffer in [0,255]. `r` advances the
// per-image RNG chain; returns the advanced state.
uint64_t color_augment(const Job& j, float* px, int npx, uint64_t r) {
  // which jitter ops are on: 0=brightness 1=contrast 2=saturation
  int ops[3], nops = 0;
  if (j.brightness > 0.f) ops[nops++] = 0;
  if (j.contrast > 0.f) ops[nops++] = 1;
  if (j.saturation > 0.f) ops[nops++] = 2;
  // Fisher-Yates shuffle of the enabled ops (RandomOrderAug)
  for (int k = nops - 1; k > 0; --k) {
    const int m = static_cast<int>(r % (k + 1));
    r = splitmix(r);
    const int tmp = ops[k];
    ops[k] = ops[m];
    ops[m] = tmp;
  }
  for (int oi = 0; oi < nops; ++oi) {
    float range = ops[oi] == 0 ? j.brightness
                  : ops[oi] == 1 ? j.contrast
                                 : j.saturation;
    const float alpha =
        1.f + static_cast<float>(u01(r) * 2.0 - 1.0) * range;
    r = splitmix(r);
    if (ops[oi] == 0) {  // brightness: arr *= alpha, clip
      for (int p = 0; p < npx * 3; ++p) {
        float v = px[p] * alpha;
        px[p] = v < 0.f ? 0.f : (v > 255.f ? 255.f : v);
      }
    } else if (ops[oi] == 1) {  // contrast: toward mean gray
      double gsum = 0.0;
      for (int p = 0; p < npx; ++p)
        gsum += 0.299f * px[3 * p] + 0.587f * px[3 * p + 1] +
                0.114f * px[3 * p + 2];
      const float gmean =
          static_cast<float>(gsum / npx) * (1.f - alpha);
      for (int p = 0; p < npx * 3; ++p) {
        float v = px[p] * alpha + gmean;
        px[p] = v < 0.f ? 0.f : (v > 255.f ? 255.f : v);
      }
    } else {  // saturation: toward per-pixel gray
      for (int p = 0; p < npx; ++p) {
        const float gray =
            (0.299f * px[3 * p] + 0.587f * px[3 * p + 1] +
             0.114f * px[3 * p + 2]) *
            (1.f - alpha);
        for (int c = 0; c < 3; ++c) {
          float v = px[3 * p + c] * alpha + gray;
          px[3 * p + c] = v < 0.f ? 0.f : (v > 255.f ? 255.f : v);
        }
      }
    }
  }
  if (j.pca_noise > 0.f) {
    // alpha ~ N(0, pca_noise)^3 via Box-Muller; rgb = (eigvec*alpha)@eigval
    float alpha[3];
    for (int k = 0; k < 3; ++k) {
      const double uu = u01(r) + 1e-12;
      r = splitmix(r);
      const double vv = u01(r);
      r = splitmix(r);
      alpha[k] = static_cast<float>(
          std::sqrt(-2.0 * std::log(uu)) *
          std::cos(2.0 * 3.14159265358979323846 * vv) * j.pca_noise);
    }
    float rgb[3];
    for (int c = 0; c < 3; ++c)
      rgb[c] = kEigvec[c][0] * alpha[0] * kEigval[0] +
               kEigvec[c][1] * alpha[1] * kEigval[1] +
               kEigvec[c][2] * alpha[2] * kEigval[2];
    for (int p = 0; p < npx; ++p)
      for (int c = 0; c < 3; ++c) px[3 * p + c] += rgb[c];  // no clip
  }
  return r;
}

void scale_down(int sw, int sh, int* cw, int* ch) {
  // reference image.py:33 — shrink the crop to fit the source while
  // keeping the requested aspect
  float w = static_cast<float>(*cw), h = static_cast<float>(*ch);
  if (sh < h) {
    w = w * sh / h;
    h = static_cast<float>(sh);
  }
  if (sw < w) {
    h = h * sw / w;
    w = static_cast<float>(sw);
  }
  *cw = static_cast<int>(w);
  *ch = static_cast<int>(h);
}

void process_one(const Job& j, int i, std::vector<uint8_t>* scratch,
                 std::vector<uint8_t>* scratch2) {
  j.ok[i] = 0;
  const uint8_t* buf = j.blob + j.offs[i];
  const size_t len = static_cast<size_t>(j.lens[i]);
  // DCT-scaled decode is only geometry-preserving when a shorter-side
  // resize follows (it approximates that resize's first octaves); a
  // bare crop must see the full-resolution image, like the python path
  const int min_side = j.resize_short > 0 ? j.resize_short : 0;
  int w = 0, h = 0;
  if (!decode_jpeg(buf, len, min_side, scratch, &w, &h)) return;

  // resize shorter side
  if (j.resize_short > 0 && (w < h ? w : h) != j.resize_short) {
    int nw, nh;
    if (h > w) {
      nw = j.resize_short;
      nh = static_cast<int>(
          static_cast<int64_t>(j.resize_short) * h / w);
    } else {
      nh = j.resize_short;
      nw = static_cast<int>(
          static_cast<int64_t>(j.resize_short) * w / h);
    }
    scratch2->resize(static_cast<size_t>(nw) * nh * 3);
    resize_bilinear(scratch->data(), w, h, scratch2->data(), nw, nh);
    scratch->swap(*scratch2);
    w = nw;
    h = nh;
  }

  // crop (random or center) at scale_down size, then resize to target
  int cw = j.out_w, ch = j.out_h;
  scale_down(w, h, &cw, &ch);
  uint64_t r = splitmix(j.seed ^ (0x85ebca6bULL * (i + 1)));
  int x0, y0;
  if (j.rand_crop) {
    x0 = static_cast<int>(r % (w - cw + 1));
    r = splitmix(r);
    y0 = static_cast<int>(r % (h - ch + 1));
    r = splitmix(r);
  } else {
    x0 = (w - cw) / 2;
    y0 = (h - ch) / 2;
  }
  const bool mirror = j.rand_mirror && (splitmix(r) & 1);

  const uint8_t* crop_src = scratch->data();
  std::vector<uint8_t>& cropped = *scratch2;
  const uint8_t* final_px;
  int fw = j.out_w, fh = j.out_h;
  if (cw == j.out_w && ch == j.out_h) {
    // in-place window, no resize needed
    final_px = nullptr;  // sampled with stride below
  } else {
    // gather the crop contiguously, then resize up to target
    static thread_local std::vector<uint8_t> gather;
    gather.resize(static_cast<size_t>(cw) * ch * 3);
    for (int y = 0; y < ch; ++y)
      std::memcpy(gather.data() + static_cast<size_t>(y) * cw * 3,
                  crop_src + ((static_cast<size_t>(y0) + y) * w + x0) * 3,
                  static_cast<size_t>(cw) * 3);
    cropped.resize(static_cast<size_t>(fw) * fh * 3);
    resize_bilinear(gather.data(), cw, ch, cropped.data(), fw, fh);
    final_px = cropped.data();
  }

  // normalize + mirror + CHW float32 write
  const float m0 = j.mean ? j.mean[0] : 0.f,
              m1 = j.mean ? j.mean[1] : 0.f,
              m2 = j.mean ? j.mean[2] : 0.f;
  const float s0 = j.stdv ? 1.f / j.stdv[0] : 1.f,
              s1 = j.stdv ? 1.f / j.stdv[1] : 1.f,
              s2 = j.stdv ? 1.f / j.stdv[2] : 1.f;
  float* dst = j.out
                   ? j.out + static_cast<size_t>(i) * 3 * fh * fw
                   : nullptr;
  uint8_t* dst8 = j.out_u8
                      ? j.out_u8 + static_cast<size_t>(i) * 3 * fh * fw
                      : nullptr;
  const size_t plane = static_cast<size_t>(fh) * fw;
  // ONE copy of the mirrored-crop source addressing, shared by the
  // plain and color-augmented paths
  const auto src_px = [&](int y, int x) -> const uint8_t* {
    const int sx = mirror ? fw - 1 - x : x;
    return final_px
               ? final_px + (static_cast<size_t>(y) * fw + sx) * 3
               : crop_src +
                     ((static_cast<size_t>(y0) + y) * w + x0 + sx) * 3;
  };
  // ONE copy of the normalize + CHW/NHWC write, over any float3
  // getter; uint8 mode writes raw pixels (mean/std forbidden by the
  // python wrapper)
  const auto write_norm = [&](auto get3) {
    for (int y = 0; y < fh; ++y)
      for (int x = 0; x < fw; ++x) {
        float f0, f1, f2;
        get3(y, x, &f0, &f1, &f2);
        const size_t o = static_cast<size_t>(y) * fw + x;
        if (dst8) {
          const auto q = [](float v) -> uint8_t {
            v = v < 0.f ? 0.f : (v > 255.f ? 255.f : v);
            return static_cast<uint8_t>(v + 0.5f);
          };
          if (j.chw) {
            dst8[o] = q(f0);
            dst8[plane + o] = q(f1);
            dst8[2 * plane + o] = q(f2);
          } else {
            dst8[3 * o] = q(f0);
            dst8[3 * o + 1] = q(f1);
            dst8[3 * o + 2] = q(f2);
          }
          continue;
        }
        if (j.chw) {
          dst[o] = (f0 - m0) * s0;
          dst[plane + o] = (f1 - m1) * s1;
          dst[2 * plane + o] = (f2 - m2) * s2;
        } else {
          dst[3 * o] = (f0 - m0) * s0;
          dst[3 * o + 1] = (f1 - m1) * s1;
          dst[3 * o + 2] = (f2 - m2) * s2;
        }
      }
  };
  const bool coloraug = j.brightness > 0.f || j.contrast > 0.f ||
                        j.saturation > 0.f || j.pca_noise > 0.f;
  if (coloraug) {
    // python augmenter order (CreateAugmenter): crop -> mirror ->
    // color jitter (random order) -> PCA lighting -> normalize; the
    // color passes need float pixels, so gather the mirrored crop
    // into a per-thread float buffer first
    static thread_local std::vector<float> fbuf;
    fbuf.resize(static_cast<size_t>(fh) * fw * 3);
    for (int y = 0; y < fh; ++y)
      for (int x = 0; x < fw; ++x) {
        const uint8_t* p = src_px(y, x);
        float* f = fbuf.data() + (static_cast<size_t>(y) * fw + x) * 3;
        f[0] = p[0];
        f[1] = p[1];
        f[2] = p[2];
      }
    // salt so the chain decorrelates from the mirror draw (which
    // consumed splitmix(r) without advancing r)
    color_augment(j, fbuf.data(), fh * fw,
                  splitmix(r ^ 0xa5a5a5a5a5a5a5a5ULL));
    write_norm([&](int y, int x, float* f0, float* f1, float* f2) {
      const float* f =
          fbuf.data() + (static_cast<size_t>(y) * fw + x) * 3;
      *f0 = f[0];
      *f1 = f[1];
      *f2 = f[2];
    });
  } else {
    write_norm([&](int y, int x, float* f0, float* f1, float* f2) {
      const uint8_t* p = src_px(y, x);
      *f0 = p[0];
      *f1 = p[1];
      *f2 = p[2];
    });
  }
  j.ok[i] = 1;
}

// ---------------------------------------------------------------- pool
struct Pool {
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  Job job;                    // written under mu before notify
  std::atomic<int> next{0};   // image claim counter
  int finished = 0;           // workers done with this generation
  uint64_t generation = 0;
  bool stop = false;

  explicit Pool(int nthreads) {
    for (int t = 0; t < nthreads; ++t)
      workers.emplace_back([this] { worker(); });
  }

  void worker() {
    std::vector<uint8_t> scratch, scratch2;
    uint64_t seen = 0;
    for (;;) {
      Job local;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk,
                     [&] { return stop || generation != seen; });
        if (stop) return;
        seen = generation;
        local = job;  // private copy: no unsynchronized reads later
      }
      for (;;) {
        const int i = next.fetch_add(1);
        if (i >= local.n) break;
        process_one(local, i, &scratch, &scratch2);
      }
      {
        // run() returns only after EVERY worker has left its claim
        // loop, so a straggler can never race the next batch's
        // job/next reset (each generation is a full barrier)
        std::lock_guard<std::mutex> lk(mu);
        if (++finished == static_cast<int>(workers.size()))
          cv_done.notify_all();
      }
    }
  }

  void run(const Job& j) {
    if (workers.empty() || j.n == 1) {
      // inline on the caller: no handoff latency for tiny batches
      std::vector<uint8_t> s1, s2;
      for (int i = 0; i < j.n; ++i) process_one(j, i, &s1, &s2);
      return;
    }
    std::unique_lock<std::mutex> lk(mu);
    job = j;
    next.store(0);
    finished = 0;
    ++generation;
    cv_work.notify_all();
    cv_done.wait(lk, [&] {
      return finished == static_cast<int>(workers.size());
    });
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
      cv_work.notify_all();
    }
    for (auto& t : workers) t.join();
  }
};

// shared Job fill for the three batch entries (exists exactly once)
void run_job(void* h, const uint8_t* blob, const int64_t* offs,
             const int64_t* lens, int n, int out_h, int out_w,
             int resize_short, int rand_crop, int rand_mirror, int chw,
             uint64_t seed, const float* mean, const float* stdv,
             float brightness, float contrast, float saturation,
             float pca_noise, float* out_f, uint8_t* out_u8,
             uint8_t* ok) {
  Job j;
  j.blob = blob;
  j.offs = offs;
  j.lens = lens;
  j.n = n;
  j.out_h = out_h;
  j.out_w = out_w;
  j.resize_short = resize_short;
  j.rand_crop = rand_crop;
  j.rand_mirror = rand_mirror;
  j.chw = chw;
  j.seed = seed;
  j.mean = mean;
  j.stdv = stdv;
  j.brightness = brightness;
  j.contrast = contrast;
  j.saturation = saturation;
  j.pca_noise = pca_noise;
  j.out = out_f;
  j.out_u8 = out_u8;
  j.ok = ok;
  static_cast<Pool*>(h)->run(j);
}

}  // namespace

extern "C" {

void* imgdec_create(int nthreads) {
  return new Pool(nthreads > 0 ? nthreads : 0);
}

void imgdec_destroy(void* h) { delete static_cast<Pool*>(h); }

// Full-recipe float32 entry: decode + geometry augs + color jitter +
// PCA lighting + normalize (the reference's standard ImageNet recipe,
// image_aug_default.cc / python CreateAugmenter). ok[i]=1 per decoded
// image (0 => caller falls back).
void imgdec_batch_aug(void* h, const uint8_t* blob,
                      const int64_t* offs, const int64_t* lens, int n,
                      int out_h, int out_w, int resize_short,
                      int rand_crop, int rand_mirror, int chw,
                      uint64_t seed, const float* mean,
                      const float* stdv, float brightness,
                      float contrast, float saturation,
                      float pca_noise, float* out, uint8_t* ok) {
  run_job(h, blob, offs, lens, n, out_h, out_w, resize_short,
          rand_crop, rand_mirror, chw, seed, mean, stdv, brightness,
          contrast, saturation, pca_noise, out, nullptr, ok);
}

// uint8 entry: raw pixels after decode + geometry/color augs (the
// reference ImageRecordIter2 uint8 registration,
// iter_image_recordio_2.cc:579): no normalize, 1/4 the host->device
// bytes — normalization runs on device.
void imgdec_batch_u8(void* h, const uint8_t* blob,
                     const int64_t* offs, const int64_t* lens, int n,
                     int out_h, int out_w, int resize_short,
                     int rand_crop, int rand_mirror, int chw,
                     uint64_t seed, float brightness, float contrast,
                     float saturation, float pca_noise,
                     unsigned char* out, uint8_t* ok) {
  run_job(h, blob, offs, lens, n, out_h, out_w, resize_short,
          rand_crop, rand_mirror, chw, seed, nullptr, nullptr,
          brightness, contrast, saturation, pca_noise, nullptr, out,
          ok);
}

// Plain float32 entry (no color augs).
void imgdec_batch(void* h, const uint8_t* blob, const int64_t* offs,
                  const int64_t* lens, int n, int out_h, int out_w,
                  int resize_short, int rand_crop, int rand_mirror,
                  int chw, uint64_t seed, const float* mean,
                  const float* stdv, float* out, uint8_t* ok) {
  run_job(h, blob, offs, lens, n, out_h, out_w, resize_short,
          rand_crop, rand_mirror, chw, seed, mean, stdv, 0.f, 0.f,
          0.f, 0.f, out, nullptr, ok);
}

}  // extern "C"

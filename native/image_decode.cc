// Threaded JPEG decode + augment + batch-layout worker pool.
//
// TPU-native analog of the reference's fused OMP parser
// (src/io/iter_image_recordio_2.cc ImageRecordIOParser2): one native
// call turns a batch of JPEG blobs into the final training tensor —
// decode (libjpeg, DCT-scaled to the smallest sufficient size),
// resize-shorter-side, random/center crop (scale_down semantics),
// horizontal mirror, mean/std normalize, CHW float32 write — with a
// persistent pthread pool so no per-batch thread spawn and no Python
// in the per-image loop.
//
// Plain C ABI consumed via ctypes (mxnet_tpu/native.py); pybind11 is
// deliberately not used (not in the image).

#include <cstdio>  // jpeglib.h needs FILE declared first

#include <jpeglib.h>

#include <atomic>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- jpeg
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* e = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(e->jb, 1);
}

// Decode a JPEG blob to RGB. Picks libjpeg's M/8 DCT scaling so the
// decoded image is the smallest one still >= min_side on its shorter
// edge (the cheap first resize the reference gets from
// cv::IMREAD_REDUCED). Returns false on any decode error.
bool decode_jpeg(const uint8_t* buf, size_t len, int min_side,
                 std::vector<uint8_t>* out, int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(buf),
               static_cast<unsigned long>(len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  cinfo.out_color_space = JCS_RGB;
  // choose num/8 scaling: smallest output whose shorter side >= min_side
  if (min_side > 0) {
    const int shorter = cinfo.image_width < cinfo.image_height
                            ? cinfo.image_width
                            : cinfo.image_height;
    int num = 8;
    while (num > 1 && shorter * (num - 1) / 8 >= min_side) --num;
    cinfo.scale_num = num;
    cinfo.scale_denom = 8;
  }
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  if (cinfo.output_components != 3) {
    // grayscale/CMYK: decode then expand below via libjpeg's own
    // conversion was requested (JCS_RGB), so components==3 normally;
    // anything else is unsupported here
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  out->resize(static_cast<size_t>(*w) * *h * 3);
  const int stride = *w * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out->data() +
                   static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ------------------------------------------------------------- resize
// Bilinear RGB resize (uint8), matching PIL/cv2 half-pixel sampling.
void resize_bilinear(const uint8_t* src, int sw, int sh, uint8_t* dst,
                     int dw, int dh) {
  const float sx = static_cast<float>(sw) / dw;
  const float sy = static_cast<float>(sh) / dh;
  for (int y = 0; y < dh; ++y) {
    float fy = (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    int y0 = static_cast<int>(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
    const float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      float fx = (x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      int x0 = static_cast<int>(fx);
      int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
      const float wx = fx - x0;
      const uint8_t* p00 = src + (static_cast<size_t>(y0) * sw + x0) * 3;
      const uint8_t* p01 = src + (static_cast<size_t>(y0) * sw + x1) * 3;
      const uint8_t* p10 = src + (static_cast<size_t>(y1) * sw + x0) * 3;
      const uint8_t* p11 = src + (static_cast<size_t>(y1) * sw + x1) * 3;
      uint8_t* d = dst + (static_cast<size_t>(y) * dw + x) * 3;
      for (int c = 0; c < 3; ++c) {
        const float top = p00[c] + (p01[c] - p00[c]) * wx;
        const float bot = p10[c] + (p11[c] - p10[c]) * wx;
        d[c] = static_cast<uint8_t>(top + (bot - top) * wy + 0.5f);
      }
    }
  }
}

// ---------------------------------------------------------------- rng
// splitmix64: deterministic per (seed, image index) — reproducible
// augmentation independent of thread scheduling.
uint64_t splitmix(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Job {
  const uint8_t* blob = nullptr;
  const int64_t* offs = nullptr;
  const int64_t* lens = nullptr;
  int n = 0;
  int out_h = 0, out_w = 0;
  int resize_short = 0;
  int rand_crop = 0;
  int rand_mirror = 0;
  int chw = 1;                  // 1 = (3,H,W) planes, 0 = (H,W,3)
  uint64_t seed = 0;
  const float* mean = nullptr;  // len 3 or null
  const float* stdv = nullptr;  // len 3 or null
  float* out = nullptr;         // (n, 3, out_h, out_w) or (n,H,W,3)
  uint8_t* ok = nullptr;        // per-image success
};

void scale_down(int sw, int sh, int* cw, int* ch) {
  // reference image.py:33 — shrink the crop to fit the source while
  // keeping the requested aspect
  float w = static_cast<float>(*cw), h = static_cast<float>(*ch);
  if (sh < h) {
    w = w * sh / h;
    h = static_cast<float>(sh);
  }
  if (sw < w) {
    h = h * sw / w;
    w = static_cast<float>(sw);
  }
  *cw = static_cast<int>(w);
  *ch = static_cast<int>(h);
}

void process_one(const Job& j, int i, std::vector<uint8_t>* scratch,
                 std::vector<uint8_t>* scratch2) {
  j.ok[i] = 0;
  const uint8_t* buf = j.blob + j.offs[i];
  const size_t len = static_cast<size_t>(j.lens[i]);
  // DCT-scaled decode is only geometry-preserving when a shorter-side
  // resize follows (it approximates that resize's first octaves); a
  // bare crop must see the full-resolution image, like the python path
  const int min_side = j.resize_short > 0 ? j.resize_short : 0;
  int w = 0, h = 0;
  if (!decode_jpeg(buf, len, min_side, scratch, &w, &h)) return;

  // resize shorter side
  if (j.resize_short > 0 && (w < h ? w : h) != j.resize_short) {
    int nw, nh;
    if (h > w) {
      nw = j.resize_short;
      nh = static_cast<int>(
          static_cast<int64_t>(j.resize_short) * h / w);
    } else {
      nh = j.resize_short;
      nw = static_cast<int>(
          static_cast<int64_t>(j.resize_short) * w / h);
    }
    scratch2->resize(static_cast<size_t>(nw) * nh * 3);
    resize_bilinear(scratch->data(), w, h, scratch2->data(), nw, nh);
    scratch->swap(*scratch2);
    w = nw;
    h = nh;
  }

  // crop (random or center) at scale_down size, then resize to target
  int cw = j.out_w, ch = j.out_h;
  scale_down(w, h, &cw, &ch);
  uint64_t r = splitmix(j.seed ^ (0x85ebca6bULL * (i + 1)));
  int x0, y0;
  if (j.rand_crop) {
    x0 = static_cast<int>(r % (w - cw + 1));
    r = splitmix(r);
    y0 = static_cast<int>(r % (h - ch + 1));
    r = splitmix(r);
  } else {
    x0 = (w - cw) / 2;
    y0 = (h - ch) / 2;
  }
  const bool mirror = j.rand_mirror && (splitmix(r) & 1);

  const uint8_t* crop_src = scratch->data();
  std::vector<uint8_t>& cropped = *scratch2;
  const uint8_t* final_px;
  int fw = j.out_w, fh = j.out_h;
  if (cw == j.out_w && ch == j.out_h) {
    // in-place window, no resize needed
    final_px = nullptr;  // sampled with stride below
  } else {
    // gather the crop contiguously, then resize up to target
    static thread_local std::vector<uint8_t> gather;
    gather.resize(static_cast<size_t>(cw) * ch * 3);
    for (int y = 0; y < ch; ++y)
      std::memcpy(gather.data() + static_cast<size_t>(y) * cw * 3,
                  crop_src + ((static_cast<size_t>(y0) + y) * w + x0) * 3,
                  static_cast<size_t>(cw) * 3);
    cropped.resize(static_cast<size_t>(fw) * fh * 3);
    resize_bilinear(gather.data(), cw, ch, cropped.data(), fw, fh);
    final_px = cropped.data();
  }

  // normalize + mirror + CHW float32 write
  const float m0 = j.mean ? j.mean[0] : 0.f,
              m1 = j.mean ? j.mean[1] : 0.f,
              m2 = j.mean ? j.mean[2] : 0.f;
  const float s0 = j.stdv ? 1.f / j.stdv[0] : 1.f,
              s1 = j.stdv ? 1.f / j.stdv[1] : 1.f,
              s2 = j.stdv ? 1.f / j.stdv[2] : 1.f;
  float* dst = j.out + static_cast<size_t>(i) * 3 * fh * fw;
  const size_t plane = static_cast<size_t>(fh) * fw;
  for (int y = 0; y < fh; ++y) {
    for (int x = 0; x < fw; ++x) {
      const int sx = mirror ? fw - 1 - x : x;
      const uint8_t* p =
          final_px
              ? final_px + (static_cast<size_t>(y) * fw + sx) * 3
              : crop_src +
                    ((static_cast<size_t>(y0) + y) * w + x0 + sx) * 3;
      const size_t o = static_cast<size_t>(y) * fw + x;
      if (j.chw) {
        dst[o] = (p[0] - m0) * s0;
        dst[plane + o] = (p[1] - m1) * s1;
        dst[2 * plane + o] = (p[2] - m2) * s2;
      } else {
        dst[3 * o] = (p[0] - m0) * s0;
        dst[3 * o + 1] = (p[1] - m1) * s1;
        dst[3 * o + 2] = (p[2] - m2) * s2;
      }
    }
  }
  j.ok[i] = 1;
}

// ---------------------------------------------------------------- pool
struct Pool {
  std::vector<std::thread> workers;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  Job job;                    // written under mu before notify
  std::atomic<int> next{0};   // image claim counter
  int finished = 0;           // workers done with this generation
  uint64_t generation = 0;
  bool stop = false;

  explicit Pool(int nthreads) {
    for (int t = 0; t < nthreads; ++t)
      workers.emplace_back([this] { worker(); });
  }

  void worker() {
    std::vector<uint8_t> scratch, scratch2;
    uint64_t seen = 0;
    for (;;) {
      Job local;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk,
                     [&] { return stop || generation != seen; });
        if (stop) return;
        seen = generation;
        local = job;  // private copy: no unsynchronized reads later
      }
      for (;;) {
        const int i = next.fetch_add(1);
        if (i >= local.n) break;
        process_one(local, i, &scratch, &scratch2);
      }
      {
        // run() returns only after EVERY worker has left its claim
        // loop, so a straggler can never race the next batch's
        // job/next reset (each generation is a full barrier)
        std::lock_guard<std::mutex> lk(mu);
        if (++finished == static_cast<int>(workers.size()))
          cv_done.notify_all();
      }
    }
  }

  void run(const Job& j) {
    if (workers.empty() || j.n == 1) {
      // inline on the caller: no handoff latency for tiny batches
      std::vector<uint8_t> s1, s2;
      for (int i = 0; i < j.n; ++i) process_one(j, i, &s1, &s2);
      return;
    }
    std::unique_lock<std::mutex> lk(mu);
    job = j;
    next.store(0);
    finished = 0;
    ++generation;
    cv_work.notify_all();
    cv_done.wait(lk, [&] {
      return finished == static_cast<int>(workers.size());
    });
  }

  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
      cv_work.notify_all();
    }
    for (auto& t : workers) t.join();
  }
};

}  // namespace

extern "C" {

void* imgdec_create(int nthreads) {
  return new Pool(nthreads > 0 ? nthreads : 0);
}

void imgdec_destroy(void* h) { delete static_cast<Pool*>(h); }

// Decode+augment a batch of JPEG blobs into (n,3,out_h,out_w) float32.
// ok[i]=1 per successfully decoded image (0 => caller falls back).
void imgdec_batch(void* h, const uint8_t* blob, const int64_t* offs,
                  const int64_t* lens, int n, int out_h, int out_w,
                  int resize_short, int rand_crop, int rand_mirror,
                  int chw, uint64_t seed, const float* mean,
                  const float* stdv, float* out, uint8_t* ok) {
  Job j;
  j.blob = blob;
  j.offs = offs;
  j.lens = lens;
  j.n = n;
  j.out_h = out_h;
  j.out_w = out_w;
  j.resize_short = resize_short;
  j.rand_crop = rand_crop;
  j.rand_mirror = rand_mirror;
  j.chw = chw;
  j.seed = seed;
  j.mean = mean;
  j.stdv = stdv;
  j.out = out;
  j.ok = ok;
  static_cast<Pool*>(h)->run(j);
}

}  // extern "C"

// Embeddable C API — NDArray / imperative-invoke / Symbol / Executor.
//
// Capability parity with the reference's core C ABI
// (include/mxnet/c_api.h: MXNDArray*, MXImperativeInvoke, MXSymbol*,
// MXExecutor*, with per-thread MXGetLastError via
// src/c_api/c_api_error.cc). Same embedding architecture as
// capi_predict.cc: the compute path is XLA-via-jax in Python, so this
// library hosts a CPython interpreter and marshals flat C calls into
// mxnet_tpu.capi (the support shim); PyObject* doubles as the C handle
// for NDArray / Symbol / Executor objects.
//
// Conventions:
//   - every function returns 0 on success, -1 on failure;
//     MXTpuGetLastError() returns the calling thread's last message.
//   - "list out" results (names, handles) live in thread-local storage
//     owned by the library and are valid until the thread's next call.
//
// Build (see mxnet_tpu/native.py build_core_lib):
//   g++ -O2 -std=c++17 -shared -fPIC capi_core.cc \
//       $(python3-config --includes --ldflags --embed) -o libmxtpu_c.so

#include <Python.h>

#include <cstdarg>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {
// callback ABI (mirrors reference MXKVStoreUpdater / monitor callback,
// include/mxnet/c_api.h:1264, :1084): handles are BORROWED PyObject*
// NDArrays, valid only for the duration of the call.
typedef void (*MXTpuKVUpdater)(int key, void* recv, void* local,
                               void* payload);
typedef void (*MXTpuMonitorCallback)(const char* name, void* arr,
                                     void* payload);
}

namespace {

std::once_flag g_init_once;
thread_local std::string tls_err;
thread_local std::vector<std::string> tls_strs;
thread_local std::vector<const char*> tls_strps;
thread_local std::vector<void*> tls_handles;
thread_local std::vector<int> tls_shape_data;

void EnsurePython() {
  std::call_once(g_init_once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();
    }
  });
}

// Build a Python str from a C string; never fails (non-UTF-8 byte
// sequences — legal in e.g. filenames — fall back to Latin-1 so the
// call surfaces a Python-level error instead of a NULL element crash).
PyObject* Str(const char* s) {
  PyObject* o = PyUnicode_FromString(s);
  if (o == nullptr) {
    PyErr_Clear();
    o = PyUnicode_DecodeLatin1(s, static_cast<Py_ssize_t>(strlen(s)),
                               nullptr);
  }
  return o;
}

void SetError(const char* where) {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  tls_err = where;
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      // PyUnicode_AsUTF8 returns nullptr (with an exception pending)
      // for non-UTF8-encodable text; appending nullptr would be UB
      const char* u = PyUnicode_AsUTF8(s);
      if (u != nullptr) {
        tls_err += ": ";
        tls_err += u;
      } else {
        PyErr_Clear();
      }
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// Call mxnet_tpu.capi.<fn>(args...) with a pre-built argument tuple.
// Returns a NEW reference or nullptr (error recorded).
PyObject* CallShim(const char* fn, PyObject* args) {
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.capi");
  if (mod == nullptr) {
    SetError("import mxnet_tpu.capi");
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* f = PyObject_GetAttrString(mod, fn);
  Py_DECREF(mod);
  if (f == nullptr) {
    SetError(fn);
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* r = args ? PyObject_CallObject(f, args)
                     : PyObject_CallObject(f, nullptr);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (r == nullptr) SetError(fn);
  return r;
}

PyObject* IntList(const int* data, int n) {
  PyObject* lst = PyList_New(n);
  for (int i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, PyLong_FromLong(data[i]));
  return lst;
}

PyObject* FloatList(const float* data, long n) {
  PyObject* lst = PyList_New(n);
  for (long i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, PyFloat_FromDouble(data[i]));
  return lst;
}

PyObject* StrList(const char** data, int n) {
  PyObject* lst = PyList_New(n);
  for (int i = 0; i < n; ++i)
    PyList_SET_ITEM(lst, i, Str(data[i]));
  return lst;
}

PyObject* HandleList(void** data, int n) {
  PyObject* lst = PyList_New(n);
  for (int i = 0; i < n; ++i) {
    PyObject* o = static_cast<PyObject*>(data[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(lst, i, o);
  }
  return lst;
}

// {keys[i]: vals[i]} with string values (the shim's op param coercion
// maps accept strings, matching the reference's all-strings C params)
PyObject* StrDict(int n, const char** keys, const char** vals) {
  PyObject* d = PyDict_New();
  for (int i = 0; i < n; ++i) {
    PyObject* v = Str(vals[i]);
    PyDict_SetItemString(d, keys[i], v);
    Py_DECREF(v);
  }
  return d;
}

// Shape spec packing used across the ABI: entity i's dims live in
// shape_data[shape_ind[i] .. shape_ind[i+1])
PyObject* ShapeLists(int num, const int* shape_ind,
                     const int* shape_data) {
  PyObject* out = PyList_New(num);
  for (int i = 0; i < num; ++i) {
    int lo = shape_ind[i], hi = shape_ind[i + 1];
    PyObject* s = PyList_New(hi - lo);
    for (int j = lo; j < hi; ++j)
      PyList_SET_ITEM(s, j - lo, PyLong_FromLong(shape_data[j]));
    PyList_SET_ITEM(out, i, s);
  }
  return out;
}

// Store a python list of strings into TLS; returns (count, ptr array).
int StashStrList(PyObject* lst, int* num, const char*** out) {
  tls_strs.clear();
  tls_strps.clear();
  Py_ssize_t n = PyList_Size(lst);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GET_ITEM(lst, i));
    tls_strs.emplace_back(s ? s : "");
  }
  for (auto& s : tls_strs) tls_strps.push_back(s.c_str());
  *num = static_cast<int>(n);
  *out = tls_strps.data();
  return 0;
}

// Store a python list of objects as NEW-reference handles in TLS.
int StashHandleList(PyObject* lst, int* num, void*** out) {
  tls_handles.clear();
  Py_ssize_t n = PyList_Size(lst);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(lst, i);
    Py_INCREF(o);
    tls_handles.push_back(o);
  }
  *num = static_cast<int>(n);
  *out = tls_handles.data();
  return 0;
}

struct Gil {
  PyGILState_STATE st;
  Gil() {
    EnsurePython();
    st = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(st); }
};

}  // namespace

extern "C" {

const char* MXTpuGetLastError() { return tls_err.c_str(); }

int MXTpuHandleFree(void* h) {
  Gil gil;
  Py_XDECREF(static_cast<PyObject*>(h));
  return 0;
}

// ------------------------------------------------------------ NDArray

int MXTpuNDArrayCreate(const int* shape, int ndim, const float* data,
                       void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0, IntList(shape, ndim));
  long size = 1;
  for (int i = 0; i < ndim; ++i) size *= shape[i];
  PyTuple_SET_ITEM(args, 1, FloatList(data, size));
  PyObject* r = CallShim("ndarray_from_data", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

int MXTpuNDArrayZeros(const int* shape, int ndim, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, IntList(shape, ndim));
  PyObject* r = CallShim("ndarray_zeros", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// Writes up to cap dims into shape; returns ndim via out param.
int MXTpuNDArrayGetShape(void* h, int* shape, int cap, int* ndim) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyObject* r = CallShim("ndarray_shape", args);
  if (r == nullptr) return -1;
  Py_ssize_t n = PyList_Size(r);
  *ndim = static_cast<int>(n);
  for (Py_ssize_t i = 0; i < n && i < cap; ++i)
    shape[i] = static_cast<int>(
        PyLong_AsLong(PyList_GET_ITEM(r, i)));
  Py_DECREF(r);
  return 0;
}

// Copies the (row-major) float data out; returns element count, or -1.
// buf may be NULL to query the size.
long MXTpuNDArrayCopyOut(void* h, float* buf, long cap) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyObject* r = CallShim("ndarray_to_list", args);
  if (r == nullptr) return -1;
  Py_ssize_t n = PyList_Size(r);
  if (buf != nullptr) {
    for (Py_ssize_t i = 0; i < n && i < cap; ++i)
      buf[i] = static_cast<float>(
          PyFloat_AsDouble(PyList_GET_ITEM(r, i)));
  }
  Py_DECREF(r);
  return static_cast<long>(n);
}

// Overwrites the array's contents from a row-major float buffer whose
// length must equal the array size (reference MXNDArraySyncCopyFromCPU).
int MXTpuNDArrayCopyIn(void* h, const float* data, long size) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 1, FloatList(data, size));
  PyObject* r = CallShim("ndarray_copy_from", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuNDArraySave(const char* fname, int num, void** handles,
                     const char** keys) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  PyTuple_SET_ITEM(args, 0, Str(fname));
  PyTuple_SET_ITEM(args, 1, HandleList(handles, num));
  PyTuple_SET_ITEM(args, 2,
                   keys ? StrList(keys, num) : PyList_New(0));
  PyObject* r = CallShim("ndarray_save", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Loaded keys via MXTpuLastStrList, handles via out params (TLS).
int MXTpuNDArrayLoad(const char* fname, int* num_out, void*** out,
                     int* num_keys, const char*** keys) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, Str(fname));
  PyObject* r = CallShim("ndarray_load", args);
  if (r == nullptr) return -1;
  PyObject* klist = PyTuple_GET_ITEM(r, 0);
  PyObject* vlist = PyTuple_GET_ITEM(r, 1);
  StashStrList(klist, num_keys, keys);
  StashHandleList(vlist, num_out, out);
  Py_DECREF(r);
  return 0;
}

// helper: call shim fn(path string) and return the NEW handle
static int PathCreate(const char* fn, const char* path, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, Str(path));
  PyObject* r = CallShim(fn, args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// helper: call shim fn(handle) and return the NEW handle it produces
static int HandleUnary(const char* fn, void* h, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyObject* r = CallShim(fn, args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// helper: call shim fn(handle) for side effect only
static int HandleUnaryVoid(const char* fn, void* h) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyObject* r = CallShim(fn, args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuNDArraySlice(void* h, int start, int stop, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(start));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(stop));
  PyObject* r = CallShim("ndarray_slice", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

int MXTpuNDArrayAt(void* h, int idx, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(idx));
  PyObject* r = CallShim("ndarray_at", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

int MXTpuNDArrayReshape(void* h, int ndim, const int* dims, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 1, IntList(dims, ndim));
  PyObject* r = CallShim("ndarray_reshape", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

int MXTpuNDArrayGetDType(void* h, int* dtype) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyObject* r = CallShim("ndarray_dtype", args);
  if (r == nullptr) return -1;
  *dtype = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXTpuNDArrayGetContext(void* h, const char** dev_type, int* dev_id) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyObject* r = CallShim("ndarray_context", args);
  if (r == nullptr) return -1;
  const char* s = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 0));
  tls_strs.clear();
  tls_strs.emplace_back(s ? s : "");
  *dev_type = tls_strs.back().c_str();
  *dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GET_ITEM(r, 1)));
  Py_DECREF(r);
  return 0;
}

int MXTpuNDArrayWaitToRead(void* h) {
  return HandleUnaryVoid("ndarray_wait_to_read", h);
}

int MXTpuNDArrayWaitAll(void) {
  Gil gil;
  PyObject* r = CallShim("ndarray_waitall", nullptr);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

static thread_local std::string tls_bytes;

int MXTpuNDArraySaveRawBytes(void* h, const char** buf, long* size) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyObject* r = CallShim("ndarray_save_raw", args);
  if (r == nullptr) return -1;
  char* data = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &data, &n) != 0) {
    SetError("ndarray_save_raw");
    Py_DECREF(r);
    return -1;
  }
  tls_bytes.assign(data, static_cast<size_t>(n));
  *buf = tls_bytes.data();
  *size = static_cast<long>(n);
  Py_DECREF(r);
  return 0;
}

int MXTpuNDArrayLoadFromRawBytes(const void* buf, long size, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, PyBytes_FromStringAndSize(
      static_cast<const char*>(buf), size));
  PyObject* r = CallShim("ndarray_load_raw", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// -------------------------------------------------- imperative invoke

// New-output form: results become TLS handles (valid until this
// thread's next call).
int MXTpuImperativeInvoke(const char* op, int num_in, void** inputs,
                          int num_params, const char** keys,
                          const char** vals, int* num_out,
                          void*** outputs) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  PyTuple_SET_ITEM(args, 0, Str(op));
  PyTuple_SET_ITEM(args, 1, HandleList(inputs, num_in));
  PyTuple_SET_ITEM(args, 2, StrDict(num_params, keys, vals));
  PyObject* r = CallShim("invoke", args);
  if (r == nullptr) return -1;
  StashHandleList(r, num_out, outputs);
  Py_DECREF(r);
  return 0;
}

// In-place form: writes results into the given existing NDArrays (the
// reference's out-array convention — how fused optimizer updates
// mutate executor weights from C).
int MXTpuImperativeInvokeInto(const char* op, int num_in,
                              void** inputs, int num_params,
                              const char** keys, const char** vals,
                              int num_out, void** outputs) {
  Gil gil;
  PyObject* args = PyTuple_New(4);
  PyTuple_SET_ITEM(args, 0, Str(op));
  PyTuple_SET_ITEM(args, 1, HandleList(inputs, num_in));
  PyTuple_SET_ITEM(args, 2, StrDict(num_params, keys, vals));
  PyTuple_SET_ITEM(args, 3, HandleList(outputs, num_out));
  PyObject* r = CallShim("invoke_into", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// ------------------------------------------------------------- Symbol

int MXTpuSymbolCreateVariable(const char* name, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, Str(name));
  PyObject* r = CallShim("symbol_variable", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// Atomic-symbol creation + composition in one call: input_keys name
// the op's symbol inputs (e.g. "data", "weight"), params are the op's
// string-typed attributes.
int MXTpuSymbolCreate(const char* op, int num_params,
                      const char** param_keys, const char** param_vals,
                      const char* name, int num_in,
                      const char** input_keys, void** input_syms,
                      void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(5);
  PyTuple_SET_ITEM(args, 0, Str(op));
  PyTuple_SET_ITEM(args, 1,
                   StrDict(num_params, param_keys, param_vals));
  PyTuple_SET_ITEM(args, 2, Str(name ? name : ""));
  PyTuple_SET_ITEM(args, 3, StrList(input_keys, num_in));
  PyTuple_SET_ITEM(args, 4, HandleList(input_syms, num_in));
  PyObject* r = CallShim("symbol_create", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

int MXTpuSymbolFromJSON(const char* json, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, Str(json));
  PyObject* r = CallShim("symbol_from_json", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// JSON into TLS string; pointer valid until this thread's next call.
int MXTpuSymbolToJSON(void* sym, const char** out_json) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyObject* r = CallShim("symbol_to_json", args);
  if (r == nullptr) return -1;
  tls_strs.clear();
  tls_strs.emplace_back(PyUnicode_AsUTF8(r));
  *out_json = tls_strs.back().c_str();
  Py_DECREF(r);
  return 0;
}

int MXTpuSymbolCreateFromFile(const char* fname, void** out) {
  return PathCreate("symbol_from_file", fname, out);
}

int MXTpuSymbolSaveToFile(void* sym, const char* fname) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 1, Str(fname));
  PyObject* r = CallShim("symbol_save_to_file", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// kind: "arg" | "out" | "aux"
int MXTpuSymbolList(void* sym, const char* kind, int* num,
                    const char*** out) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 1, Str(kind));
  PyObject* r = CallShim("symbol_list", args);
  if (r == nullptr) return -1;
  StashStrList(r, num, out);
  Py_DECREF(r);
  return 0;
}

// shared core of InferShape / InferShapePartial: call the shim and
// pack the arg-shape lists into TLS (shape_ind has num+1 entries).
static int InferShapeVia(const char* shim_fn, void* sym, int num_in,
                         const char** names, const int* shape_ind,
                         const int* shape_data, int* num_arg,
                         const int** arg_ind, const int** arg_data) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 1, StrList(names, num_in));
  PyTuple_SET_ITEM(args, 2,
                   ShapeLists(num_in, shape_ind, shape_data));
  PyObject* r = CallShim(shim_fn, args);
  if (r == nullptr) return -1;
  PyObject* arg_shapes = PyTuple_GET_ITEM(r, 0);
  tls_shape_data.clear();
  static thread_local std::vector<int> ind;
  ind.clear();
  ind.push_back(0);
  Py_ssize_t n = PyList_Size(arg_shapes);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* s = PyList_GET_ITEM(arg_shapes, i);
    for (Py_ssize_t j = 0; j < PyList_Size(s); ++j)
      tls_shape_data.push_back(static_cast<int>(
          PyLong_AsLong(PyList_GET_ITEM(s, j))));
    ind.push_back(static_cast<int>(tls_shape_data.size()));
  }
  *num_arg = static_cast<int>(n);
  *arg_ind = ind.data();
  *arg_data = tls_shape_data.data();
  Py_DECREF(r);
  return 0;
}

// Infers all argument shapes from the named input shapes.
int MXTpuSymbolInferShape(void* sym, int num_in, const char** names,
                          const int* shape_ind, const int* shape_data,
                          int* num_arg, const int** arg_ind,
                          const int** arg_data) {
  return InferShapeVia("symbol_infer_shape", sym, num_in, names,
                       shape_ind, shape_data, num_arg, arg_ind,
                       arg_data);
}

int MXTpuSymbolGetAttr(void* sym, const char* key, const char** out,
                       int* success) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 1, Str(key));
  PyObject* r = CallShim("symbol_get_attr", args);
  if (r == nullptr) return -1;
  if (r == Py_None) {
    *success = 0;
    *out = "";
  } else {
    *success = 1;
    const char* s = PyUnicode_AsUTF8(r);
    tls_strs.clear();
    tls_strs.emplace_back(s ? s : "");
    *out = tls_strs.back().c_str();
  }
  Py_DECREF(r);
  return 0;
}

int MXTpuSymbolSetAttr(void* sym, const char* key, const char* value) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 1, Str(key));
  PyTuple_SET_ITEM(args, 2, Str(value));
  PyObject* r = CallShim("symbol_set_attr", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuSymbolListAttr(void* sym, int* num, const char*** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyObject* r = CallShim("symbol_list_attr", args);
  if (r == nullptr) return -1;
  int n_flat = 0;
  StashStrList(r, &n_flat, out);
  *num = n_flat / 2;  // pair count, reference ListAttr convention
  Py_DECREF(r);
  return 0;
}

int MXTpuSymbolGetInternals(void* sym, void** out) {
  return HandleUnary("symbol_get_internals", sym, out);
}

int MXTpuSymbolGetOutput(void* sym, int index, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(index));
  PyObject* r = CallShim("symbol_get_output", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

int MXTpuSymbolGetChildren(void* sym, void** out) {
  return HandleUnary("symbol_get_children", sym, out);
}

int MXTpuSymbolGetName(void* sym, const char** out, int* success) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyObject* r = CallShim("symbol_get_name", args);
  if (r == nullptr) return -1;
  if (r == Py_None) {
    *success = 0;
    *out = "";
  } else {
    *success = 1;
    const char* s = PyUnicode_AsUTF8(r);
    tls_strs.clear();
    tls_strs.emplace_back(s ? s : "");
    *out = tls_strs.back().c_str();
  }
  Py_DECREF(r);
  return 0;
}

int MXTpuSymbolCopy(void* sym, void** out) {
  return HandleUnary("symbol_copy", sym, out);
}

int MXTpuSymbolInferType(void* sym, int num_in, const char** names,
                         const int* dtypes, int* num_arg,
                         const int** arg_dtypes) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 1, StrList(names, num_in));
  PyTuple_SET_ITEM(args, 2, IntList(dtypes, num_in));
  PyObject* r = CallShim("symbol_infer_type", args);
  if (r == nullptr) return -1;
  PyObject* arg_t = PyTuple_GET_ITEM(r, 0);
  tls_shape_data.clear();
  Py_ssize_t n = PyList_Size(arg_t);
  for (Py_ssize_t i = 0; i < n; ++i)
    tls_shape_data.push_back(static_cast<int>(
        PyLong_AsLong(PyList_GET_ITEM(arg_t, i))));
  *num_arg = static_cast<int>(n);
  *arg_dtypes = tls_shape_data.data();
  Py_DECREF(r);
  return 0;
}

int MXTpuSymbolCreateGroup(int num, void** syms, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, HandleList(syms, num));
  PyObject* r = CallShim("symbol_create_group", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// Same packing as MXTpuSymbolInferShape; unknown shapes come back as
// zero-length entries (reference MXSymbolInferShapePartial).
int MXTpuSymbolInferShapePartial(void* sym, int num_in,
                                 const char** names,
                                 const int* shape_ind,
                                 const int* shape_data, int* num_arg,
                                 const int** arg_ind,
                                 const int** arg_data) {
  return InferShapeVia("symbol_infer_shape_partial", sym, num_in,
                       names, shape_ind, shape_data, num_arg, arg_ind,
                       arg_data);
}

// ---------------------------------------------------------- custom op

typedef void (*MXTpuCustomOpCB)(int num_in, void** ins, int num_out,
                                void** outs, void* payload);

// Register a C-implemented op under `op_type`, then build it like any
// Custom op (imperative "Custom" invoke / Symbol with op_type param) —
// reference MXCustomOpRegister. Callback handles are BORROWED.
int MXTpuCustomOpRegister(const char* op_type, int num_inputs,
                          int num_outputs, MXTpuCustomOpCB forward,
                          MXTpuCustomOpCB backward, void* payload) {
  Gil gil;
  PyObject* args = PyTuple_New(6);
  PyTuple_SET_ITEM(args, 0, Str(op_type));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(num_inputs));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(num_outputs));
  PyTuple_SET_ITEM(args, 3,
                   PyLong_FromVoidPtr(reinterpret_cast<void*>(forward)));
  PyTuple_SET_ITEM(args, 4,
                   PyLong_FromVoidPtr(reinterpret_cast<void*>(backward)));
  PyTuple_SET_ITEM(args, 5, PyLong_FromVoidPtr(payload));
  PyObject* r = CallShim("custom_op_register", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// ---------------------------------------------------------------- rtc

// Pallas-source RTC (the reference MXRtcCreate took CUDA text for
// NVRTC; here the source text defines a Pallas kernel function).
int MXTpuRtcCreate(const char* name, const char* py_source,
                   const char* kernel_fn_name, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  PyTuple_SET_ITEM(args, 0, Str(name));
  PyTuple_SET_ITEM(args, 1, Str(py_source));
  PyTuple_SET_ITEM(args, 2, Str(kernel_fn_name));
  PyObject* r = CallShim("rtc_create", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// Launch on NDArrays; results land in the pre-allocated outs (their
// shapes/dtypes define the kernel's output spec).
int MXTpuRtcPush(void* h, int num_in, void** ins, int num_out,
                 void** outs) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 1, HandleList(ins, num_in));
  PyTuple_SET_ITEM(args, 2, HandleList(outs, num_out));
  PyObject* r = CallShim("rtc_push", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuRtcFree(void* h) { return MXTpuHandleFree(h); }

// -------------------------------------------------------------- op info

int MXTpuListAllOpNames(int* num, const char*** names) {
  Gil gil;
  PyObject* r = CallShim("list_all_op_names", nullptr);
  if (r == nullptr) return -1;
  StashStrList(r, num, names);
  Py_DECREF(r);
  return 0;
}

int MXTpuOpGetInfo(const char* op, const char** description,
                   int* num_args, const char*** arg_names,
                   int* num_params, const char*** param_keys) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, Str(op));
  PyObject* r = CallShim("op_info", args);
  if (r == nullptr) return -1;
  // Pack desc + args + params into ONE TLS string table:
  // [desc, arg0..argN, param0..paramM]
  PyObject* desc = PyTuple_GET_ITEM(r, 0);
  PyObject* arg_l = PyTuple_GET_ITEM(r, 1);
  PyObject* par_l = PyTuple_GET_ITEM(r, 2);
  tls_strs.clear();
  tls_strps.clear();
  const char* d = PyUnicode_AsUTF8(desc);
  tls_strs.emplace_back(d ? d : "");
  Py_ssize_t na = PyList_Size(arg_l), np = PyList_Size(par_l);
  for (Py_ssize_t i = 0; i < na; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GET_ITEM(arg_l, i));
    tls_strs.emplace_back(s ? s : "");
  }
  for (Py_ssize_t i = 0; i < np; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GET_ITEM(par_l, i));
    tls_strs.emplace_back(s ? s : "");
  }
  for (auto& s : tls_strs) tls_strps.push_back(s.c_str());
  *description = tls_strps[0];
  *num_args = static_cast<int>(na);
  *arg_names = tls_strps.data() + 1;
  *num_params = static_cast<int>(np);
  *param_keys = tls_strps.data() + 1 + na;
  Py_DECREF(r);
  return 0;
}

// ------------------------------------------------------------ RecordIO

int MXTpuRecordIOWriterCreate(const char* path, void** out) {
  return PathCreate("recordio_writer_create", path, out);
}

int MXTpuRecordIOReaderCreate(const char* path, void** out) {
  return PathCreate("recordio_reader_create", path, out);
}

int MXTpuRecordIOWriterWriteRecord(void* h, const char* buf, long size) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 1, PyBytes_FromStringAndSize(buf, size));
  PyObject* r = CallShim("recordio_write", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuRecordIOWriterTell(void* h, long* pos) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyObject* r = CallShim("recordio_tell", args);
  if (r == nullptr) return -1;
  *pos = PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int MXTpuRecordIOReaderReadRecord(void* h, const char** buf, long* size) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyObject* r = CallShim("recordio_read", args);
  if (r == nullptr) return -1;
  if (r == Py_None) {
    // end of file: NULL buf (a zero SIZE alone is a legal empty record)
    *buf = nullptr;
    *size = 0;
  } else {
    char* data = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(r, &data, &n) != 0) {
      SetError("recordio_read");
      Py_DECREF(r);
      return -1;
    }
    tls_bytes.assign(data, static_cast<size_t>(n));
    *buf = tls_bytes.data();
    *size = static_cast<long>(n);
  }
  Py_DECREF(r);
  return 0;
}

int MXTpuRecordIOReaderSeek(void* h, long pos) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(h));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(pos));
  PyObject* r = CallShim("recordio_seek", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuRecordIOWriterFree(void* h) {
  // always release the handle, even when the close itself fails
  // (e.g. ENOSPC on the final flush) — the caller still gets -1
  int rc = HandleUnaryVoid("recordio_close", h);
  MXTpuHandleFree(h);
  return rc;
}

int MXTpuRecordIOReaderFree(void* h) {
  return MXTpuRecordIOWriterFree(h);
}

// ------------------------------------------------------------ profiler

int MXTpuSetProfilerConfig(int mode, const char* filename) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0, PyLong_FromLong(mode));
  PyTuple_SET_ITEM(args, 1, Str(filename));
  PyObject* r = CallShim("profiler_set_config", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuSetProfilerState(int state) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, PyLong_FromLong(state));
  PyObject* r = CallShim("profiler_set_state", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuDumpProfile(void) {
  Gil gil;
  PyObject* r = CallShim("profiler_dump", nullptr);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// ------------------------------------------------------------- runtime

int MXTpuRandomSeed(int seed) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, PyLong_FromLong(seed));
  PyObject* r = CallShim("random_seed", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuNotifyShutdown(void) {
  Gil gil;
  PyObject* r = CallShim("notify_shutdown", nullptr);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuInitPSEnv(int num, const char** keys, const char** vals) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0, StrList(keys, num));
  PyTuple_SET_ITEM(args, 1, StrList(vals, num));
  PyObject* r = CallShim("init_ps_env", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

static int RoleIs(const char* role, int* out) {
  Gil gil;
  PyObject* r = CallShim("kvstore_role", nullptr);
  if (r == nullptr) return -1;
  const char* s = PyUnicode_AsUTF8(r);
  *out = (s != nullptr && strcmp(s, role) == 0) ? 1 : 0;
  Py_DECREF(r);
  return 0;
}

int MXTpuKVStoreIsWorkerNode(int* out) { return RoleIs("worker", out); }
int MXTpuKVStoreIsServerNode(int* out) { return RoleIs("server", out); }
int MXTpuKVStoreIsSchedulerNode(int* out) {
  return RoleIs("scheduler", out);
}

// ----------------------------------------------------------- Executor

int MXTpuExecutorSimpleBind(void* sym, const char* ctx_type,
                            int dev_id, const char* grad_req,
                            int num_in, const char** names,
                            const int* shape_ind,
                            const int* shape_data, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(6);
  Py_INCREF(static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(sym));
  PyTuple_SET_ITEM(args, 1, Str(ctx_type));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(dev_id));
  PyTuple_SET_ITEM(args, 3, Str(grad_req));
  PyTuple_SET_ITEM(args, 4, StrList(names, num_in));
  PyTuple_SET_ITEM(args, 5,
                   ShapeLists(num_in, shape_ind, shape_data));
  PyObject* r = CallShim("executor_bind", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

int MXTpuExecutorForward(void* ex, int is_train) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(is_train));
  PyObject* r = CallShim("executor_forward", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuExecutorBackward(void* ex) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(ex));
  PyObject* r = CallShim("executor_backward", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuExecutorOutputs(void* ex, int* num, void*** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(ex));
  PyObject* r = CallShim("executor_outputs", args);
  if (r == nullptr) return -1;
  StashHandleList(r, num, out);
  Py_DECREF(r);
  return 0;
}

// kind: "arg" | "grad" | "aux"; returns a NEW handle to the named
// executor array (shared storage with the executor).
int MXTpuExecutorArray(void* ex, const char* name, const char* kind,
                       void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 1, Str(name));
  PyTuple_SET_ITEM(args, 2, Str(kind));
  PyObject* r = CallShim("executor_arg", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// Install a per-node monitor callback on the executor (reference
// MXExecutorSetMonitorCallback, c_api.h:1084): cb(name, array_handle,
// payload) fires for EVERY node output on monitored forwards. The
// array handle is BORROWED for the duration of the call.
int MXTpuExecutorSetMonitorCallback(void* ex,
                                    MXTpuMonitorCallback cb,
                                    void* payload) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 1,
                   PyLong_FromVoidPtr(reinterpret_cast<void*>(cb)));
  PyTuple_SET_ITEM(args, 2, PyLong_FromVoidPtr(payload));
  PyObject* r = CallShim("executor_set_monitor", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// New executor bound at new shapes, params shared with the original
// (reference MXExecutorReshape).
int MXTpuExecutorReshape(void* ex, int num_in, const char** names,
                         const int* shape_ind, const int* shape_data,
                         void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 1, StrList(names, num_in));
  PyTuple_SET_ITEM(args, 2,
                   ShapeLists(num_in, shape_ind, shape_data));
  PyObject* r = CallShim("executor_reshape", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

int MXTpuExecutorCopyParamsFrom(void* ex, int num, const char** names,
                                void** handles, int allow_extra) {
  Gil gil;
  PyObject* args = PyTuple_New(4);
  Py_INCREF(static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 1, StrList(names, num));
  PyTuple_SET_ITEM(args, 2, HandleList(handles, num));
  PyTuple_SET_ITEM(args, 3, PyLong_FromLong(allow_extra));
  PyObject* r = CallShim("executor_copy_params_from", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Debug description of the bound graph (reference MXExecutorPrint's
// out_str form); TLS string.
int MXTpuExecutorPrint(void* ex, const char** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(ex));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(ex));
  PyObject* r = CallShim("executor_print", args);
  if (r == nullptr) return -1;
  const char* s = PyUnicode_AsUTF8(r);
  tls_strs.clear();
  tls_strs.emplace_back(s ? s : "");
  *out = tls_strs.back().c_str();
  Py_DECREF(r);
  return 0;
}

// ----------------------------------------------------------- DataIter

// Registered iterator names (reference MXListDataIters, c_api.h:1096).
int MXTpuListDataIters(int* num, const char*** names) {
  Gil gil;
  PyObject* r = CallShim("dataiter_list", nullptr);
  if (r == nullptr) return -1;
  StashStrList(r, num, names);
  Py_DECREF(r);
  return 0;
}

// All params are strings, exactly the reference's kwargs convention
// (MXDataIterCreateIter, c_api.h:1108).
int MXTpuDataIterCreate(const char* name, int num_params,
                        const char** keys, const char** vals,
                        void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0, Str(name));
  PyTuple_SET_ITEM(args, 1, StrDict(num_params, keys, vals));
  PyObject* r = CallShim("dataiter_create", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// *out = 1 while a batch is available, 0 at epoch end.
int MXTpuDataIterNext(void* it, int* out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(it));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(it));
  PyObject* r = CallShim("dataiter_next", args);
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXTpuDataIterBeforeFirst(void* it) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(it));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(it));
  PyObject* r = CallShim("dataiter_reset", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

static int DataIterFetch(void* it, const char* what, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(it));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(it));
  PyTuple_SET_ITEM(args, 1, Str(what));
  PyObject* r = CallShim("dataiter_get", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

// NEW NDArray handles for the current batch's data / label.
int MXTpuDataIterGetData(void* it, void** out) {
  return DataIterFetch(it, "data", out);
}

int MXTpuDataIterGetLabel(void* it, void** out) {
  return DataIterFetch(it, "label", out);
}

// Current batch's per-example indices; *num = 0 when untracked
// (reference MXDataIterGetIndex).
int MXTpuDataIterGetIndex(void* it, int* num, const int** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(it));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(it));
  PyObject* r = CallShim("dataiter_index", args);
  if (r == nullptr) return -1;
  tls_shape_data.clear();
  Py_ssize_t n = PyList_Size(r);
  for (Py_ssize_t i = 0; i < n; ++i)
    tls_shape_data.push_back(static_cast<int>(
        PyLong_AsLong(PyList_GET_ITEM(r, i))));
  *num = static_cast<int>(n);
  *out = tls_shape_data.data();
  Py_DECREF(r);
  return 0;
}

// description + param names for a registered iterator (reference
// MXDataIterGetIterInfo).
int MXTpuDataIterGetIterInfo(const char* name,
                             const char** description,
                             int* num_params,
                             const char*** param_names) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, Str(name));
  PyObject* r = CallShim("dataiter_info", args);
  if (r == nullptr) return -1;
  PyObject* desc = PyTuple_GET_ITEM(r, 0);
  PyObject* par = PyTuple_GET_ITEM(r, 1);
  tls_strs.clear();
  tls_strps.clear();
  const char* d = PyUnicode_AsUTF8(desc);
  tls_strs.emplace_back(d ? d : "");
  Py_ssize_t n = PyList_Size(par);
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GET_ITEM(par, i));
    tls_strs.emplace_back(s ? s : "");
  }
  for (auto& s : tls_strs) tls_strps.push_back(s.c_str());
  *description = tls_strps[0];
  *num_params = static_cast<int>(n);
  *param_names = tls_strps.data() + 1;
  Py_DECREF(r);
  return 0;
}

int MXTpuDataIterGetPadNum(void* it, int* pad) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(it));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(it));
  PyObject* r = CallShim("dataiter_pad", args);
  if (r == nullptr) return -1;
  *pad = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

// ------------------------------------------------------------ KVStore

int MXTpuKVStoreCreate(const char* type, void** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, Str(type));
  PyObject* r = CallShim("kvstore_create", args);
  if (r == nullptr) return -1;
  *out = r;
  return 0;
}

static int KVStoreKV(const char* fn, void* kv, int num, const int* keys,
                     void** vals) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 1, IntList(keys, num));
  PyTuple_SET_ITEM(args, 2, HandleList(vals, num));
  PyObject* r = CallShim(fn, args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuKVStoreInit(void* kv, int num, const int* keys, void** vals) {
  return KVStoreKV("kvstore_init", kv, num, keys, vals);
}

int MXTpuKVStorePush(void* kv, int num, const int* keys, void** vals) {
  return KVStoreKV("kvstore_push", kv, num, keys, vals);
}

// Pull writes INTO the given existing NDArrays.
int MXTpuKVStorePull(void* kv, int num, const int* keys, void** outs) {
  return KVStoreKV("kvstore_pull", kv, num, keys, outs);
}

// cb(key, recv_grad_handle, local_weight_handle, payload); handles are
// BORROWED for the duration of the call (reference MXKVStoreUpdater,
// c_api.h:1264-1276).
int MXTpuKVStoreSetUpdater(void* kv, MXTpuKVUpdater cb, void* payload) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 1,
                   PyLong_FromVoidPtr(reinterpret_cast<void*>(cb)));
  PyTuple_SET_ITEM(args, 2, PyLong_FromVoidPtr(payload));
  PyObject* r = CallShim("kvstore_set_updater", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

static int KVStoreIntProp(const char* fn, void* kv, int* out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(kv));
  PyObject* r = CallShim(fn, args);
  if (r == nullptr) return -1;
  *out = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

int MXTpuKVStoreGetRank(void* kv, int* rank) {
  return KVStoreIntProp("kvstore_rank", kv, rank);
}

int MXTpuKVStoreGetGroupSize(void* kv, int* size) {
  return KVStoreIntProp("kvstore_group_size", kv, size);
}

int MXTpuKVStoreGetNumDeadNode(void* kv, int node_id, int timeout,
                               int* dead) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(node_id));
  PyTuple_SET_ITEM(args, 2, PyLong_FromLong(timeout));
  PyObject* r = CallShim("kvstore_num_dead_node", args);
  if (r == nullptr) return -1;
  *dead = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

// Server-side optimizer by name + string params (the reference ships
// a pickled optimizer via MXKVStoreSendCommmandToServers; same info).
int MXTpuKVStoreSetOptimizer(void* kv, const char* opt_name,
                             int num_params, const char** keys,
                             const char** vals) {
  Gil gil;
  PyObject* args = PyTuple_New(3);
  Py_INCREF(static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 1, Str(opt_name));
  PyTuple_SET_ITEM(args, 2, StrDict(num_params, keys, vals));
  PyObject* r = CallShim("kvstore_set_optimizer", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

int MXTpuKVStoreSetBarrierBeforeExit(void* kv, int flag) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  Py_INCREF(static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 1, PyLong_FromLong(flag));
  PyObject* r = CallShim("kvstore_set_barrier_before_exit", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Ensure this process's server role is live (reference
// MXKVStoreRunServer; our dist_async hosts the server inside rank 0's
// process, so this returns immediately elsewhere).
int MXTpuKVStoreRunServer(void* kv) {
  return HandleUnaryVoid("kvstore_run_server", kv);
}

int MXTpuKVStoreBarrier(void* kv) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(kv));
  PyObject* r = CallShim("kvstore_barrier", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// TLS string; valid until this thread's next call.
int MXTpuKVStoreGetType(void* kv, const char** out) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  Py_INCREF(static_cast<PyObject*>(kv));
  PyTuple_SET_ITEM(args, 0, static_cast<PyObject*>(kv));
  PyObject* r = CallShim("kvstore_type", args);
  if (r == nullptr) return -1;
  tls_strs.clear();
  const char* s = PyUnicode_AsUTF8(r);
  tls_strs.emplace_back(s ? s : "");
  *out = tls_strs.back().c_str();
  Py_DECREF(r);
  return 0;
}

// ----------------------------------------------------------- Autograd

// Returns the previous mode via *prev (reference
// MXAutogradSetIsTraining, c_api.h:529).
int MXTpuAutogradSetIsTraining(int is_training, int* prev) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, PyLong_FromLong(is_training));
  PyObject* r = CallShim("autograd_set_training", args);
  if (r == nullptr) return -1;
  *prev = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return 0;
}

// Attach gradient buffers to variables (reference
// MXAutogradMarkVariables, c_api.h:536). Gradients accumulate into
// grad_handles after ComputeGradient.
int MXTpuAutogradMarkVariables(int num, void** var_handles,
                               void** grad_handles) {
  Gil gil;
  PyObject* args = PyTuple_New(2);
  PyTuple_SET_ITEM(args, 0, HandleList(var_handles, num));
  PyTuple_SET_ITEM(args, 1, HandleList(grad_handles, num));
  PyObject* r = CallShim("autograd_mark_variables", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

// Backprop from the given outputs; gradients land in the buffers given
// at MarkVariables (reference MXAutogradComputeGradient, c_api.h:546).
int MXTpuAutogradComputeGradient(int num, void** output_handles) {
  Gil gil;
  PyObject* args = PyTuple_New(1);
  PyTuple_SET_ITEM(args, 0, HandleList(output_handles, num));
  PyObject* r = CallShim("autograd_compute_gradient", args);
  if (r == nullptr) return -1;
  Py_DECREF(r);
  return 0;
}

}  // extern "C"

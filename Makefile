# Build/test entry points (the reference drove everything through
# make; here the Python path needs no compilation, so targets wrap the
# native builds, test tiers, docs generation, and deploy bundle).
#
# The CPU guard (JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=) keeps every
# target off the TPU tunnel; drop it to run something on the chip.

PY      ?= python
CPUENV  := JAX_PLATFORMS=cpu PALLAS_AXON_POOL_IPS=
XLA8    := XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: all test nightly examples lint lint-check libs predict perl \
	docs dryrun cache-check serving-check sync-check data-check \
	passes-check telemetry-check decode-check race-check \
	effects-check fusion-check \
	shard-check profiling-check numerics-check coldstart-check \
	fleet-check quant-check elastic-check bench-diff clean

all: libs test

# full unit suite on the virtual 8-device CPU mesh
test:
	$(CPUENV) $(PY) -m pytest tests/ -q --ignore=tests/nightly

# distributed tier: multi-process workers on one host (CI pattern)
nightly:
	$(CPUENV) $(PY) tools/launch.py -n 2 --launcher local \
	    $(PY) tests/nightly/dist_sync_kvstore.py
	$(CPUENV) $(PY) tools/launch.py -n 2 --launcher local \
	    $(PY) tests/nightly/dist_async_kvstore.py
	$(CPUENV) $(PY) tools/launch.py -n 2 --launcher local \
	    $(PY) tests/nightly/dist_fused_module.py
	$(CPUENV) $(PY) tools/launch.py -n 2 --launcher local \
	    $(PY) tests/nightly/dist_fault_detect.py
	$(CPUENV) $(PY) tools/launch.py -n 2 --launcher local \
	    $(PY) tests/nightly/dist_push_overlap.py
	$(CPUENV) $(PY) tools/launch.py -n 2 --launcher local \
	    $(PY) tests/nightly/dist_run_steps.py
	$(CPUENV) $(PY) tests/nightly/multi_kvstore_types.py

examples:
	$(CPUENV) $(PY) -m pytest tests/test_examples.py -q

lint:
	$(CPUENV) $(PY) -m pytest tests/test_lint.py tests/test_docs.py -q

# framework-native analyzer gate: mxlint over the tree (baseline-aware),
# self-hosting pass, and a seeded-violation sanity check. Stdlib-only —
# no CPU guard needed (the CLI never imports jax).
lint-check:
	bash ci/check_lint.sh

# native libraries: embeddable core C API + predict-only ABI +
# IO cores (recordio reader, JPEG decode pool, dependency engine)
libs:
	$(CPUENV) $(PY) -c "from mxnet_tpu import native; \
	    print(native.build_core_lib()); \
	    print(native.build_predict_lib()); \
	    native.get_lib(); native.get_lib_imgdec(); \
	    native.get_lib_engine(); print('io/engine libs OK')"

# amalgamated single-file predict bundle -> build/
predict:
	$(CPUENV) $(PY) tools/amalgamation.py --out build

# perl XS binding over the predict C ABI (compiled-and-run smoke)
perl:
	$(CPUENV) $(PY) -m pytest tests/test_perl_binding.py -q

docs:
	$(CPUENV) $(PY) tools/gen_env_docs.py

# executor-cache tier: static no-jit-in-per-step guard + cache tests
cache-check:
	$(CPUENV) bash ci/check_exec_cache.sh

# serving tier: test suite + dynamic-batching >=2x / zero-retrace gate
serving-check:
	$(CPUENV) bash ci/check_serving.sh

# pipelined-loop tier: the steady-state fit loop performs blocking
# fetches only at log intervals, never per step
sync-check:
	$(CPUENV) $(PY) ci/check_no_perstep_sync.py

# input-pipeline tier: steady-state fit over the mxnet_tpu.data stack
# has zero input stalls with device prefetch on, and a run killed
# mid-epoch auto-resumes with a bit-identical remaining batch stream
data-check:
	$(CPUENV) $(PY) ci/check_input_stall.py

# graph-pass tier: per-pass parity tests + runtime A/B gate (pipeline
# shrinks the executed graph at 1e-6 parity, zero steady-state retraces,
# isomorphic builds share one compiled program)
passes-check:
	$(CPUENV) bash ci/check_passes.sh

# telemetry tier: test suite + runtime gates (every serving request
# correlated submit->reply, /metrics + /statusz agree with in-process
# snapshots, always-on tracing within 3% of step time, flight record
# on an injected fault)
telemetry-check:
	$(CPUENV) bash ci/check_telemetry.sh

# decode tier: test suite + runtime gates (zero retraces over a
# >=64-step continuous decode with mid-stream admission/eviction/
# preemption, greedy parity vs an unbatched reference loop, page-pool
# exhaustion preempts instead of crashing) + paged-vs-rectangular
# KV-memory bench gate
decode-check:
	$(CPUENV) bash ci/check_decode.sh

# generated-kernel codegen gate: test suite + runtime gates (every
# __fusion_group__ lowers with an interpret-mode parity proof or a
# counted fallback reason — no silent drops; fused vs fallback
# programs key separately in the exec cache; kind="kernel"
# calibration records back the tuner's fuse-vs-fallback call; the
# merged ragged step drops the tail-prefill programs from the warmup
# grid at token parity with zero retraces)
fusion-check:
	$(CPUENV) bash ci/check_fusion.sh

# effects + protocol gate: MX010-MX013 clean tree with no baseline,
# then one seeded violation per rule (jit impurity, use-after-donate,
# unordered digest iteration, orphaned wire op) each caught with
# exactly its own code. Stdlib-only — no CPU guard needed.
effects-check:
	bash ci/check_effects.sh

# concurrency race gate: MX006-MX008 clean tree with no baseline, a
# seeded lock-order inversion caught both statically (MX007) and by
# the runtime witness (LockOrderViolation instead of deadlock), and a
# serving+decoding+data+telemetry soak that finishes deadlock-free
# under MXNET_LOCK_WITNESS=raise
race-check:
	$(CPUENV) bash ci/check_concurrency.sh

# sharding tier: test suite + runtime gates (bitwise training parity
# across unsharded / dp-only / dp*tp*fsdp plans on exact arithmetic,
# fsdp per-device storage <= 1/2 replicated, zero steady-state
# retraces, pre-trace rejection of non-dividing explicit specs) +
# storage/step-time bench gate on 8 virtual devices
shard-check:
	$(CPUENV) $(XLA8) bash ci/check_sharding.sh

# profiling tier: test suite + runtime gates (deviceStats covers every
# cached executable after warmup, zero steady-state traces/records
# under instrumentation, calibrated_cost measured-backed for served
# graphs, HBM pre-flight warns/raises before any trace)
profiling-check:
	$(CPUENV) bash ci/check_profiling.sh

# numerics tier: test suite + runtime gates (injected NaN detected at
# the seeded step within one drain interval, attributed to the op fed
# by the poisoned parameter, durable flight record, host-sync budget
# unchanged with numerics on) + paired A/B overhead bench gate
numerics-check:
	$(CPUENV) bash ci/check_numerics.sh

# coldstart tier: disk exec-cache + bundle test suite, then the
# three-subprocess runtime gate (warm snapshot -> fresh-interpreter
# restore with zero traces, zero compiles, bit-identical outputs;
# tampered bundle rejected)
coldstart-check:
	$(CPUENV) bash ci/check_coldstart.sh

# fleet tier: control-plane test suite, then the three-replica
# runtime gate (one bundle -> 0 traces/0 compiles per replica;
# SIGKILL + graceful drain both zero-loss and bit-identical) and the
# affinity-vs-random routing bench A/B
fleet-check:
	$(CPUENV) bash ci/check_fleet.sh

# quantized-serving tier: int8 KV-page test suite, then the runtime
# gates (greedy top-1 agreement >= 0.9 vs float32, measured pool
# capacity >= 1.9x, zero steady-state retraces at int8, a
# quantize="int8" bundle restored in a fresh process at 0 traces /
# 0 compiles, stripped quantization record refused)
quant-check:
	$(CPUENV) bash ci/check_quant.sh

# elastic-training tier: reshard/re-key test suite, then the runtime
# gates (one of two subprocess workers SIGKILLed mid-epoch by its own
# fault injector, survivor finishes bitwise equal to the
# uninterrupted reference with every example consumed exactly once;
# 1→2 re-grow at zero example loss and zero steady-state retraces)
# and the transition-cost bench
elastic-check:
	$(CPUENV) bash ci/check_elastic.sh

# regression diff of two bench captures (nonzero exit on >10% drops):
#   make bench-diff OLD=BENCH_r04.json NEW=BENCH_r05.json
bench-diff:
	$(PY) tools/benchdiff.py $(OLD) $(NEW)

# multi-chip sharding dryrun (DP / SP+TP / PP / EP) on 8 virtual devices
dryrun:
	PALLAS_AXON_POOL_IPS= $(PY) __graft_entry__.py

bench:
	$(PY) bench.py

clean:
	rm -rf build __pycache__ */__pycache__ */*/__pycache__
	rm -f native/libmxtpu_c.so native/libmxtpu_predict.so

"""Torch bridge (reference python/mxnet/torch.py + plugin/torch/):
call torch tensor functions on NDArrays. The reference shipped a
compiled TorchModule/TorchCriterion bridge; here torch (CPU build in
the image) interoperates at the array level — NDArray <-> torch.Tensor
zero-copy via numpy where possible — and `th.<fn>` applies any torch
function to NDArrays, returning NDArrays."""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array


def _torch():
    try:
        import torch

        return torch
    except ImportError as e:  # pragma: no cover
        raise MXNetError("torch is not available") from e


def to_torch(x):
    """NDArray -> torch.Tensor (host copy)."""
    torch = _torch()
    if isinstance(x, NDArray):
        return torch.from_numpy(x.asnumpy())
    return torch.as_tensor(x)


def from_torch(t, ctx=None):
    """torch.Tensor -> NDArray."""
    return array(np.asarray(t.detach().cpu().numpy()), ctx=ctx)


class _TorchNamespace(object):
    """th.add(a, b), th.nn.functional.relu(x), ... on NDArrays."""

    def __init__(self, mod=None):
        self._mod = mod

    def __getattr__(self, name):
        torch = _torch()
        target = getattr(self._mod or torch, name)
        if callable(target):
            def wrapped(*args, **kwargs):
                conv = [
                    to_torch(a) if isinstance(a, NDArray) else a
                    for a in args
                ]
                out = target(*conv, **kwargs)
                torch_mod = _torch()
                if isinstance(out, torch_mod.Tensor):
                    return from_torch(out)
                if isinstance(out, (list, tuple)):
                    return type(out)(
                        from_torch(o)
                        if isinstance(o, torch_mod.Tensor) else o
                        for o in out
                    )
                return out

            return wrapped
        # submodule (e.g. th.nn.functional)
        return _TorchNamespace(target)


th = _TorchNamespace()


def torch_module(module):
    """Wrap a torch.nn.Module as a callable on NDArrays (the
    TorchModule plugin capability, plugin/torch/torch_module-inl.h)."""
    def call(*inputs):
        torch = _torch()
        tins = [to_torch(x) for x in inputs]
        with torch.no_grad():
            out = module(*tins)
        if isinstance(out, torch.Tensor):
            return from_torch(out)
        return [from_torch(o) for o in out]

    return call


def register_torch_module(op_name, module_factory, probe_dtype=None):
    """Register a torch.nn.Module as a RUNTIME symbol op — the
    reference's TorchModule plugin (plugin/torch/torch_module-inl.h:
    lua modules as graph nodes, trainable by the mxnet optimizer).

    The module's parameters surface as mxnet arguments (named
    `<param>` with dots -> underscores), so the regular optimizer
    updates them; forward runs the module, backward runs
    torch.autograd. Use with mx.sym.Custom(data=..., op_type=op_name).

    The custom-op contract is stateless, so backward REPLAYS the torch
    forward under autograd. Stochastic modules (Dropout etc.) would
    draw a fresh mask in the replay — gradients then correspond to a
    different realization than the forward's output. Keep bridged
    modules deterministic; eval/train mode is set from is_train.

    `probe_dtype` sets the dtype of the zeros tensor used to probe the
    module at shape inference (default torch float32); pass e.g.
    torch.long for Embedding-style modules whose forward requires
    integer inputs.

    Returns the ordered mxnet argument names for the module's params.
    """
    torch = _torch()

    from . import ndarray as _nd
    from . import operator as _op

    # ONE shared module instance: every call overwrites the weights
    # from in_data anyway, so per-callback reconstruction (full torch
    # init each step) would be pure waste
    shared = module_factory()
    pnames = [n.replace(".", "_")
              for n, _ in shared.named_parameters()]

    class _TorchModuleOp(_op.CustomOp):
        def __init__(self):
            self._m = shared
            self._params = [p for _, p in self._m.named_parameters()]

        def _load_params(self, in_data):
            with torch.no_grad():
                for p, v in zip(self._params, in_data[1:]):
                    p.copy_(torch.from_numpy(v.asnumpy()))

        def forward(self, is_train, req, in_data, out_data, aux):
            self._load_params(in_data)
            self._m.train(bool(is_train))
            x = torch.from_numpy(in_data[0].asnumpy())
            with torch.no_grad():
                out = self._m(x)
            self.assign(out_data[0], req[0],
                        _nd.array(out.detach().numpy()))

        def backward(self, req, out_grad, in_data, out_data, in_grad,
                     aux):
            # stateless replay (see docstring): forward again under
            # autograd, then grad wrt input + params
            self._load_params(in_data)
            self._m.train(True)
            x = torch.from_numpy(in_data[0].asnumpy())
            x.requires_grad_(True)
            out = self._m(x)
            go = torch.from_numpy(out_grad[0].asnumpy())
            grads = torch.autograd.grad(
                out, [x] + self._params, grad_outputs=go,
                allow_unused=True)
            for i, g in enumerate(grads):
                val = (np.zeros(in_grad[i].shape, np.float32)
                       if g is None else g.numpy())
                self.assign(in_grad[i], req[i], _nd.array(val))

    class _TorchModuleProp(_op.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"] + pnames

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            was_training = shared.training
            shared.train(False)
            try:
                with torch.no_grad():
                    out = shared(torch.zeros(*in_shape[0],
                                             dtype=probe_dtype))
            except Exception as exc:
                raise MXNetError(
                    f"register_torch_module('{op_name}'): shape "
                    f"inference probes the module with torch.zeros"
                    f"{tuple(in_shape[0])} of dtype "
                    f"{probe_dtype or 'float32'}; the module rejected "
                    f"it ({exc}). If its forward needs integer inputs "
                    f"(e.g. nn.Embedding), pass probe_dtype=torch.long"
                ) from exc
            finally:
                shared.train(was_training)
            pshapes = [tuple(p.shape)
                       for _, p in shared.named_parameters()]
            return ([tuple(in_shape[0])] + pshapes,
                    [tuple(out.shape)], [])

        def create_operator(self, ctx, shapes, dtypes):
            return _TorchModuleOp()

    _op.register(op_name)(_TorchModuleProp)
    return pnames


def register_caffe_op(op_name, prototxt=None, layer=None,
                      num_params=None):
    """The reference's CaffeOp plugin (plugin/caffe/caffe_op-inl.h):
    run a caffe layer as a trainable graph node. Implemented in
    mxnet_tpu/caffe_bridge.py (pycaffe when importable, built-in numpy
    layers otherwise); offline model import stays with
    tools/caffe_converter.py."""
    from .caffe_bridge import register_caffe_op as _impl

    return _impl(op_name, prototxt=prototxt, layer=layer,
                 num_params=num_params)


def torch_module_init_params(module_factory, prefix=""):
    """{mxnet arg name: NDArray} holding the torch module's OWN
    initialization — feed to init_params(arg_params=...) so the graph
    starts from torch's init, reference TorchModule behavior."""
    m = module_factory()
    return {
        prefix + n.replace(".", "_"): array(
            p.detach().numpy().astype(np.float32))
        for n, p in m.named_parameters()
    }

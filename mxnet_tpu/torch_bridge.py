"""Torch bridge (reference python/mxnet/torch.py + plugin/torch/):
call torch tensor functions on NDArrays. The reference shipped a
compiled TorchModule/TorchCriterion bridge; here torch (CPU build in
the image) interoperates at the array level — NDArray <-> torch.Tensor
zero-copy via numpy where possible — and `th.<fn>` applies any torch
function to NDArrays, returning NDArrays."""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .ndarray import NDArray, array


def _torch():
    try:
        import torch

        return torch
    except ImportError as e:  # pragma: no cover
        raise MXNetError("torch is not available") from e


def to_torch(x):
    """NDArray -> torch.Tensor (host copy)."""
    torch = _torch()
    if isinstance(x, NDArray):
        return torch.from_numpy(x.asnumpy())
    return torch.as_tensor(x)


def from_torch(t, ctx=None):
    """torch.Tensor -> NDArray."""
    return array(np.asarray(t.detach().cpu().numpy()), ctx=ctx)


class _TorchNamespace(object):
    """th.add(a, b), th.nn.functional.relu(x), ... on NDArrays."""

    def __init__(self, mod=None):
        self._mod = mod

    def __getattr__(self, name):
        torch = _torch()
        target = getattr(self._mod or torch, name)
        if callable(target):
            def wrapped(*args, **kwargs):
                conv = [
                    to_torch(a) if isinstance(a, NDArray) else a
                    for a in args
                ]
                out = target(*conv, **kwargs)
                torch_mod = _torch()
                if isinstance(out, torch_mod.Tensor):
                    return from_torch(out)
                if isinstance(out, (list, tuple)):
                    return type(out)(
                        from_torch(o)
                        if isinstance(o, torch_mod.Tensor) else o
                        for o in out
                    )
                return out

            return wrapped
        # submodule (e.g. th.nn.functional)
        return _TorchNamespace(target)


th = _TorchNamespace()


def torch_module(module):
    """Wrap a torch.nn.Module as a callable on NDArrays (the
    TorchModule plugin capability, plugin/torch/torch_module-inl.h)."""
    def call(*inputs):
        torch = _torch()
        tins = [to_torch(x) for x in inputs]
        with torch.no_grad():
            out = module(*tins)
        if isinstance(out, torch.Tensor):
            return from_torch(out)
        return [from_torch(o) for o in out]

    return call

"""Symbolic graph API.

Analog of the reference Symbol (nnvm::Symbol, python/mxnet/symbol.py):
composition, auto-created weight/aux variables, attribute scopes, JSON
save/load (MXNet-compatible node-list format), shape/type inference, and
`bind`/`simple_bind` producing an Executor (executor.py) that lowers the
whole graph to one jax.jit computation — the TPU-native replacement for
the NNVM pass pipeline + GraphExecutor (src/executor/graph_executor.cc).
"""
from __future__ import annotations

import json
import threading

import numpy as np

from .base import MXNetError, _auto_name
from .context import Context, current_context
from .ops import registry as _registry
from .ops import shape_infer as _shape_infer


class AttrScope:
    """with mx.AttrScope(ctx_group='dev1'): ... (python/mxnet/attribute.py)"""

    _current = threading.local()

    def __init__(self, **kwargs):
        self._attr = {f"__{k}__" if not k.startswith("__") else k: str(v)
                      for k, v in kwargs.items()}

    @classmethod
    def current_attrs(cls):
        stack = getattr(cls._current, "stack", None)
        out = {}
        for scope in stack or ():
            out.update(scope._attr)
        return out

    def __enter__(self):
        if not hasattr(AttrScope._current, "stack"):
            AttrScope._current.stack = []
        AttrScope._current.stack.append(self)
        return self

    def __exit__(self, *_):
        AttrScope._current.stack.pop()


class Prefix:
    """with mx.name.Prefix('stage1_'): (python/mxnet/name.py)"""

    _current = threading.local()

    def __init__(self, prefix):
        self._prefix = prefix

    @classmethod
    def current_prefix(cls):
        stack = getattr(cls._current, "stack", None)
        return "".join(p._prefix for p in stack or ())

    def __enter__(self):
        if not hasattr(Prefix._current, "stack"):
            Prefix._current.stack = []
        Prefix._current.stack.append(self)
        return self

    def __exit__(self, *_):
        Prefix._current.stack.pop()


class Node:
    __slots__ = ("op", "name", "attrs", "inputs", "is_aux", "_extra_attrs")

    def __init__(self, op, name, attrs=None, inputs=None, is_aux=False):
        self.op = op  # OpDef or None for variables
        self.name = name
        self.attrs = dict(attrs or {})  # op params (python values)
        self.inputs = list(inputs or [])  # [(Node, out_index)]
        self.is_aux = is_aux
        self._extra_attrs = {}  # user attrs (__ctx_group__, lr_mult, ...)

    @property
    def is_variable(self):
        return self.op is None


def _topo(heads):
    """Post-order DFS over nodes reachable from head (node, idx) pairs."""
    seen = set()
    order = []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for inp, _ in node.inputs:
            visit(inp)
        order.append(node)

    for node, _ in heads:
        visit(node)
    return order


class Symbol:
    """Symbolic graph handle: a list of (Node, output-index) heads.

    Compose with op calls, inspect (list_arguments/outputs/internals),
    infer shapes/types, serialize to the reference JSON, and bind into
    an Executor (reference python/mxnet/symbol.py surface)."""

    def __init__(self, outputs):
        self._outputs = list(outputs)  # [(Node, int)]

    # ------------------------------------------------------- structure
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def list_outputs(self):
        out = []
        for node, idx in self._outputs:
            if node.is_variable:
                out.append(node.name)
            else:
                n_out = node.op.resolved_num_outputs(
                    node.op.normalize_params(node.attrs)
                )
                if n_out == 1:
                    out.append(f"{node.name}_output")
                else:
                    out.append(f"{node.name}_output{idx}")
        return out

    def list_arguments(self):
        return [
            n.name
            for n in _topo(self._outputs)
            if n.is_variable and not n.is_aux
        ]

    def list_auxiliary_states(self):
        return [
            n.name for n in _topo(self._outputs) if n.is_variable and n.is_aux
        ]

    def list_inputs(self):
        return [n.name for n in _topo(self._outputs) if n.is_variable]

    def get_internals(self):
        heads = []
        for node in _topo(self._outputs):
            if node.is_variable:
                heads.append((node, 0))
            else:
                params = node.op.normalize_params(node.attrs)
                for i in range(node.op.resolved_num_outputs(params)):
                    heads.append((node, i))
        return Symbol(heads)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError(
                    f"cannot find output {index!r} in {names}"
                )
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (self[i] for i in range(len(self._outputs)))

    # ------------------------------------------------------ attributes
    def attr(self, key):
        node = self._outputs[0][0]
        return node._extra_attrs.get(key)

    def _set_attr(self, **kwargs):
        node = self._outputs[0][0]
        node._extra_attrs.update({k: str(v) for k, v in kwargs.items()})

    def attr_dict(self):
        out = {}
        for node in _topo(self._outputs):
            d = {}
            d.update({k: str(v) for k, v in node.attrs.items()})
            d.update(node._extra_attrs)
            if d:
                out[node.name] = d
        return out

    # ------------------------------------------------------ composition
    def __call__(self, *args, **kwargs):
        # compose: replace variable inputs (used by rnn cells)
        raise MXNetError("Symbol.__call__ composition not supported; "
                         "pass inputs at creation time")

    def __add__(self, other):
        return _sym_binary(self, other, "elemwise_add", "_plus_scalar")

    def __radd__(self, other):
        return _sym_binary(self, other, "elemwise_add", "_plus_scalar")

    def __sub__(self, other):
        return _sym_binary(self, other, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, other):
        return _sym_scalar(self, other, "_rminus_scalar")

    def __mul__(self, other):
        return _sym_binary(self, other, "elemwise_mul", "_mul_scalar")

    def __rmul__(self, other):
        return _sym_binary(self, other, "elemwise_mul", "_mul_scalar")

    def __div__(self, other):
        return _sym_binary(self, other, "elemwise_div", "_div_scalar")

    __truediv__ = __div__

    def __rdiv__(self, other):
        return _sym_scalar(self, other, "_rdiv_scalar")

    __rtruediv__ = __rdiv__

    def __pow__(self, other):
        return _sym_binary(self, other, "_power", "_power_scalar")

    def __rpow__(self, other):
        return _sym_scalar(self, other, "_rpower_scalar")

    def __neg__(self):
        return _sym_scalar(self, -1.0, "_mul_scalar")

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __repr__(self):
        return f"<Symbol {self.name or 'grouped'}>"

    # -------------------------------------------------------- inference
    def infer_shape(self, *args, **kwargs):
        res = self._infer_shape_impl(False, *args, **kwargs)
        return res

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        known = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    known[name] = tuple(shape)
        known.update(
            {k: tuple(v) for k, v in kwargs.items() if v is not None}
        )
        shapes, dtypes = _graph_infer(
            self._outputs, known, {}, partial=partial
        )
        if shapes is None:
            return None, None, None
        arg_shapes = [shapes.get((n, 0)) for n in _var_nodes(self._outputs)
                      if not n.is_aux]
        aux_shapes = [shapes.get((n, 0)) for n in _var_nodes(self._outputs)
                      if n.is_aux]
        out_shapes = [shapes.get(_key(h)) for h in self._outputs]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        """Dtype propagation, independent of shapes: variables default to
        float32 (or their __dtype__ attr / explicit kwargs); op outputs
        take the op's `dtype` param when present, else the first input's
        dtype — matching the reference's overwhelmingly same-dtype op set."""
        arg_names = self.list_arguments()
        known = {}
        if args:
            for name, t in zip(arg_names, args):
                if t is not None:
                    known[name] = np.dtype(t)
        known.update(
            {k: np.dtype(v) for k, v in kwargs.items() if v is not None}
        )
        dtypes = {}
        for n in _topo(self._outputs):
            if n.is_variable:
                if n.name in known:
                    dt = known[n.name]
                elif "__dtype__" in n._extra_attrs:
                    dt = np.dtype(n._extra_attrs["__dtype__"])
                else:
                    dt = np.dtype(np.float32)
                dtypes[(n, 0)] = dt
            else:
                params = n.op.normalize_params(n.attrs)
                if "dtype" in params:
                    dt = np.dtype(params["dtype"])
                elif n.inputs:
                    dt = dtypes[(n.inputs[0][0], n.inputs[0][1])]
                else:
                    dt = np.dtype(np.float32)
                for i in range(n.op.resolved_num_outputs(params)):
                    dtypes[(n, i)] = dt
        arg_types = [dtypes.get((n, 0), np.dtype(np.float32))
                     for n in _var_nodes(self._outputs) if not n.is_aux]
        aux_types = [dtypes.get((n, 0), np.dtype(np.float32))
                     for n in _var_nodes(self._outputs) if n.is_aux]
        out_types = [dtypes.get(_key(h), np.dtype(np.float32))
                     for h in self._outputs]
        return arg_types, out_types, aux_types

    # ------------------------------------------------------ canonical form
    def structure_key(self):
        """Canonical, hashable signature of the graph structure: the
        topo-sorted node list (op name, node name, normalized params,
        ctx-group tag, input wiring as topo indices) plus the head
        wiring. Two symbols with equal keys lower to the same
        computation, so executors bound to them (with equal shapes /
        dtypes / grad config) can share one compiled program — the
        exec_cache key's graph component."""
        nodes = _topo(self._outputs)
        idx = {id(n): i for i, n in enumerate(nodes)}
        entries = []
        for n in nodes:
            if n.is_variable:
                entries.append((
                    "null", n.name, bool(n.is_aux),
                    n._extra_attrs.get("__ctx_group__"),
                ))
            else:
                entries.append((
                    n.op.name, n.name,
                    _canon(n.op.normalize_params(n.attrs)),
                    n._extra_attrs.get("__ctx_group__"),
                    tuple((idx[id(src)], i) for src, i in n.inputs),
                ))
        heads = tuple((idx[id(n)], i) for n, i in self._outputs)
        return (tuple(entries), heads)

    def canonical_signature(self):
        """Stable hex digest of the canonical (pass-pipeline-optimized)
        graph. Unlike structure_key() it survives pickling/processes,
        and unlike tojson() it is construction-order independent — two
        differently-built isomorphic symbols share one signature. Keys
        the tuning cache (passes.Autotuner)."""
        from . import passes as _passes

        return _passes.canonical_digest(self)

    # ------------------------------------------------------- serialization
    def tojson(self, canonical=False):
        """Serialize to the node-list JSON graph. `canonical=True`
        first runs the default pass pipeline (passes.optimize), so the
        emitted JSON is the canonical form: stable topo order, dense
        auto-names, normalized params, folded constants."""
        if canonical:
            from . import passes as _passes

            return _passes.optimize(self).tojson()
        nodes = _topo(self._outputs)
        node_index = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            attrs = {k: _attr_str(v) for k, v in n.attrs.items()}
            attrs.update(n._extra_attrs)
            jn = {
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "inputs": [
                    [node_index[id(src)], idx, 0] for src, idx in n.inputs
                ],
            }
            if attrs:
                jn["attrs"] = attrs
            if n.is_aux:
                jn.setdefault("attrs", {})["__is_aux__"] = "True"
            jnodes.append(jn)
        return json.dumps(
            {
                "nodes": jnodes,
                "arg_nodes": [
                    i for i, n in enumerate(nodes) if n.is_variable
                ],
                "heads": [
                    [node_index[id(n)], idx, 0] for n, idx in self._outputs
                ],
                "attrs": {"mxnet_version": ["str", "0.9.5-tpu"]},
            },
            indent=2,
        )

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------------- binding
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    group2ctx=None, shared_exec=None, sharding=None,
                    **kwargs):
        from .executor import Executor

        ctx = ctx or current_context()
        arg_shapes, out_shapes, aux_shapes = self.infer_shape(**kwargs)
        if arg_shapes is None or any(s is None for s in arg_shapes):
            raise MXNetError(
                f"simple_bind: could not infer all argument shapes from "
                f"{kwargs}"
            )
        type_dict = type_dict or {}
        arg_types, _, aux_types = self.infer_type(**type_dict)
        from . import ndarray as nd

        arg_names = self.list_arguments()
        args = {
            n: nd.zeros(s, ctx=ctx, dtype=t)
            for n, s, t in zip(arg_names, arg_shapes, arg_types)
        }
        aux = {
            n: nd.zeros(s, ctx=ctx, dtype=t)
            for n, s, t in zip(
                self.list_auxiliary_states(), aux_shapes, aux_types
            )
        }
        if isinstance(grad_req, str):
            req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            req = dict(zip(arg_names, grad_req))
        else:
            req = dict(grad_req)
        grads = {
            n: nd.zeros(s, ctx=ctx, dtype=t)
            for n, s, t in zip(arg_names, arg_shapes, arg_types)
            if req.get(n, "null") != "null"
        }
        return Executor(
            self, ctx, args, grads, req, aux, group2ctx=group2ctx,
            shared_exec=shared_exec, sharding=sharding
        )

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from .executor import Executor

        ctx = ctx or current_context()
        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        args_grad = args_grad or {}
        if isinstance(aux_states, (list, tuple)):
            aux_states = dict(zip(aux_names, aux_states))
        aux_states = aux_states or {}
        if isinstance(grad_req, str):
            req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            req = dict(zip(arg_names, grad_req))
        else:
            req = dict(grad_req)
        # missing aux -> zeros of inferred shape
        if aux_names and len(aux_states) < len(aux_names):
            shapes = {n: tuple(a.shape) for n, a in args.items()}
            arg_shapes, _, aux_shapes = self.infer_shape(**shapes)
            from . import ndarray as nd

            for n, s in zip(aux_names, aux_shapes):
                if n not in aux_states:
                    aux_states[n] = nd.zeros(s, ctx=ctx)
        return Executor(
            self, ctx, args, args_grad, req, aux_states,
            group2ctx=group2ctx, shared_exec=shared_exec
        )

    def eval(self, ctx=None, **kwargs):
        ex = self.bind(ctx or current_context(), args=kwargs, grad_req="null")
        return ex.forward()

    # debug
    def debug_str(self):
        lines = []
        for n in _topo(self._outputs):
            kind = "Variable" if n.is_variable else n.op.name
            ins = ", ".join(f"{src.name}[{i}]" for src, i in n.inputs)
            lines.append(f"{kind} {n.name}({ins})")
        return "\n".join(lines)


def _canon(value):
    """Hashable canonical form of an op-param value. Containers become
    tuples of canonical items; np.dtype becomes its name; hashable
    leaves (including functions — identity-hashed, and kept strongly
    referenced by the cache key so their id cannot be recycled) pass
    through unchanged."""
    if isinstance(value, dict):
        return tuple(sorted(
            (str(k), _canon(v)) for k, v in value.items()))
    if isinstance(value, (list, tuple)):
        return tuple(_canon(v) for v in value)
    if isinstance(value, (set, frozenset)):
        return tuple(sorted(repr(_canon(v)) for v in value))
    if isinstance(value, np.dtype):
        return value.name
    if isinstance(value, np.ndarray):
        return (value.dtype.name, value.shape, value.tobytes())
    try:
        hash(value)
        return value
    except TypeError:
        return repr(value)


def _key(head):
    return (head[0], head[1])


def _var_nodes(outputs):
    return [n for n in _topo(outputs) if n.is_variable]


def _attr_str(v):
    if isinstance(v, (tuple, list)):
        return "(" + ", ".join(str(x) for x in v) + ")"
    return str(v)


def _graph_infer(heads, known_shapes, known_dtypes, partial=False):
    """Iterative forward inference to fixpoint over the graph."""
    nodes = _topo(heads)
    shapes = {}  # (node, idx) -> tuple
    dtypes = {}
    for n in nodes:
        if n.is_variable:
            if n.name in known_shapes:
                shapes[(n, 0)] = tuple(known_shapes[n.name])
            elif "__shape__" in n._extra_attrs:
                # shape declared at Variable() creation
                from .base import coerce_tuple

                shapes[(n, 0)] = coerce_tuple(n._extra_attrs["__shape__"])
            if n.name in known_dtypes:
                dtypes[(n, 0)] = np.dtype(known_dtypes[n.name])
    progress = True
    failures = {}
    while progress:
        progress = False
        failures = {}
        for n in nodes:
            if n.is_variable:
                continue
            params = n.op.normalize_params(n.attrs)
            n_out = n.op.resolved_num_outputs(params)
            outkeys = [(n, i) for i in range(n_out)]
            if all(k in shapes for k in outkeys) and all(
                (src, i) in shapes for src, i in n.inputs
            ):
                continue
            in_shapes = [shapes.get((src, i)) for src, i in n.inputs]
            in_dtypes = [
                dtypes.get((src, i), np.dtype(np.float32))
                for src, i in n.inputs
            ]
            try:
                new_in, out_shapes, out_dtypes = _shape_infer.infer_node(
                    n.op, params, in_shapes, in_dtypes
                )
            except MXNetError as e:
                failures[n.name] = str(e)
                continue
            except Exception as e:  # abstract eval failure
                failures[n.name] = f"{type(e).__name__}: {e}"
                continue
            for (src, i), s in zip(n.inputs, new_in):
                if (src, i) not in shapes and s is not None:
                    shapes[(src, i)] = tuple(s)
                    progress = True
            for k, s, d in zip(outkeys, out_shapes, out_dtypes):
                if k not in shapes:
                    shapes[k] = tuple(s)
                    progress = True
                dtypes[k] = d
    if failures and not partial:
        detail = "; ".join(f"{k}: {v}" for k, v in failures.items())
        raise MXNetError(f"infer_shape failed: {detail}")
    return shapes, dtypes


# ------------------------------------------------------------ constructors


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, **kwargs):
    """A named graph input/parameter Symbol.

    Extra kwargs become __attr__ annotations (shape, sharding,
    ctx_group, init, ...)."""
    node = Node(None, name)
    if attr:
        node._extra_attrs.update({k: str(v) for k, v in attr.items()})
    node._extra_attrs.update(AttrScope.current_attrs())
    if shape is not None:
        node._extra_attrs["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        node._extra_attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        node._extra_attrs["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        node._extra_attrs["__dtype__"] = str(np.dtype(dtype))
    if init is not None:
        node._extra_attrs["__init__"] = (
            init if isinstance(init, str) else init.dumps()
        )
    for k, v in kwargs.items():
        node._extra_attrs[f"__{k}__"] = str(v)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    """One multi-output Symbol from many (reference mx.sym.Group)."""
    outputs = []
    for s in symbols:
        outputs.extend(s._outputs)
    return Symbol(outputs)


def load(fname):
    with open(fname) as f:
        return loads(f.read())


def loads(json_str):
    data = json.loads(json_str)
    jnodes = data["nodes"]
    nodes = []
    for jn in jnodes:
        attrs = dict(jn.get("attrs", jn.get("attr", {}) or {}))
        is_aux = attrs.pop("__is_aux__", "False") in ("True", "1", "true")
        extra = {k: v for k, v in attrs.items() if k.startswith("__")}
        params = {k: v for k, v in attrs.items() if not k.startswith("__")}
        if jn["op"] == "null":
            node = Node(None, jn["name"], is_aux=is_aux)
        else:
            node = Node(_registry.get(jn["op"]), jn["name"], attrs=params)
        node._extra_attrs = extra
        node.inputs = [
            (nodes[i], idx) for i, idx, *_ in jn["inputs"]
        ]
        nodes.append(node)
    heads = [(nodes[i], idx) for i, idx, *_ in data["heads"]]
    return Symbol(heads)


def _sym_binary(lhs, rhs, elem_op, scalar_op):
    if isinstance(rhs, Symbol):
        return _create(_registry.get(elem_op), [lhs, rhs], {})
    return _create(_registry.get(scalar_op), [lhs], {"scalar": float(rhs)})


def _sym_scalar(sym, scalar, op):
    return _create(_registry.get(op), [sym], {"scalar": float(scalar)})


def _create(opdef, input_syms, params, name=None):
    """Create an op node: auto-name, auto-create missing weight/aux vars
    (python/mxnet/symbol.py _compose semantics)."""
    prefix = Prefix.current_prefix()
    if name is None:
        name = prefix + _auto_name(opdef.name.lower().lstrip("_"))
    else:
        name = prefix + name
    inputs = []
    params = opdef.normalize_params(params)
    if opdef.arg_names is not None or opdef.arg_names_fn is not None:
        given = list(input_syms)
        # positionally fill declared args; auto-create the rest
        needed = _required_inputs(opdef, params)
        gi = iter(given)
        for an in needed:
            s = next(gi, None)
            if s is None:
                v = Variable(f"{name}_{an}")
                inputs.append(v._outputs[0])
            else:
                if len(s._outputs) != 1:
                    raise MXNetError(
                        f"{opdef.name}: grouped symbol cannot be an input"
                    )
                inputs.append(s._outputs[0])
        rest = list(gi)
        if rest:
            raise MXNetError(
                f"{opdef.name}: too many inputs ({len(given)} given, "
                f"{len(needed)} expected)"
            )
    else:
        for s in input_syms:
            inputs.extend(s._outputs)
        if "num_args" in (opdef.coerce or {}):
            params.setdefault("num_args", len(inputs))
    for aux in opdef.aux_names:
        v = Variable(f"{name}_{aux}")
        v._outputs[0][0].is_aux = True
        inputs.append(v._outputs[0])
    node = Node(opdef, name, attrs=params, inputs=inputs)
    node._extra_attrs.update(AttrScope.current_attrs())
    n_out = opdef.resolved_num_outputs(params)
    return Symbol([(node, i) for i in range(n_out)])


def _required_inputs(opdef, params):
    """Declared inputs actually used given params (e.g. no bias when
    no_bias=True, no gamma unless prelu)."""
    if opdef.arg_names_fn is not None:
        return list(opdef.arg_names_fn(params))
    names = list(opdef.arg_names)
    if params.get("no_bias") and "bias" in names:
        names.remove("bias")
    if opdef.name == "LeakyReLU" and params.get("act_type") != "prelu":
        names = ["data"]
    if opdef.name in ("SequenceMask", "SequenceLast", "SequenceReverse") and \
            not params.get("use_sequence_length"):
        names = ["data"]
    if opdef.name == "RNN" and params.get("mode") != "lstm":
        names = [n for n in names if n != "state_cell"]
    return names


def _make_symbol_function(opdef, func_name):
    def creator(*args, **kwargs):
        name = kwargs.pop("name", None)
        kwargs.pop("attr", None)
        input_syms = [a for a in args if isinstance(a, Symbol)]
        sym_kwargs = {}
        params = {}
        # aux states are auto-created (reference ListAuxiliaryStates
        # semantics), so only declared args are valid symbol inputs
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                if opdef.arg_names is None and opdef.arg_names_fn is None:
                    raise MXNetError(
                        f"{func_name}: variadic op takes positional "
                        f"symbol inputs only"
                    )
                sym_kwargs[k] = v
            else:
                params[k] = v
        if opdef.arg_names_fn is not None:
            valid_names = set(
                opdef.arg_names_fn(opdef.normalize_params(params))
            )
        else:
            valid_names = set(opdef.arg_names or ())
        for k in sym_kwargs:
            if k not in valid_names:
                raise MXNetError(
                    f"{func_name}: unknown input {k!r} "
                    f"(expected one of {sorted(valid_names)})"
                )
        if sym_kwargs:
            # slot-exact merge: kwargs pin their named slot; positional
            # args fill remaining slots in declaration order; unfilled
            # slots stay None for _create to auto-create (so e.g.
            # Convolution(data=d, bias=b, ...) cannot misbind b as weight)
            merged = []
            pos = iter(input_syms)
            norm = opdef.normalize_params(params)
            for an in _required_inputs(opdef, norm):
                if an in sym_kwargs:
                    merged.append(sym_kwargs[an])
                else:
                    merged.append(next(pos, None))
            leftover = list(pos)
            if leftover:
                raise MXNetError(f"{func_name}: too many symbol inputs")
            input_syms = merged
        return _create(opdef, input_syms, params, name=name)

    creator.__name__ = func_name
    from .ndarray import _op_doc

    creator.__doc__ = _op_doc(opdef, func_name, "Symbolic")
    return creator


import sys as _sys

_this = _sys.modules[__name__]
for _name in _registry.list_ops():
    _opdef = _registry.get(_name)
    if not hasattr(_this, _name):
        setattr(_this, _name, _make_symbol_function(_opdef, _name))


def zeros(shape, dtype=np.float32, **kwargs):
    return _create(_registry.get("_zeros"), [],
                   {"shape": shape, "dtype": np.dtype(dtype).name}, **kwargs)


def ones(shape, dtype=np.float32, **kwargs):
    return _create(_registry.get("_ones"), [],
                   {"shape": shape, "dtype": np.dtype(dtype).name}, **kwargs)

"""Vision / contrib operator tier.

Covers the reference's hand-written CUDA contrib ops with TPU-idiomatic
vectorized implementations (no scalar loops — everything is masked
dense math so XLA can tile it):

- SpatialTransformer + GridGenerator + BilinearSampler
  (reference src/operator/spatial_transformer-inl.h, grid_generator-inl.h,
  bilinear_sampler-inl.h)
- ROIPooling (reference src/operator/roi_pooling-inl.h)
- Correlation (reference src/operator/correlation-inl.h)
- MultiBoxPrior / MultiBoxTarget / MultiBoxDetection — SSD anchors,
  matching, NMS (reference src/operator/contrib/multibox_*.cc/.cu)
- Proposal — Faster-RCNN RPN proposals (reference
  src/operator/contrib/proposal-inl.h)
- fft / ifft (reference src/operator/contrib/fft-inl.h, cuFFT-backed
  there; jnp.fft → XLA here, complex packed as interleaved re/im)
- count_sketch (reference src/operator/contrib/count_sketch-inl.h)
- quantize / dequantize (reference src/operator/contrib/quantize-inl.h)

NMS note: suppression is inherently sequential in the reference's CUDA
kernel; here it is a lax.fori_loop over the fixed top-k candidates with
masked IoU updates — static shapes, compiles once.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from ..base import MXNetError, coerce_bool, coerce_float, coerce_int, coerce_tuple


# ---------------------------------------------------- spatial transformer


def _affine_grid(theta, out_h, out_w):
    """theta: (N, 6) affine params -> sampling grid (N, out_h, out_w, 2)
    in normalized [-1, 1] target coords."""
    n = theta.shape[0]
    theta = theta.reshape(n, 2, 3)
    ys = jnp.linspace(-1.0, 1.0, out_h)
    xs = jnp.linspace(-1.0, 1.0, out_w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    coords = jnp.stack(
        [gx.ravel(), gy.ravel(), ones.ravel()], axis=0
    )  # (3, H*W)
    out = jnp.einsum("nij,jk->nik", theta, coords)  # (N, 2, H*W)
    return out.transpose(0, 2, 1).reshape(n, out_h, out_w, 2)


def _bilinear_sample(data, grid_xy):
    """data: (N, C, H, W); grid_xy: (N, out_h, out_w, 2) normalized
    (x, y) in [-1, 1]. Out-of-bounds samples are zero (reference
    bilinear_sampler-inl.h border behavior)."""
    n, c, h, w = data.shape
    gx = (grid_xy[..., 0] + 1.0) * (w - 1) / 2.0
    gy = (grid_xy[..., 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    x1 = x0 + 1
    y1 = y0 + 1

    def gather(yi, xi):
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        # (N, out_h, out_w) index maps -> gather per batch
        out = jax.vmap(
            lambda img, yy, xx: img[:, yy, xx]
        )(data, yc, xc)  # (N, C, out_h, out_w)
        valid = (
            (yi >= 0) & (yi <= h - 1) & (xi >= 0) & (xi <= w - 1)
        )
        return out * valid[:, None].astype(data.dtype)

    w00 = (x1 - gx) * (y1 - gy)
    w01 = (gx - x0) * (y1 - gy)
    w10 = (x1 - gx) * (gy - y0)
    w11 = (gx - x0) * (gy - y0)
    return (
        gather(y0, x0) * w00[:, None]
        + gather(y0, x1) * w01[:, None]
        + gather(y1, x0) * w10[:, None]
        + gather(y1, x1) * w11[:, None]
    )


@register(
    "GridGenerator",
    arg_names=["data"],
    coerce={"target_shape": coerce_tuple},
    defaults={"transform_type": "affine"},
)
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """affine: data (N, 6) -> grid (N, 2, H, W); warp: data (N, 2, H, W)
    flow field -> absolute sampling grid."""
    if transform_type == "affine":
        h, w = int(target_shape[0]), int(target_shape[1])
        grid = _affine_grid(data, h, w)  # (N, H, W, 2) xy
        return grid.transpose(0, 3, 1, 2)
    if transform_type == "warp":
        n, _, h, w = data.shape
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        base = jnp.stack([gx, gy], axis=0)[None]
        flow = jnp.stack(
            [data[:, 0] * 2.0 / max(w - 1, 1),
             data[:, 1] * 2.0 / max(h - 1, 1)],
            axis=1,
        )
        return base + flow
    raise MXNetError(f"unknown transform_type {transform_type!r}")


@register(
    "BilinearSampler",
    arg_names=["data", "grid"],
)
def bilinear_sampler(data, grid):
    """data (N, C, H, W), grid (N, 2, out_h, out_w) normalized (x, y)."""
    return _bilinear_sample(data, grid.transpose(0, 2, 3, 1))


@register(
    "SpatialTransformer",
    arg_names=["data", "loc"],
    coerce={"target_shape": coerce_tuple},
    defaults={"transform_type": "affine", "sampler_type": "bilinear"},
)
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine",
                        sampler_type="bilinear"):
    if transform_type != "affine" or sampler_type != "bilinear":
        raise MXNetError(
            "SpatialTransformer supports affine + bilinear"
        )
    h, w = int(target_shape[0]), int(target_shape[1])
    grid = _affine_grid(loc, h, w)
    return _bilinear_sample(data, grid)


# ------------------------------------------------------------ roi pooling


@register(
    "ROIPooling",
    arg_names=["data", "rois"],
    coerce={"pooled_size": coerce_tuple, "spatial_scale": coerce_float},
)
def roi_pooling(data, rois, pooled_size, spatial_scale):
    """data (N, C, H, W); rois (R, 5) [batch_idx, x1, y1, x2, y2] in
    image coords. Max-pool each roi into (R, C, ph, pw). Vectorized:
    each output bin is a masked max over the whole feature map (dense
    mask instead of the reference's per-bin scalar loops,
    roi_pooling-inl.h)."""
    n, c, h, w = data.shape
    ph, pw = int(pooled_size[0]), int(pooled_size[1])
    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
        roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
        bin_h = roi_h / ph
        bin_w = roi_w / pw
        img = data[bidx]  # (C, H, W)

        py = jnp.arange(ph, dtype=jnp.float32)
        px = jnp.arange(pw, dtype=jnp.float32)
        ys0 = jnp.floor(y1 + py * bin_h)            # (ph,)
        ys1 = jnp.ceil(y1 + (py + 1.0) * bin_h)
        xs0 = jnp.floor(x1 + px * bin_w)            # (pw,)
        xs1 = jnp.ceil(x1 + (px + 1.0) * bin_w)
        ymask = (ys[None, :] >= ys0[:, None]) & (
            ys[None, :] < jnp.maximum(ys1, ys0 + 1.0)[:, None]
        )  # (ph, H)
        xmask = (xs[None, :] >= xs0[:, None]) & (
            xs[None, :] < jnp.maximum(xs1, xs0 + 1.0)[:, None]
        )  # (pw, W)
        mask = ymask[:, None, :, None] & xmask[None, :, None, :]
        # (ph, pw, H, W); masked max over H, W per channel
        neg = jnp.full((c, h, w), -jnp.inf, data.dtype)
        vals = jnp.where(mask[:, :, None], img[None, None], neg)
        out = vals.max(axis=(-1, -2))  # (ph, pw, C)
        out = jnp.where(jnp.isfinite(out), out, 0.0)
        return out.transpose(2, 0, 1)  # (C, ph, pw)

    return jax.vmap(one_roi)(rois)


# ------------------------------------------------------------- correlation


@register(
    "Correlation",
    arg_names=["data1", "data2"],
    num_outputs=1,
    coerce={
        "kernel_size": coerce_int,
        "max_displacement": coerce_int,
        "stride1": coerce_int,
        "stride2": coerce_int,
        "pad_size": coerce_int,
        "is_multiply": coerce_bool,
    },
    defaults={
        "kernel_size": 1,
        "max_displacement": 1,
        "stride1": 1,
        "stride2": 1,
        "pad_size": 0,
        "is_multiply": True,
    },
)
def correlation(data1, data2, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (reference correlation-inl.h),
    simplified to kernel_size=1/stride1=1: output channel per
    displacement (dy, dx) in the window, value = mean over channels of
    data1 * shift(data2)."""
    n, c, h, w = data1.shape
    d = max_displacement
    disp = range(-d, d + 1, stride2)
    p2 = jnp.pad(
        data2, ((0, 0), (0, 0), (d, d), (d, d))
    )
    outs = []
    for dy in disp:
        for dx in disp:
            shifted = lax.dynamic_slice(
                p2, (0, 0, d + dy, d + dx), (n, c, h, w)
            )
            if is_multiply:
                outs.append((data1 * shifted).mean(axis=1))
            else:
                outs.append(
                    jnp.abs(data1 - shifted).mean(axis=1)
                )
    return jnp.stack(outs, axis=1)


# ---------------------------------------------------------- multibox (SSD)


def _iou_matrix(a, b):
    """a: (A, 4), b: (G, 4) corner boxes -> (A, G) IoU."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(
        (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1]), 0.0
    )
    area_b = jnp.maximum(
        (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1]), 0.0
    )
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register(
    "MultiBoxPrior",
    arg_names=["data"],
    coerce={"clip": coerce_bool},
    defaults={"sizes": (1.0,), "ratios": (1.0,), "clip": False},
    aliases=("_contrib_MultiBoxPrior",),
)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False):
    """Anchor boxes for SSD (reference contrib/multibox_prior.cc):
    data (N, C, H, W) -> (1, H*W*(S+R-1), 4) normalized corners."""
    if isinstance(sizes, str):
        sizes = tuple(float(x) for x in sizes.strip("()[]").split(","))
    if isinstance(ratios, str):
        ratios = tuple(float(x) for x in ratios.strip("()[]").split(","))
    _, _, h, w = data.shape
    cy = (jnp.arange(h) + 0.5) / h
    cx = (jnp.arange(w) + 0.5) / w
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([gx.ravel(), gy.ravel()], axis=-1)  # (HW, 2)
    whs = []
    for i, s in enumerate(sizes):
        whs.append((s * jnp.sqrt(ratios[0]), s / jnp.sqrt(ratios[0])))
    for r in ratios[1:]:
        s = sizes[0]
        whs.append((s * jnp.sqrt(r), s / jnp.sqrt(r)))
    whs = jnp.asarray(whs, jnp.float32)  # (K, 2) width, height
    k = whs.shape[0]
    cs = jnp.repeat(centers, k, axis=0)          # (HW*K, 2)
    ws = jnp.tile(whs, (centers.shape[0], 1))     # (HW*K, 2)
    boxes = jnp.concatenate(
        [cs - ws / 2.0, cs + ws / 2.0], axis=-1
    )
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes[None]


@register(
    "MultiBoxTarget",
    arg_names=["anchor", "label", "cls_pred"],
    num_outputs=3,
    coerce={
        "overlap_threshold": coerce_float,
        "ignore_label": coerce_float,
        "negative_mining_ratio": coerce_float,
        "negative_mining_thresh": coerce_float,
        "minimum_negative_samples": coerce_int,
    },
    defaults={
        "overlap_threshold": 0.5,
        "ignore_label": -1.0,
        "negative_mining_ratio": -1.0,
        "negative_mining_thresh": 0.5,
        "minimum_negative_samples": 0,
        "variances": (0.1, 0.1, 0.2, 0.2),
    },
    aliases=("_contrib_MultiBoxTarget",),
    no_grad_inputs=("anchor", "label", "cls_pred"),
)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5,
                    minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (reference contrib/multibox_target.cc).
    anchor (1, A, 4); label (B, G, 5) [cls, x1, y1, x2, y2] with cls=-1
    padding; cls_pred (B, num_cls+1, A). Returns (loc_target (B, A*4),
    loc_mask (B, A*4), cls_target (B, A))."""
    if isinstance(variances, str):
        variances = tuple(
            float(x) for x in variances.strip("()[]").split(",")
        )
    anchors = anchor[0]  # (A, 4)
    a = anchors.shape[0]
    var = jnp.asarray(variances, jnp.float32)

    def one_batch(lab):
        gt_boxes = lab[:, 1:5]
        gt_cls = lab[:, 0]
        valid = gt_cls >= 0  # (G,)
        iou = _iou_matrix(anchors, gt_boxes)  # (A, G)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = iou.argmax(axis=1)              # (A,)
        best_iou = iou.max(axis=1)
        # force-match: each gt claims its best anchor
        best_anchor = iou.argmax(axis=0)          # (G,)
        forced = jnp.zeros((a,), bool)
        forced = forced.at[best_anchor].set(valid)
        gt_of_forced = jnp.zeros((a,), jnp.int32)
        gt_of_forced = gt_of_forced.at[best_anchor].set(
            jnp.arange(gt_boxes.shape[0], dtype=jnp.int32)
        )
        matched = forced | (best_iou >= overlap_threshold)
        match_gt = jnp.where(forced, gt_of_forced, best_gt)

        mg_boxes = gt_boxes[match_gt]  # (A, 4)
        # encode: center offsets scaled by variances
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.maximum(mg_boxes[:, 2] - mg_boxes[:, 0], 1e-8)
        gh = jnp.maximum(mg_boxes[:, 3] - mg_boxes[:, 1], 1e-8)
        gcx = (mg_boxes[:, 0] + mg_boxes[:, 2]) / 2
        gcy = (mg_boxes[:, 1] + mg_boxes[:, 3]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / var[0]
        ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / var[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / var[2]
        th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / var[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)  # (A, 4)
        loc_t = jnp.where(matched[:, None], loc_t, 0.0)
        loc_m = jnp.repeat(
            matched[:, None].astype(jnp.float32), 4, axis=1
        )
        cls_t = jnp.where(
            matched, gt_cls[match_gt] + 1.0, 0.0
        )
        return loc_t.reshape(-1), loc_m.reshape(-1), cls_t

    loc_target, loc_mask, cls_target = jax.vmap(one_batch)(label)

    if negative_mining_ratio > 0:
        # hard negative mining: keep ratio*num_pos hardest negatives
        # (highest max non-background confidence), ignore the rest
        def mine(cls_t, cp):
            pos = cls_t > 0
            num_pos = pos.sum()
            max_k = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                minimum_negative_samples,
            )
            neg_conf = jnp.where(
                ~pos, cp[1:, :].max(axis=0) - cp[0, :], -jnp.inf
            )
            order = jnp.argsort(-neg_conf)
            rank = jnp.zeros((a,), jnp.int32).at[order].set(
                jnp.arange(a, dtype=jnp.int32)
            )
            keep_neg = (~pos) & (rank < max_k)
            return jnp.where(
                pos | keep_neg, cls_t, ignore_label
            )

        cls_target = jax.vmap(mine)(cls_target, cls_pred)
    return loc_target, loc_mask, cls_target


def _nms_loop(boxes, scores, classes, iou_thresh, force_suppress):
    """Greedy NMS over pre-sorted candidates. boxes (K, 4) sorted by
    descending score; returns keep mask (K,)."""
    k = boxes.shape[0]
    iou = _iou_matrix(boxes, boxes)
    same_cls = (
        jnp.ones((k, k), bool)
        if force_suppress
        else classes[:, None] == classes[None, :]
    )
    valid0 = scores > 0

    def body(i, keep):
        sup = (
            keep & (iou[i] > iou_thresh) & same_cls[i]
            & (jnp.arange(k) > i)
        )
        return keep & ~jnp.where(keep[i], sup, False)

    keep = lax.fori_loop(0, k, body, valid0)
    return keep


@register(
    "MultiBoxDetection",
    arg_names=["cls_prob", "loc_pred", "anchor"],
    coerce={
        "clip": coerce_bool,
        "threshold": coerce_float,
        "nms_threshold": coerce_float,
        "force_suppress": coerce_bool,
        "nms_topk": coerce_int,
    },
    defaults={
        "clip": True,
        "threshold": 0.01,
        "nms_threshold": 0.5,
        "force_suppress": False,
        "variances": (0.1, 0.1, 0.2, 0.2),
        "nms_topk": -1,
    },
    aliases=("_contrib_MultiBoxDetection",),
    no_grad_inputs=("cls_prob", "loc_pred", "anchor"),
)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD detection decode + NMS (reference
    contrib/multibox_detection.cc). cls_prob (B, num_cls+1, A),
    loc_pred (B, A*4), anchor (1, A, 4) -> (B, A, 6)
    [cls_id, score, x1, y1, x2, y2], suppressed rows cls_id=-1."""
    if isinstance(variances, str):
        variances = tuple(
            float(x) for x in variances.strip("()[]").split(",")
        )
    anchors = anchor[0]
    a = anchors.shape[0]
    var = jnp.asarray(variances, jnp.float32)
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2

    def one_batch(cp, lp):
        deltas = lp.reshape(a, 4)
        cx = deltas[:, 0] * var[0] * aw + acx
        cy = deltas[:, 1] * var[1] * ah + acy
        bw = jnp.exp(deltas[:, 2] * var[2]) * aw
        bh = jnp.exp(deltas[:, 3] * var[3]) * ah
        boxes = jnp.stack(
            [cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2],
            axis=-1,
        )
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        scores = cp[1:, :]  # (num_cls, A)
        cls_id = scores.argmax(axis=0)            # (A,)
        score = scores.max(axis=0)
        score = jnp.where(score > threshold, score, 0.0)
        order = jnp.argsort(-score)
        boxes_s = boxes[order]
        score_s = score[order]
        cls_s = cls_id[order]
        keep = _nms_loop(
            boxes_s, score_s, cls_s, nms_threshold, force_suppress
        )
        out_cls = jnp.where(keep, cls_s.astype(jnp.float32), -1.0)
        return jnp.concatenate(
            [out_cls[:, None], score_s[:, None], boxes_s], axis=-1
        )

    return jax.vmap(one_batch)(cls_prob, loc_pred)


# ----------------------------------------------------------------- proposal


@register(
    "Proposal",
    arg_names=["cls_prob", "bbox_pred", "im_info"],
    coerce={
        "rpn_pre_nms_top_n": coerce_int,
        "rpn_post_nms_top_n": coerce_int,
        "threshold": coerce_float,
        "feature_stride": coerce_int,
        "rpn_min_size": coerce_int,
        "output_score": coerce_bool,
    },
    defaults={
        "rpn_pre_nms_top_n": 6000,
        "rpn_post_nms_top_n": 300,
        "threshold": 0.7,
        "feature_stride": 16,
        "rpn_min_size": 16,
        "scales": (4.0, 8.0, 16.0, 32.0),
        "ratios": (0.5, 1.0, 2.0),
        "output_score": False,
    },
    aliases=("_contrib_Proposal",),
    no_grad_inputs=("cls_prob", "bbox_pred", "im_info"),
)
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, feature_stride=16,
             rpn_min_size=16, scales=(4.0, 8.0, 16.0, 32.0),
             ratios=(0.5, 1.0, 2.0), output_score=False):
    """RPN proposals (reference contrib/proposal-inl.h). cls_prob
    (B, 2*K, H, W); bbox_pred (B, 4*K, H, W); im_info (B, 3)
    [height, width, scale]. Output (B*post_nms, 5)
    [batch_idx, x1, y1, x2, y2]."""
    if isinstance(scales, str):
        scales = tuple(float(x) for x in scales.strip("()[]").split(","))
    if isinstance(ratios, str):
        ratios = tuple(float(x) for x in ratios.strip("()[]").split(","))
    b, twok, h, w = cls_prob.shape
    k = twok // 2
    base = float(feature_stride)
    # anchors at each feature cell (pixel coords)
    whs = []
    for r in ratios:
        for s in scales:
            size = base * base
            ws_ = jnp.sqrt(size / r) * s / base
            hs_ = ws_ * r
            whs.append((ws_ * base, hs_ * base))
    whs = jnp.asarray(whs, jnp.float32)[: k]
    cy = (jnp.arange(h) + 0.5) * base
    cx = (jnp.arange(w) + 0.5) * base
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([gx, gy], -1).reshape(-1, 2)  # (HW, 2)
    cs = jnp.repeat(centers, whs.shape[0], axis=0)
    ws2 = jnp.tile(whs, (centers.shape[0], 1))
    anchors = jnp.concatenate(
        [cs - ws2 / 2, cs + ws2 / 2], axis=-1
    )  # (HW*K, 4)
    num = anchors.shape[0]
    topk = min(rpn_post_nms_top_n, num)

    def one_batch(bi, cp, bp, info):
        fg = cp[k:, :, :].transpose(1, 2, 0).reshape(-1)  # (HWK,)
        deltas = (
            bp.reshape(k, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        )
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        cx_ = deltas[:, 0] * aw + acx
        cy_ = deltas[:, 1] * ah + acy
        w_ = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * aw
        h_ = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ah
        boxes = jnp.stack(
            [cx_ - w_ / 2, cy_ - h_ / 2, cx_ + w_ / 2, cy_ + h_ / 2],
            -1,
        )
        boxes = jnp.stack(
            [
                jnp.clip(boxes[:, 0], 0, info[1] - 1),
                jnp.clip(boxes[:, 1], 0, info[0] - 1),
                jnp.clip(boxes[:, 2], 0, info[1] - 1),
                jnp.clip(boxes[:, 3], 0, info[0] - 1),
            ],
            -1,
        )
        min_size = rpn_min_size * info[2]
        keep_size = (
            (boxes[:, 2] - boxes[:, 0] + 1 >= min_size)
            & (boxes[:, 3] - boxes[:, 1] + 1 >= min_size)
        )
        fg = jnp.where(keep_size, fg, -1.0)
        order = jnp.argsort(-fg)[: min(rpn_pre_nms_top_n, num)]
        boxes_s = boxes[order]
        fg_s = fg[order]
        keep = _nms_loop(
            boxes_s, jnp.maximum(fg_s, 0.0),
            jnp.zeros_like(fg_s, jnp.int32), threshold, True
        )
        score_for_rank = jnp.where(keep, fg_s, -jnp.inf)
        sel = jnp.argsort(-score_for_rank)[:topk]
        out_boxes = boxes_s[sel]
        out_scores = jnp.where(keep[sel], fg_s[sel], 0.0)
        out_boxes = out_boxes * keep[sel][:, None]
        rois = jnp.concatenate(
            [jnp.full((topk, 1), bi, jnp.float32), out_boxes], -1
        )
        return rois, out_scores[:, None]

    rois, scores = jax.vmap(one_batch)(
        jnp.arange(b, dtype=jnp.float32), cls_prob, bbox_pred, im_info
    )
    rois = rois.reshape(b * topk, 5)
    scores = scores.reshape(b * topk, 1)
    if output_score:
        return rois, scores
    return rois


# --------------------------------------------------------------------- fft


@register(
    "fft",
    arg_names=["data"],
    coerce={"compute_size": coerce_int},
    defaults={"compute_size": 128},
    aliases=("_contrib_fft",),
)
def fft(data, compute_size=128):
    """FFT along the last axis; complex output packed as interleaved
    [re, im] (reference contrib/fft-inl.h output layout: last dim
    doubled)."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    re = jnp.real(out)
    im = jnp.imag(out)
    packed = jnp.stack([re, im], axis=-1)
    return packed.reshape(*data.shape[:-1], data.shape[-1] * 2) \
        .astype(jnp.float32)


@register(
    "ifft",
    arg_names=["data"],
    coerce={"compute_size": coerce_int},
    defaults={"compute_size": 128},
    aliases=("_contrib_ifft",),
)
def ifft(data, compute_size=128):
    """Inverse of `fft`: interleaved [re, im] input, real output
    scaled by n (matching cuFFT's unnormalized inverse, which the
    reference exposes)."""
    n = data.shape[-1] // 2
    unpacked = data.reshape(*data.shape[:-1], n, 2)
    comp = unpacked[..., 0] + 1j * unpacked[..., 1]
    out = jnp.fft.ifft(comp, axis=-1)
    return (jnp.real(out) * n).astype(jnp.float32)


# ------------------------------------------------------------ count sketch


@register(
    "count_sketch",
    arg_names=["data", "h", "s"],
    coerce={"out_dim": coerce_int},
    aliases=("_contrib_count_sketch",),
    no_grad_inputs=("h", "s"),
)
def count_sketch(data, h, s, out_dim):
    """Count sketch projection (reference contrib/count_sketch-inl.h):
    out[:, h[i]] += s[i] * data[:, i]. h (1, in_dim) int hash bucket,
    s (1, in_dim) ±1 signs."""
    n, in_dim = data.shape
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1)
    vals = data * ss[None, :]
    out = jnp.zeros((n, out_dim), data.dtype)
    return out.at[:, hh].add(vals)


# ----------------------------------------------------------- quantization


@register(
    "quantize",
    arg_names=["data", "min_range", "max_range"],
    num_outputs=3,
    defaults={"out_type": "uint8"},
    aliases=("_contrib_quantize",),
    no_grad_inputs=("data", "min_range", "max_range"),
)
def quantize(data, min_range, max_range, out_type="uint8"):
    """Affine-quantize float32 -> uint8 (reference
    contrib/quantize-inl.h). Returns (quantized, min, max)."""
    if out_type != "uint8":
        raise MXNetError("quantize supports out_type='uint8'")
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    scale = 255.0 / jnp.maximum(mx - mn, 1e-8)
    q = jnp.clip(
        jnp.round((data - mn) * scale), 0, 255
    ).astype(jnp.uint8)
    return q, mn.reshape(1), mx.reshape(1)


@register(
    "dequantize",
    arg_names=["data", "min_range", "max_range"],
    defaults={"out_type": "float32"},
    aliases=("_contrib_dequantize",),
    no_grad_inputs=("data", "min_range", "max_range"),
)
def dequantize(data, min_range, max_range, out_type="float32"):
    mn = min_range.reshape(())
    mx = max_range.reshape(())
    scale = jnp.maximum(mx - mn, 1e-8) / 255.0
    return data.astype(jnp.float32) * scale + mn

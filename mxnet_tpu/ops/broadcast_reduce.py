"""Reductions and broadcasting ops.

Covers reference src/operator/tensor/broadcast_reduce_op.{h,cc,cu} (sum,
mean, prod, max, min, argmax, argmin, norm, broadcast_to/axis). The
reference hand-writes tiled reduction kernels
(broadcast_reduce-inl.{h,cuh}); on TPU these lower to XLA `reduce`, which
tiles onto the VPU natively.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register
from ..base import coerce_bool, coerce_int, coerce_tuple


def _norm_axis(axis, ndim, exclude=False):
    if axis is None or axis == () or axis == "":
        ax = None
    elif isinstance(axis, int):
        ax = (axis % ndim,)
    else:
        ax = tuple(a % ndim for a in axis)
    if exclude:
        keep = set(ax or ())
        ax = tuple(i for i in range(ndim) if i not in keep)
    return ax


_REDUCE_COERCE = {
    "axis": lambda v: None if v in (None, "None", "") else coerce_tuple(v),
    "keepdims": coerce_bool,
    "exclude": coerce_bool,
}


def _reduce(name, fn, aliases=()):
    @register(name, arg_names=["data"], coerce=_REDUCE_COERCE, aliases=aliases)
    def _impl(data, axis=None, keepdims=False, exclude=False, _fn=fn):
        ax = _norm_axis(axis, data.ndim, exclude)
        return _fn(data, axis=ax, keepdims=keepdims)


_reduce("sum", jnp.sum, aliases=("sum_axis",))
_reduce("mean", jnp.mean)
_reduce("prod", jnp.prod)
_reduce("nansum", jnp.nansum)
_reduce("nanprod", jnp.nanprod)
_reduce("max", jnp.max, aliases=("max_axis",))
_reduce("min", jnp.min, aliases=("min_axis",))


@register("norm", arg_names=["data"])
def norm(data):
    # Reference norm is the flat L2 norm returning shape (1,)
    # (broadcast_reduce_op.h L2 norm registration).
    return jnp.sqrt(jnp.sum(jnp.square(data))).reshape((1,))


_ARG_COERCE = {
    "axis": lambda v: None if v in (None, "None", "") else coerce_int(v),
    "keepdims": coerce_bool,
}


@register("argmax", arg_names=["data"], coerce=_ARG_COERCE)
def argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmin", arg_names=["data"], coerce=_ARG_COERCE)
def argmin(data, axis=None, keepdims=False):
    out = jnp.argmin(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)


@register("argmax_channel", arg_names=["data"])
def argmax_channel(data):
    return jnp.argmax(data, axis=-1).astype(jnp.float32)


@register(
    "broadcast_to",
    arg_names=["data"],
    coerce={"shape": lambda v: coerce_tuple(v)},
)
def broadcast_to(data, shape=()):
    # Zeros in target shape mean "keep source dim" (matrix_op-inl.h).
    tgt = tuple(
        s if s != 0 else data.shape[i] for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(data, tgt)


@register(
    "broadcast_axis",
    arg_names=["data"],
    coerce={"axis": coerce_tuple, "size": coerce_tuple},
    aliases=("broadcast_axes",),
)
def broadcast_axis(data, axis=(), size=()):
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a % data.ndim] = s
    return jnp.broadcast_to(data, tuple(tgt))

"""Elementwise unary/binary ops (+scalar, +logic, +broadcast variants).

Covers the reference's src/operator/tensor/elemwise_*op*.{h,cc,cu} and the
scalar functor zoo in src/operator/mshadow_op.h. One jax expression per
op; XLA fuses chains of these into single kernels, which replaces the
reference's Kernel<OP,xpu>::Launch machinery (src/operator/mxnet_op.h).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import coerce_bool, coerce_float

# ---------------------------------------------------------------- unary


def _unary(name, fn, aliases=()):
    register(name, arg_names=["data"], aliases=aliases)(
        lambda data, _fn=fn: _fn(data)
    )


_unary("relu", lambda x: jnp.maximum(x, 0), aliases=("Relu",))
_unary("sigmoid", jax.nn.sigmoid)
_unary("tanh", jnp.tanh)
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log2", jnp.log2)
_unary("log10", jnp.log10)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: jax.lax.rsqrt(x))
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("square", jnp.square)
_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("round", jnp.round)
_unary("rint", jnp.rint)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("fix", jnp.trunc)
_unary("trunc", jnp.trunc)
_unary("negative", jnp.negative)
_unary("reciprocal", jnp.reciprocal)
_unary("gamma", lambda x: jnp.exp(jax.lax.lgamma(x)))
_unary("gammaln", jax.lax.lgamma)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("softsign", jax.nn.soft_sign)
_unary("erf", jax.lax.erf)
_unary("erfinv", jax.lax.erf_inv)
_unary("logical_not", lambda x: (x == 0).astype(x.dtype))
_unary("_copy", lambda x: x)
_unary("identity", lambda x: x)


@register("_identity_with_attr_like_rhs", arg_names=["lhs", "rhs"])
def _identity_with_attr_like_rhs(lhs, rhs):
    return lhs


@register("cast", arg_names=["data"], aliases=("Cast",))
def cast(data, dtype="float32"):
    return data.astype(jnp.dtype(dtype))


@register(
    "BlockGrad", arg_names=["data"], aliases=("stop_gradient", "block_grad")
)
def block_grad(data):
    return jax.lax.stop_gradient(data)


# ---------------------------------------------------------------- binary
# Reference elemwise binary ops require identical shapes
# (elemwise_op_common.h); jax broadcasting is a strict superset, which the
# Python frontend historically allowed via broadcast_* anyway.


def _binary(name, fn, aliases=()):
    register(name, arg_names=["lhs", "rhs"], aliases=aliases)(
        lambda lhs, rhs, _fn=fn: _fn(lhs, rhs)
    )


_binary("elemwise_add", jnp.add, aliases=("_plus", "_Plus"))
_binary("elemwise_sub", jnp.subtract, aliases=("_minus", "_Minus"))
_binary("elemwise_mul", jnp.multiply, aliases=("_mul", "_Mul"))
_binary("elemwise_div", jnp.divide, aliases=("_div", "_Div"))
_binary("_power", jnp.power, aliases=("_Power", "pow"))
_binary("_maximum", jnp.maximum, aliases=("_Maximum",))
_binary("_minimum", jnp.minimum, aliases=("_Minimum",))
_binary("_mod", jnp.mod, aliases=("_Mod",))
_binary("_hypot", jnp.hypot, aliases=("_Hypot",))


def _logic(name, fn, aliases=()):
    # Reference logic ops return same-dtype 0/1 tensors (mshadow_op.h).
    register(name, arg_names=["lhs", "rhs"], aliases=aliases)(
        lambda lhs, rhs, _fn=fn: _fn(lhs, rhs).astype(
            jnp.result_type(lhs, rhs)
        )
    )


_logic("_equal", jnp.equal, aliases=("_Equal",))
_logic("_not_equal", jnp.not_equal, aliases=("_Not_Equal",))
_logic("_greater", jnp.greater, aliases=("_Greater",))
_logic("_greater_equal", jnp.greater_equal, aliases=("_Greater_Equal",))
_logic("_lesser", jnp.less, aliases=("_Lesser",))
_logic("_lesser_equal", jnp.less_equal, aliases=("_Lesser_Equal",))

# ------------------------------------------------------- broadcast binary

for _name, _fn in [
    ("broadcast_add", jnp.add),
    ("broadcast_sub", jnp.subtract),
    ("broadcast_mul", jnp.multiply),
    ("broadcast_div", jnp.divide),
    ("broadcast_power", jnp.power),
    ("broadcast_maximum", jnp.maximum),
    ("broadcast_minimum", jnp.minimum),
    ("broadcast_mod", jnp.mod),
    ("broadcast_hypot", jnp.hypot),
]:
    _binary(_name, _fn)

for _name, _fn in [
    ("broadcast_equal", jnp.equal),
    ("broadcast_not_equal", jnp.not_equal),
    ("broadcast_greater", jnp.greater),
    ("broadcast_greater_equal", jnp.greater_equal),
    ("broadcast_lesser", jnp.less),
    ("broadcast_lesser_equal", jnp.less_equal),
]:
    _logic(_name, _fn)

# --------------------------------------------------------------- scalar

_SCALAR_COERCE = {"scalar": coerce_float}


def _scalar_op(name, fn, aliases=()):
    register(
        name, arg_names=["data"], coerce=_SCALAR_COERCE, aliases=aliases
    )(lambda data, scalar=0.0, _fn=fn: _fn(data, scalar))


_scalar_op("_plus_scalar", lambda x, s: x + s, aliases=("_PlusScalar",))
_scalar_op("_minus_scalar", lambda x, s: x - s, aliases=("_MinusScalar",))
_scalar_op(
    "_rminus_scalar", lambda x, s: s - x, aliases=("_RMinusScalar",)
)
_scalar_op("_mul_scalar", lambda x, s: x * s, aliases=("_MulScalar",))
_scalar_op("_div_scalar", lambda x, s: x / s, aliases=("_DivScalar",))
_scalar_op("_rdiv_scalar", lambda x, s: s / x, aliases=("_RDivScalar",))
_scalar_op(
    "_power_scalar", lambda x, s: jnp.power(x, s), aliases=("_PowerScalar",)
)
_scalar_op(
    "_rpower_scalar",
    lambda x, s: jnp.power(s, x),
    aliases=("_RPowerScalar",),
)
_scalar_op(
    "_maximum_scalar",
    lambda x, s: jnp.maximum(x, s),
    aliases=("_MaximumScalar",),
)
_scalar_op(
    "_minimum_scalar",
    lambda x, s: jnp.minimum(x, s),
    aliases=("_MinimumScalar",),
)
_scalar_op("_mod_scalar", lambda x, s: jnp.mod(x, s), aliases=("_ModScalar",))
_scalar_op(
    "_rmod_scalar", lambda x, s: jnp.mod(s, x), aliases=("_RModScalar",)
)
_scalar_op(
    "_hypot_scalar",
    lambda x, s: jnp.hypot(x, s),
    aliases=("_HypotScalar",),
)


def _scalar_logic(name, fn, aliases=()):
    register(
        name, arg_names=["data"], coerce=_SCALAR_COERCE, aliases=aliases
    )(lambda data, scalar=0.0, _fn=fn: _fn(data, scalar).astype(data.dtype))


_scalar_logic("_equal_scalar", jnp.equal, aliases=("_EqualScalar",))
_scalar_logic(
    "_not_equal_scalar", jnp.not_equal, aliases=("_NotEqualScalar",)
)
_scalar_logic("_greater_scalar", jnp.greater, aliases=("_GreaterScalar",))
_scalar_logic(
    "_greater_equal_scalar",
    jnp.greater_equal,
    aliases=("_GreaterEqualScalar",),
)
_scalar_logic("_lesser_scalar", jnp.less, aliases=("_LesserScalar",))
_scalar_logic(
    "_lesser_equal_scalar", jnp.less_equal, aliases=("_LesserEqualScalar",)
)

# ------------------------------------------------------------- variadic


@register("add_n", aliases=("ElementWiseSum", "element_wise_sum"))
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register(
    "smooth_l1",
    arg_names=["data"],
    coerce=_SCALAR_COERCE,
    defaults={"scalar": 1.0},
)
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    absx = jnp.abs(data)
    return jnp.where(
        absx < 1.0 / s2, 0.5 * s2 * jnp.square(data), absx - 0.5 / s2
    )

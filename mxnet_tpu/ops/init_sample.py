"""Init ops (zeros/ones/arange) and random samplers.

Covers reference src/operator/tensor/init_op.{h,cc} and sample_op.{h,cc}.
Random ops consume an explicit jax PRNG key (`rng` kwarg threaded by the
imperative layer / executor) instead of the reference's per-device mshadow
Random resource (include/mxnet/resource.h kRandom) — counter-based PRNG is
the TPU-native idiom: reproducible across replicas and shardable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import coerce_float, coerce_int, coerce_tuple

_SHAPE_DTYPE = {
    "shape": coerce_tuple,
}


@register("_zeros", coerce=_SHAPE_DTYPE, defaults={"dtype": "float32"})
def _zeros(shape=(), dtype="float32", ctx=None):
    return jnp.zeros(shape, dtype=jnp.dtype(dtype))


@register("_ones", coerce=_SHAPE_DTYPE, defaults={"dtype": "float32"})
def _ones(shape=(), dtype="float32", ctx=None):
    return jnp.ones(shape, dtype=jnp.dtype(dtype))


@register(
    "_full",
    coerce={"shape": coerce_tuple, "value": coerce_float},
    defaults={"dtype": "float32"},
)
def _full(shape=(), value=0.0, dtype="float32", ctx=None):
    return jnp.full(shape, value, dtype=jnp.dtype(dtype))


@register(
    "_arange",
    coerce={
        "start": coerce_float,
        "stop": lambda v: None if v in (None, "None", "") else float(v),
        "step": coerce_float,
        "repeat": coerce_int,
    },
    defaults={"start": 0.0, "stop": None, "step": 1.0, "repeat": 1,
              "dtype": "float32"},
)
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32",
            ctx=None, infer_range=False):
    if stop is None:
        start, stop = 0.0, start
    out = jnp.arange(start, stop, step, dtype=jnp.dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


def _coerce_const_value(v):
    # Serialized graphs carry the value as its repr (nested tuples of
    # numbers); live graphs pass python lists/scalars straight through.
    if isinstance(v, str):
        import ast

        return ast.literal_eval(v)
    return v


@register(
    "_graph_constant",
    coerce={"value": _coerce_const_value},
    defaults={"dtype": "float32"},
)
def _graph_constant(value=0.0, dtype="float32", ctx=None):
    """Materialized result of constant folding (passes.fold): holds the
    folded subgraph's value as nested python lists so it survives the
    tojson/loads round-trip. Never constructed by user code."""
    return jnp.asarray(value, dtype=jnp.dtype(dtype))


@register(
    "ones_like",
    arg_names=["data"],
    no_grad_inputs=("data",),
)
def ones_like(data):
    return jnp.ones_like(data)


@register(
    "zeros_like",
    arg_names=["data"],
    no_grad_inputs=("data",),
)
def zeros_like(data):
    return jnp.zeros_like(data)


# ------------------------------------------------------------- samplers

_SAMPLE_COERCE = {
    "shape": coerce_tuple,
    "low": coerce_float,
    "high": coerce_float,
    "loc": coerce_float,
    "scale": coerce_float,
    "lam": coerce_float,
    "alpha": coerce_float,
    "beta": coerce_float,
    "k": coerce_float,
    "p": coerce_float,
    "mu": coerce_float,
    "sigma": coerce_float,
}


def _sample(name, aliases=()):
    def deco(fn):
        return register(
            name,
            coerce=_SAMPLE_COERCE,
            defaults={"dtype": "float32"},
            needs_rng=True,
            aliases=aliases,
        )(fn)

    return deco


@_sample("_random_uniform", aliases=("_sample_uniform", "uniform"))
def random_uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None,
                   rng=None):
    return jax.random.uniform(
        rng, shape, jnp.dtype(dtype), minval=low, maxval=high
    )


@_sample("_random_normal", aliases=("_sample_normal", "normal"))
def random_normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None,
                  rng=None, mu=None, sigma=None):
    if mu is not None:
        loc = mu
    if sigma is not None:
        scale = sigma
    return loc + scale * jax.random.normal(rng, shape, jnp.dtype(dtype))


@_sample("_random_gamma", aliases=("_sample_gamma",))
def random_gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None,
                 rng=None):
    return beta * jax.random.gamma(rng, alpha, shape, jnp.dtype(dtype))


@_sample("_random_exponential", aliases=("_sample_exponential",))
def random_exponential(lam=1.0, shape=(), dtype="float32", ctx=None,
                       rng=None):
    return jax.random.exponential(rng, shape, jnp.dtype(dtype)) / lam


@_sample("_random_poisson", aliases=("_sample_poisson",))
def random_poisson(lam=1.0, shape=(), dtype="float32", ctx=None, rng=None):
    return jax.random.poisson(rng, lam, shape).astype(jnp.dtype(dtype))


@_sample(
    "_random_negative_binomial", aliases=("_sample_negative_binomial",)
)
def random_negative_binomial(k=1.0, p=1.0, shape=(), dtype="float32",
                             ctx=None, rng=None):
    # NB(k, p) == Poisson(Gamma(k, (1-p)/p)) (sample_op.h semantics)
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, shape) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam, shape).astype(jnp.dtype(dtype))


@_sample(
    "_random_generalized_negative_binomial",
    aliases=("_sample_generalized_negative_binomial",),
)
def random_gen_negative_binomial(mu=1.0, alpha=1.0, shape=(),
                                 dtype="float32", ctx=None, rng=None):
    k = 1.0 / alpha
    p = k / (k + mu)
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, shape) * ((1.0 - p) / p)
    return jax.random.poisson(k2, lam, shape).astype(jnp.dtype(dtype))

"""Single operator registry.

The reference has TWO registries (legacy `OperatorProperty`,
include/mxnet/operator.h:166, bridged by src/nnvm/legacy_op_util.cc, plus
new-style `NNVM_REGISTER_OP` FCompute ops, include/mxnet/op_attr_types.h).
The TPU-native design collapses them into one: an op is a **pure jax
function** plus metadata. Shape/type inference is NOT hand-written per op
(the reference's InferShape/InferType attributes) — it falls out of
`jax.eval_shape` abstract evaluation, and gradients fall out of `jax.vjp`
instead of per-op Backward kernels. Ops whose reference semantics differ
from the mathematical vjp (SoftmaxOutput, MakeLoss, BlockGrad, ...) wrap
their fn in `jax.custom_vjp`.

Conventions for the registered fn:
  fn(*inputs, **params) -> jax.Array | tuple[jax.Array, ...]
  - `params` are already-coerced python values (see `coerce` map).
  - ops with `needs_rng` receive a `rng` kwarg (jax PRNG key).
  - ops with `needs_mode` receive an `is_train` kwarg (python bool --
    static under jit; executors trace train/eval variants separately).
  - ops with `aux_names` take the aux arrays as trailing inputs and,
    when `is_train=True`, return extra trailing outputs: the updated aux
    values (the functional replacement for the reference's mutable
    aux_states, e.g. BatchNorm moving mean/var).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from ..base import MXNetError


@dataclass
class OpDef:
    name: str
    fn: Callable
    num_outputs: int = 1
    # Named inputs for Symbol composition (e.g. ['data','weight','bias']).
    # None => variadic (*args), e.g. Concat / add_n.
    arg_names: Optional[Sequence[str]] = None
    aux_names: Sequence[str] = ()
    coerce: dict = field(default_factory=dict)
    defaults: dict = field(default_factory=dict)
    needs_rng: bool = False
    needs_mode: bool = False
    # Aliases under which the op is also exposed (reference registers many
    # ops under both CamelCase and snake_case names).
    aliases: Sequence[str] = ()
    # Which num_outputs to expose when params are known (e.g. SliceChannel's
    # num_outputs depends on its params); callable(params)->int.
    num_outputs_fn: Optional[Callable] = None
    # Param-dependent input names (e.g. Custom's depend on op_type);
    # callable(params)->list[str]. Overrides arg_names when set.
    arg_names_fn: Optional[Callable] = None
    # Optional list of input names whose gradient is always zero
    # (e.g. labels); purely informational for executors.
    no_grad_inputs: Sequence[str] = ()

    def resolved_num_outputs(self, params) -> int:
        if self.num_outputs_fn is not None:
            return self.num_outputs_fn(params)
        return self.num_outputs

    def normalize_params(self, kwargs: dict) -> dict:
        out = dict(self.defaults)
        for k, v in kwargs.items():
            if v is None:
                continue
            fn = self.coerce.get(k)
            out[k] = fn(v) if fn is not None else v
        return out


_REGISTRY: dict[str, OpDef] = {}


def register(
    name,
    num_outputs=1,
    arg_names=None,
    aux_names=(),
    coerce=None,
    defaults=None,
    needs_rng=False,
    needs_mode=False,
    aliases=(),
    num_outputs_fn=None,
    no_grad_inputs=(),
    arg_names_fn=None,
):
    """Decorator registering a jax function as a framework op."""

    def deco(fn):
        op = OpDef(
            name=name,
            fn=fn,
            num_outputs=num_outputs,
            arg_names=arg_names,
            aux_names=tuple(aux_names),
            coerce=coerce or {},
            defaults=defaults or {},
            needs_rng=needs_rng,
            needs_mode=needs_mode,
            aliases=tuple(aliases),
            num_outputs_fn=num_outputs_fn,
            no_grad_inputs=tuple(no_grad_inputs),
            arg_names_fn=arg_names_fn,
        )
        if name in _REGISTRY:
            raise MXNetError(f"op {name!r} registered twice")
        _REGISTRY[name] = op
        for alias in op.aliases:
            if alias in _REGISTRY:
                raise MXNetError(f"op alias {alias!r} registered twice")
            _REGISTRY[alias] = op
        return fn

    return deco


def get(name: str) -> OpDef:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise MXNetError(f"unknown op {name!r}") from None


def exists(name: str) -> bool:
    return name in _REGISTRY


def list_ops() -> list[str]:
    return sorted(_REGISTRY)


def canonical_ops() -> dict[str, OpDef]:
    """name -> OpDef for canonical names only (aliases collapsed)."""
    return {name: op for name, op in _REGISTRY.items() if op.name == name}

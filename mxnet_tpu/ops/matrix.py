"""Matrix/shape-manipulation ops.

Covers reference src/operator/tensor/matrix_op-inl.h (1733 LoC): dot,
batch_dot, transpose, reshape, flatten, slice, slice_axis, flip, clip,
repeat, tile, expand_dims, swapaxes, pad, crop. dot/batch_dot lower to
XLA dot_general — the MXU path; everything else is layout work XLA folds
into neighboring kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import MXNetError, coerce_bool, coerce_float, coerce_int, coerce_tuple

_TT = {"transpose_a": coerce_bool, "transpose_b": coerce_bool}


@register("dot", arg_names=["lhs", "rhs"], coerce=_TT)
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b).reshape((1,))
    # Reference dot contracts last axis of a with first of b for any rank
    # (matrix_op-inl.h DotForward).
    return jnp.tensordot(a, b, axes=([a.ndim - 1], [0]))


@register("batch_dot", arg_names=["lhs", "rhs"], coerce=_TT)
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


def _infer_reshape(shape, src_shape, reverse=False):
    """MXNet reshape semantics: 0 copies input dim, -1 infers, -2 copies
    all remaining, -3 merges two dims, -4 splits a dim
    (matrix_op-inl.h ReshapeInferShape)."""
    if reverse:
        src = list(reversed(src_shape))
        out = _infer_reshape(list(reversed(list(shape))), src, False)
        return tuple(reversed(out))
    src = list(src_shape)
    out = []
    src_idx = 0
    i = 0
    shape = list(shape)
    while i < len(shape):
        s = shape[i]
        if s > 0:
            out.append(s)
            src_idx += 1
        elif s == 0:
            out.append(src[src_idx])
            src_idx += 1
        elif s == -1:
            out.append(-1)
            src_idx += 1
        elif s == -2:
            out.extend(src[src_idx:])
            src_idx = len(src)
        elif s == -3:
            out.append(src[src_idx] * src[src_idx + 1])
            src_idx += 2
        elif s == -4:
            d1, d2 = shape[i + 1], shape[i + 2]
            cur = src[src_idx]
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2])
            src_idx += 1
            i += 2
        else:
            raise MXNetError(f"bad reshape token {s}")
        i += 1
    if out.count(-1) > 1:
        raise MXNetError("reshape can infer at most one dim")
    return tuple(out)


@register(
    "reshape",
    arg_names=["data"],
    coerce={"shape": coerce_tuple, "reverse": coerce_bool},
    aliases=("Reshape",),
)
def reshape(data, shape=(), reverse=False, target_shape=None, keep_highest=False):
    if target_shape is not None and not shape:
        # legacy target_shape param (matrix_op-inl.h ReshapeParam)
        tgt = coerce_tuple(target_shape)
        if keep_highest:
            tgt = (data.shape[0],) + tuple(tgt)[1:]
        return jnp.reshape(data, tgt)
    out = _infer_reshape(shape, data.shape, reverse)
    return jnp.reshape(data, out)


@register("flatten", arg_names=["data"], aliases=("Flatten",))
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register(
    "transpose",
    arg_names=["data"],
    coerce={"axes": coerce_tuple},
)
def transpose(data, axes=()):
    return jnp.transpose(data, axes or None)


@register(
    "expand_dims", arg_names=["data"], coerce={"axis": coerce_int}
)
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register(
    "SwapAxis",
    arg_names=["data"],
    coerce={"dim1": coerce_int, "dim2": coerce_int},
    aliases=("swapaxes",),
)
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


def _coerce_idx_tuple(v):
    if v in (None, "None", ""):
        return None
    return coerce_tuple(
        v, typ=lambda x: None if str(x) in ("None", "") else int(x)
    )


@register(
    "slice",
    arg_names=["data"],
    coerce={"begin": _coerce_idx_tuple, "end": _coerce_idx_tuple},
    aliases=("crop",),
)
def slice_op(data, begin=(), end=()):
    idx = tuple(
        _slice(b, e)
        for b, e in zip(begin, end)
    )
    return data[idx]


def _slice(b, e):
    return slice(b, e)


@register(
    "slice_axis",
    arg_names=["data"],
    coerce={
        "axis": coerce_int,
        "begin": coerce_int,
        "end": lambda v: None if v in (None, "None", "") else coerce_int(v),
    },
)
def slice_axis(data, axis=0, begin=0, end=None):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


@register("flip", arg_names=["data"], coerce={"axis": coerce_tuple},
          aliases=("reverse",))
def flip(data, axis=()):
    return jnp.flip(data, axis)


@register(
    "clip",
    arg_names=["data"],
    coerce={"a_min": coerce_float, "a_max": coerce_float},
)
def clip(data, a_min=None, a_max=None):
    if a_min is None or a_max is None:
        # required dmlc params in the reference (matrix_op-inl.h ClipParam)
        raise MXNetError("clip requires both a_min and a_max")
    return jnp.clip(data, a_min, a_max)


@register(
    "repeat",
    arg_names=["data"],
    coerce={
        "repeats": coerce_int,
        "axis": lambda v: None if v in (None, "None", "") else coerce_int(v),
    },
)
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("tile", arg_names=["data"], coerce={"reps": coerce_tuple})
def tile(data, reps=()):
    return jnp.tile(data, reps)


@register(
    "Concat",
    coerce={"dim": coerce_int, "num_args": coerce_int},
    defaults={"dim": 1},
    aliases=("concat",),
)
def concat(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=dim)


@register(
    "SliceChannel",
    arg_names=["data"],
    coerce={
        "num_outputs": coerce_int,
        "axis": coerce_int,
        "squeeze_axis": coerce_bool,
    },
    defaults={"axis": 1, "squeeze_axis": False},
    aliases=("slice_channel", "split"),
    num_outputs_fn=lambda p: int(p.get("num_outputs", 1)),
)
def slice_channel(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register(
    "stack",
    coerce={"axis": coerce_int, "num_args": coerce_int},
    defaults={"axis": 0},
)
def stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=axis)


@register(
    "Pad",
    arg_names=["data"],
    coerce={
        "pad_width": coerce_tuple,
        "constant_value": coerce_float,
    },
    defaults={"mode": "constant", "constant_value": 0.0},
    aliases=("pad",),
)
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = [
        (pad_width[2 * i], pad_width[2 * i + 1]) for i in range(data.ndim)
    ]
    if mode == "constant":
        return jnp.pad(data, pw, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(data, pw, mode="edge")
    if mode == "reflect":
        return jnp.pad(data, pw, mode="reflect")
    raise MXNetError(f"unknown pad mode {mode!r}")


@register(
    "Crop",
    coerce={
        "num_args": coerce_int,
        "offset": coerce_tuple,
        "h_w": coerce_tuple,
        "center_crop": coerce_bool,
    },
    defaults={"offset": (0, 0), "h_w": (0, 0), "center_crop": False},
)
def crop_like(*args, num_args=None, offset=(0, 0), h_w=(0, 0), center_crop=False):
    """Crop op (src/operator/crop-inl.h): crop first input spatially to
    h_w, or to the size of a second reference input."""
    data = args[0]
    if len(args) == 2:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = h_w
    H, W = data.shape[2], data.shape[3]
    if center_crop:
        y0, x0 = (H - th) // 2, (W - tw) // 2
    else:
        y0, x0 = offset
    return data[:, :, y0 : y0 + th, x0 : x0 + tw]

"""Parallelism-aware ops: sequence-parallel attention and expert-parallel
MoE as first-class registered operators.

The reference exposed model parallelism only through ctx-group placement
(example/model-parallel-lstm/lstm.py:48-99 + PlaceDevice); here the
TPU-native equivalents are ordinary Symbol ops. Each op reads the
ambient device mesh (parallel/mesh.py) at trace time:

  - mesh has the op's axis and size > 1  -> sharded implementation
    (ring / Ulysses all-to-all attention, expert all-to-all dispatch)
    via shard_map; XLA lowers the ppermute/all-to-all onto ICI.
  - otherwise -> mathematically identical single-device fallback, so
    the same Symbol runs unmodified on one chip, in eager executors,
    and in shape inference.

The FusedTrainStep installs the Module's mesh as ambient for the trace
of its step, so `Module(..., mesh_shape={'data': 2, 'seq': 4})` + these
ops is the complete user-facing SP/EP story.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register
from ..base import coerce_bool, coerce_float, coerce_int


def _ambient_mesh(axis_name):
    from ..parallel import mesh as mesh_mod

    m = mesh_mod.current_mesh()
    if m is not None and axis_name in m.axis_names \
            and m.shape[axis_name] > 1:
        return m
    return None


def _plain_attention(q, k, v, causal, scale):
    """Reference attention math for the single-device fallback; (B, T,
    H, D) layout, numerically the target the ring/Ulysses paths match
    (tests/test_attention.py)."""
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool))
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


@register(
    "RingAttention",
    arg_names=["query", "key", "value"],
    coerce={"causal": coerce_bool, "scale": coerce_float},
    defaults={"causal": False, "impl": "ring", "axis_name": "seq"},
    aliases=("ring_attention",),
)
def ring_attention_op(query, key, value, causal=False, impl="ring",
                      axis_name="seq", scale=None):
    """Sequence-parallel attention over (B, T, H, D) inputs.

    impl='ring': blockwise ring attention (K/V rotate over the mesh
    axis via ppermute — parallel/ring_attention.py).
    impl='ulysses': head-scatter/seq-gather all-to-all attention.
    Without a mesh (or axis size 1) both reduce to plain attention.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(query.shape[-1])
    m = _ambient_mesh(axis_name)
    if m is None:
        return _plain_attention(query, key, value, causal, scale)
    from ..parallel.ring_attention import ring_attention, ulysses_attention

    fn = ulysses_attention if impl == "ulysses" else ring_attention
    return fn(query, key, value, mesh=m, axis_name=axis_name,
              causal=causal, scale=scale)


@register(
    "MoEFFN",
    arg_names=["data", "gate_weight", "w1_weight", "w2_weight"],
    coerce={"num_experts": coerce_int, "hidden_size": coerce_int,
            "capacity_factor": coerce_float},
    defaults={"capacity_factor": 1.25, "axis_name": "expert"},
    num_outputs=2,
    aliases=("moe_ffn",),
)
def moe_ffn_op(data, gate_weight, w1_weight, w2_weight, num_experts=0,
               hidden_size=0, capacity_factor=1.25, axis_name="expert"):
    """Top-1-routed mixture-of-experts FFN over (..., D) tokens.

    Outputs: (transformed tokens, load-balancing aux loss). With an
    ambient mesh carrying `axis_name`, expert weights and dispatched
    token blocks shard over it (parallel/moe.py) — the dispatch einsum
    becomes the token-routing all-to-all on ICI.
    """
    from ..parallel.moe import moe_ffn

    lead = data.shape[:-1]
    x = data.reshape((-1, data.shape[-1]))
    out, aux = moe_ffn(
        x, gate_weight, w1_weight, w2_weight,
        capacity_factor=capacity_factor, mesh=_ambient_mesh(axis_name),
        axis_name=axis_name,
    )
    return out.reshape(lead + (data.shape[-1],)), aux

"""Forward shape/type inference for symbolic binding.

Analog of the reference's InferShape pass (nnvm) + per-op InferShape
attributes (src/operator/operator_common.h macros). TPU-native twist: only
ops that *create* parameter shapes (FullyConnected infers its weight from
the data shape, etc.) need hand-written rules; every other op's output
shape falls out of `jax.eval_shape` abstract evaluation of its registered
jax function — no per-op shape code.

An infer rule has signature
    fn(params, in_shapes) -> (in_shapes, out_shapes)
where `in_shapes` is a list of tuples-or-None (None = unknown, to be
inferred); the returned in_shapes must be fully known. Inputs include
trailing aux states for ops that have them.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from . import registry as _registry

_RULES: dict[str, callable] = {}


def rule(name):
    def deco(fn):
        _RULES[name] = fn
        return fn

    return deco


def _prod(t):
    p = 1
    for x in t:
        p *= x
    return p


def infer_node(opdef, params, in_shapes, in_dtypes):
    """Infer (in_shapes, out_shapes, out_dtypes) for one node.

    Raises MXNetError if inference is impossible with the known inputs.
    """
    r = _RULES.get(opdef.name)
    if r is not None:
        in_shapes, _ = r(params, list(in_shapes))
    if any(s is None for s in in_shapes):
        missing = [i for i, s in enumerate(in_shapes) if s is None]
        raise MXNetError(
            f"op {opdef.name!r}: cannot infer shapes of inputs {missing}"
        )
    # abstract-eval the registered fn for output shapes/dtypes
    kwargs = dict(params)
    structs = [
        jax.ShapeDtypeStruct(s, d or np.float32)
        for s, d in zip(in_shapes, in_dtypes)
    ]

    def f(*xs):
        extra = {}
        if opdef.needs_rng:
            extra["rng"] = jax.random.PRNGKey(0)
        if opdef.needs_mode:
            extra["is_train"] = False
        res = opdef.fn(*xs, **kwargs, **extra)
        return res

    out = jax.eval_shape(f, *structs)
    if not isinstance(out, (tuple, list)):
        out = (out,)
    n_out = opdef.resolved_num_outputs(params)
    out = tuple(out)[:n_out]
    return (
        [tuple(s) for s in in_shapes],
        [tuple(o.shape) for o in out],
        [np.dtype(o.dtype) for o in out],
    )


# --------------------------------------------------- parameter-creating ops


@rule("FullyConnected")
def _fc(params, ins):
    data, weight, bias = (ins + [None] * 3)[:3]
    nh = int(params["num_hidden"])
    no_bias = params.get("no_bias", False)
    if data is not None:
        d = (
            _prod(data[1:])
            if params.get("flatten", True)
            else data[-1]
        )
        if weight is None:
            weight = (nh, d)
    if weight is None:
        raise MXNetError("FullyConnected: cannot infer weight shape")
    if no_bias:
        return [data, weight], None
    if bias is None:
        bias = (nh,)
    return [data, weight, bias], None


@rule("Convolution")
def _conv(params, ins):
    data, weight, bias = (ins + [None] * 3)[:3]
    nf = int(params["num_filter"])
    ng = int(params.get("num_group", 1))
    kernel = tuple(params["kernel"])
    no_bias = params.get("no_bias", False)
    layout = str(params.get("layout") or "")
    channels_last = layout.upper().endswith("C")
    if data is not None and weight is None:
        if channels_last:  # e.g. NHWC -> OHWI weights
            weight = (nf,) + kernel + (data[-1] // ng,)
        else:
            weight = (nf, data[1] // ng) + kernel
    if no_bias:
        return [data, weight], None
    if bias is None:
        bias = (nf,)
    return [data, weight, bias], None


@rule("Deconvolution")
def _deconv(params, ins):
    data, weight, bias = (ins + [None] * 3)[:3]
    nf = int(params["num_filter"])
    ng = int(params.get("num_group", 1))
    kernel = tuple(params["kernel"])
    no_bias = params.get("no_bias", True)
    if data is not None and weight is None:
        weight = (data[1], nf // ng) + kernel
    if no_bias:
        return [data, weight], None
    if bias is None:
        bias = (nf,)
    return [data, weight, bias], None


@rule("BatchNorm")
def _bn(params, ins):
    data = ins[0]
    if data is None:
        raise MXNetError("BatchNorm: data shape required")
    c = (data[int(params.get("axis", 1)) % len(data)],)
    out = [data] + [s if s is not None else c for s in ins[1:]]
    while len(out) < 5:
        out.append(c)
    return out, None


@rule("InstanceNorm")
def _in(params, ins):
    data = ins[0]
    c = (data[1],)
    return [data, ins[1] or c, ins[2] if len(ins) > 2 and ins[2] else c], None


@rule("MoEFFN")
def _moe_ffn(params, ins):
    data, gate_w, w1, w2 = (ins + [None] * 4)[:4]
    e = int(params["num_experts"])
    f = int(params["hidden_size"])
    if data is not None:
        d = data[-1]
        if gate_w is None:
            gate_w = (d, e)
        if w1 is None:
            w1 = (e, d, f)
        if w2 is None:
            w2 = (e, f, d)
    return [data, gate_w, w1, w2], None


@rule("Embedding")
def _emb(params, ins):
    data, weight = (ins + [None] * 2)[:2]
    if weight is None:
        weight = (int(params["input_dim"]), int(params["output_dim"]))
    return [data, weight], None


@rule("LeakyReLU")
def _lrelu(params, ins):
    if params.get("act_type") == "prelu":
        data = ins[0]
        gamma = ins[1] if len(ins) > 1 and ins[1] else (data[1],)
        return [data, gamma], None
    return ins, None


def _label_rule(label_like_data=False):
    def fn(params, ins):
        data, label = (ins + [None] * 2)[:2]
        if data is not None and label is None:
            if label_like_data:
                # regression: label shaped like data, except (N,1)->(N,)
                label = (
                    (data[0],)
                    if len(data) == 2 and data[1] == 1
                    else data
                )
            else:
                if params.get("multi_output"):
                    label = (data[0],) + tuple(data[2:])
                elif params.get("preserve_shape"):
                    label = tuple(data[:-1])
                else:
                    label = (data[0],)
        return [data, label], None

    return fn


for _n in ("SoftmaxOutput", "SVMOutput"):
    _RULES[_n] = _label_rule(False)
for _n in (
    "LinearRegressionOutput",
    "MAERegressionOutput",
    "LogisticRegressionOutput",
):
    _RULES[_n] = _label_rule(True)


@rule("RNN")
def _rnn(params, ins):
    from .rnn_op import rnn_param_size

    mode = params["mode"]
    h = int(params["state_size"])
    num_layers = int(params.get("num_layers", 1))
    bidir = bool(params.get("bidirectional", False))
    dirs = 2 if bidir else 1
    data = ins[0]
    if data is None:
        raise MXNetError("RNN: data shape required")
    t, n, input_size = data
    size = rnn_param_size(input_size, h, num_layers, bidir, mode)
    out = [data, ins[1] or (size,), ins[2] or (num_layers * dirs, n, h)]
    if mode == "lstm":
        cell = ins[3] if len(ins) > 3 and ins[3] else (num_layers * dirs, n, h)
        out.append(cell)
    return out, None


@rule("softmax_cross_entropy")
def _sce(params, ins):
    data, label = (ins + [None] * 2)[:2]
    if data is not None and label is None:
        label = (data[0],)
    return [data, label], None

"""Neural-network layer ops.

Covers the reference's legacy layer-op tier (src/operator/*-inl.h):
FullyConnected, Convolution, Deconvolution, Pooling, BatchNorm, Dropout,
Activation, LeakyReLU, LRN, InstanceNorm, L2Normalization, softmax family,
loss/output ops, sequence ops. Design notes:

- Convs/matmuls lower to XLA `conv_general_dilated` / `dot_general`, the
  MXU path — no im2col (reference src/operator/nn/im2col.h) and no cuDNN
  algo registry (cudnn_algoreg-inl.h); XLA autotunes.
- Stateful aux (BatchNorm moving stats, reference batch_norm-inl.h) is
  functional: aux arrays in, updated aux out (see ops/registry.py).
- Output/loss ops (SoftmaxOutput, *RegressionOutput, MakeLoss) reproduce
  the reference's *custom backward semantics* — they ignore or replace the
  incoming head gradient — via jax.custom_vjp, so `Executor.backward()`
  with default head grads matches the reference bit-for-bit in structure.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from ..base import MXNetError, coerce_bool, coerce_float, coerce_int, coerce_tuple

# ------------------------------------------------------------ activation


@register(
    "Activation",
    arg_names=["data"],
    defaults={"act_type": "relu"},
    aliases=("activation",),
)
def activation(data, act_type="relu"):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    raise MXNetError(f"unknown act_type {act_type!r}")


@register(
    "LeakyReLU",
    arg_names=["data", "gamma"],
    defaults={"act_type": "leaky", "slope": 0.25,
              "lower_bound": 0.125, "upper_bound": 0.334},
    coerce={"slope": coerce_float, "lower_bound": coerce_float,
            "upper_bound": coerce_float},
    needs_rng=True,
    needs_mode=True,
)
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, rng=None,
               is_train=False):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data >= 0, data, g * data)
    if act_type == "rrelu":
        if is_train:
            s = jax.random.uniform(
                rng, data.shape, data.dtype, lower_bound, upper_bound
            )
        else:
            s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, s * data)
    raise MXNetError(f"unknown act_type {act_type!r}")


# PReLU variant takes gamma as a learned input; expose it through the same
# registered op — Symbol-level composition passes gamma when act_type=prelu.


# -------------------------------------------------------- fully connected


@register(
    "FullyConnected",
    arg_names=["data", "weight", "bias"],
    coerce={"num_hidden": coerce_int, "no_bias": coerce_bool,
            "flatten": coerce_bool},
    defaults={"no_bias": False, "flatten": True},
    aliases=("fully_connected",),
)
def fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False,
                    flatten=True):
    if flatten:
        x = data.reshape((data.shape[0], -1))
    else:
        x = data
    out = jnp.matmul(x, weight.T)
    if not no_bias and bias is not None:
        out = out + bias
    return out


# ------------------------------------------------------------ convolution


def _conv_dims(kernel):
    return len(kernel)


def _norm_layout(layout, nd):
    """Resolve a reference-style layout string ('NCHW', 'NHWC', 'NCDHW',
    'NDHWC', 'NCW', 'NWC'); default channel-first like the reference."""
    if not layout or layout in ("None",):
        return "NC" + "DHW"[3 - nd:]
    layout = str(layout).upper()
    if len(layout) != nd + 2 or "N" not in layout or "C" not in layout:
        raise MXNetError(f"bad conv layout {layout!r} for {nd}d")
    return layout


def _spatial_tuple(v, nd, default):
    t = coerce_tuple(v) if v not in (None, "", ()) else ()
    if not t:
        t = (default,) * nd
    if len(t) != nd:
        t = (t[0],) * nd
    return t


@register(
    "Convolution",
    arg_names=["data", "weight", "bias"],
    coerce={
        "kernel": coerce_tuple,
        "stride": coerce_tuple,
        "dilate": coerce_tuple,
        "pad": coerce_tuple,
        "num_filter": coerce_int,
        "num_group": coerce_int,
        "no_bias": coerce_bool,
        "workspace": coerce_int,
    },
    defaults={"num_group": 1, "no_bias": False},
    aliases=("convolution",),
)
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, no_bias=False,
                workspace=1024, cudnn_tune=None, cudnn_off=False,
                layout=None):
    """Convolution (reference src/operator/convolution-inl.h), any
    reference layout: NCHW (default) or channels-last NHWC/NDHWC/NWC.

    The reference lowers to im2col+GEMM (nn/im2col.h) or cuDNN; here a
    single lax.conv_general_dilated lowers straight onto the MXU. On TPU
    channels-last is the native orientation (C maps onto the 128-wide
    lane dimension), so NHWC graphs skip XLA's NCHW->NHWC relayout.
    Weight layout follows the reference convention: data layout with
    N->O, C->I (NCHW weights are OIHW, NHWC weights are OHWI).
    """
    nd = _conv_dims(kernel)
    stride = _spatial_tuple(stride, nd, 1)
    dilate = _spatial_tuple(dilate, nd, 1)
    pad = _spatial_tuple(pad, nd, 0)
    lay = _norm_layout(layout, nd)
    dn = lax.conv_dimension_numbers(
        data.shape,
        weight.shape,
        (lay, lay.replace("N", "O").replace("C", "I"), lay),
    )
    out = lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if not no_bias and bias is not None:
        c_ax = lay.index("C")
        out = out + bias.reshape(
            tuple(-1 if i == c_ax else 1 for i in range(nd + 2))
        )
    return out


@register(
    "Deconvolution",
    arg_names=["data", "weight", "bias"],
    coerce={
        "kernel": coerce_tuple,
        "stride": coerce_tuple,
        "dilate": coerce_tuple,
        "pad": coerce_tuple,
        "adj": coerce_tuple,
        "target_shape": coerce_tuple,
        "num_filter": coerce_int,
        "num_group": coerce_int,
        "no_bias": coerce_bool,
    },
    defaults={"num_group": 1, "no_bias": True},
)
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), target_shape=(), num_filter=0,
                  num_group=1, no_bias=True, workspace=512, cudnn_tune=None,
                  cudnn_off=False, layout=None):
    """Transposed convolution (reference src/operator/deconvolution-inl.h):
    the gradient of Convolution w.r.t. its input, expressed directly via
    lax.conv_transpose."""
    nd = _conv_dims(kernel)
    stride = _spatial_tuple(stride, nd, 1)
    dilate = _spatial_tuple(dilate, nd, 1)
    pad = _spatial_tuple(pad, nd, 0)
    adj = _spatial_tuple(adj, nd, 0) if adj else (0,) * nd
    spatial = "DHW"[3 - nd :]
    dn = lax.conv_dimension_numbers(
        data.shape,
        weight.shape,
        ("NC" + spatial, "IO" + spatial, "NC" + spatial),
    )
    # explicit padding matching the reference output formula:
    # out = (in-1)*stride - 2*pad + dilate*(kernel-1) + adj + 1
    out = lax.conv_transpose(
        data,
        weight,
        strides=stride,
        padding=[
            (d * (k - 1) - p, d * (k - 1) - p + a)
            for k, p, a, d in zip(kernel, pad, adj, dilate)
        ],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        transpose_kernel=False,
    )
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# --------------------------------------------------------------- pooling


@register(
    "Pooling",
    arg_names=["data"],
    coerce={
        "kernel": coerce_tuple,
        "stride": coerce_tuple,
        "pad": coerce_tuple,
        "global_pool": coerce_bool,
    },
    defaults={"pool_type": "max", "global_pool": False,
              "pooling_convention": "valid"},
    aliases=("pooling",),
)
def pooling(data, kernel=(), pool_type="max", global_pool=False,
            pooling_convention="valid", stride=(), pad=(), cudnn_off=False,
            layout=None):
    nd = data.ndim - 2
    lay = _norm_layout(layout, nd)
    sp_axes = [i for i, ch in enumerate(lay) if ch not in "NC"]
    if global_pool:
        kernel = tuple(data.shape[a] for a in sp_axes)
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = _spatial_tuple(kernel, nd, 1)
        stride = _spatial_tuple(stride, nd, 1)
        pad = _spatial_tuple(pad, nd, 0)

    window = [1] * (nd + 2)
    strides = [1] * (nd + 2)
    base_pad = [(0, 0)] * (nd + 2)
    for i, ax in enumerate(sp_axes):
        window[ax] = kernel[i]
        strides[ax] = stride[i]
        base_pad[ax] = (pad[i], pad[i])
    if pooling_convention == "full" and not global_pool:
        # ceil output convention (pooling-inl.h): pad extra on the right
        # so that ceil((in + 2p - k)/s) + 1 windows fit.
        import math

        for i, ax in enumerate(sp_axes):
            in_ = data.shape[ax]
            out_ = int(
                math.ceil((in_ + 2 * pad[i] - kernel[i]) / stride[i])
            ) + 1
            needed = (out_ - 1) * stride[i] + kernel[i] - in_ - pad[i]
            base_pad[ax] = (pad[i], max(needed, pad[i]))
    window = tuple(window)
    strides = tuple(strides)

    if pool_type == "max":
        init = -jnp.inf
        out = lax.reduce_window(
            data, init, lax.max, window, strides, base_pad
        )
        return out
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(
            data, 0.0, lax.add, window, strides, base_pad
        )
        if pool_type == "sum":
            return summed
        # reference avg-pool divides by the full kernel size, padding
        # included (pooling-inl.h pool_enum::kAvgPooling)
        denom = 1.0
        for k in kernel:
            denom *= k
        return summed / denom
    raise MXNetError(f"unknown pool_type {pool_type!r}")


# ------------------------------------------------------------- batchnorm


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_core(x, gamma, beta, axis, eps):
    """Training-mode BN with one-pass sufficient statistics and a
    hand-written backward. The HBM traffic budget is the whole game on
    TPU (the profile shows ResNet-50 is BN/elementwise-bound, not
    MXU-bound): forward reads x once for the fused (sum, sum-of-squares)
    sibling reduction and once for the normalize pass; backward reads
    (dy, x) once for the fused (sum dy, sum dy*xhat) pair and once for
    the dx pass — the minimum for a non-materializing BN. Stats
    accumulate in f32 regardless of the compute dtype.

    Returns (out, mean, var) with mean/var in f32.
    """
    (out, mean, var), _ = _bn_core_fwd(x, gamma, beta, axis, eps)
    return out, mean, var


def _bn_stats(x, axis):
    axes = tuple(i for i in range(x.ndim) if i != axis)
    n = x.size // x.shape[axis]
    # f32 ACCUMULATION of low-precision elements via the reduce dtype —
    # never a materialized f32 cast of x (a cast the fusion planner may
    # schedule as its own full HBM pass)
    s1 = jnp.sum(x, axis=axes, dtype=jnp.float32)
    s2 = jnp.sum(x * x, axis=axes, dtype=jnp.float32)
    mean = s1 / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    return mean, var, n


def _bn_core_fwd(x, gamma, beta, axis, eps):
    mean, var, _ = _bn_stats(x, axis)
    inv = lax.rsqrt(var + eps)
    bshape = tuple(
        x.shape[i] if i == axis else 1 for i in range(x.ndim))
    gf = gamma.astype(jnp.float32)
    # per-channel coefficients in f32 (C-sized, cheap); the big
    # elementwise pass stays in x.dtype end to end
    scale = (gf * inv).astype(x.dtype).reshape(bshape)
    shift = (beta.astype(jnp.float32) - mean * gf * inv).astype(
        x.dtype).reshape(bshape)
    out = x * scale + shift
    return (out, mean, var), (x, gamma, mean, inv)


def _bn_core_bwd(axis, eps, res, cts):
    dy, dmean_ct, dvar_ct = cts
    x, gamma, mean, inv = res
    axes = tuple(i for i in range(x.ndim) if i != axis)
    n = x.size // x.shape[axis]
    bshape = tuple(
        x.shape[i] if i == axis else 1 for i in range(x.ndim))
    dt = x.dtype
    mean_b = mean.astype(dt).reshape(bshape)
    inv_b = inv.astype(dt).reshape(bshape)
    xhat = (x - mean_b) * inv_b
    sum_dy = jnp.sum(dy, axis=axes, dtype=jnp.float32)
    sum_dy_xhat = jnp.sum(dy * xhat, axis=axes, dtype=jnp.float32)
    gf = gamma.astype(jnp.float32)
    c1 = (gf * inv).astype(dt).reshape(bshape)
    c2 = (sum_dy / n).astype(dt).reshape(bshape)
    c3 = (sum_dy_xhat / n).astype(dt).reshape(bshape)
    dx = c1 * (dy.astype(dt) - c2 - xhat * c3)
    # stat-output cotangents: literal zeros when the stats only feed the
    # (non-differentiated) moving-average update, so XLA folds these away
    dx = dx + (dmean_ct / n).astype(dt).reshape(bshape) \
        + (x - mean_b) * ((2.0 / n) * dvar_ct).astype(dt).reshape(bshape)
    return (dx, sum_dy_xhat.astype(gamma.dtype),
            sum_dy.astype(gamma.dtype))


_bn_core.defvjp(_bn_core_fwd, _bn_core_bwd)



def _bn_num_outputs(params):
    return 3 if coerce_bool(params.get("output_mean_var", False)) else 1


@register(
    "BatchNorm",
    arg_names=["data", "gamma", "beta"],
    aux_names=("moving_mean", "moving_var"),
    coerce={
        "eps": coerce_float,
        "momentum": coerce_float,
        "fix_gamma": coerce_bool,
        "use_global_stats": coerce_bool,
        "output_mean_var": coerce_bool,
        "axis": coerce_int,
    },
    defaults={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
              "use_global_stats": False, "output_mean_var": False,
              "axis": 1},
    needs_mode=True,
    num_outputs_fn=_bn_num_outputs,
    aliases=("batch_norm",),
)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               is_train=False):
    """Reference src/operator/batch_norm-inl.h. Channel axis default 1
    (NCHW). Functional aux: returns updated moving stats in train mode."""
    axis = axis % data.ndim
    bshape = tuple(
        data.shape[i] if i == axis else 1 for i in range(data.ndim)
    )
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    g = lax.stop_gradient(g) if fix_gamma else g

    if is_train and not use_global_stats:
        out, mean, var = _bn_core(data, g, beta, axis, eps)
        new_mean = moving_mean * momentum + mean.astype(
            moving_mean.dtype) * (1 - momentum)
        new_var = moving_var * momentum + var.astype(
            moving_var.dtype) * (1 - momentum)
    else:
        mean = lax.stop_gradient(moving_mean)
        var = lax.stop_gradient(moving_var)
        inv = lax.rsqrt(var + eps)
        out = (data - mean.reshape(bshape)) * inv.reshape(
            bshape) * g.reshape(bshape) + beta.reshape(bshape)

    outs = (out,)
    if output_mean_var:
        # visible stat outputs keep the declared dtype contract
        # (infer_type reports the data dtype for every BN output); the
        # f32 copies still feed the moving-average update below
        outs = (out, mean.astype(data.dtype), var.astype(data.dtype))
    if is_train:
        return outs + (new_mean, new_var) if not use_global_stats else outs + (moving_mean, moving_var)
    return outs if len(outs) > 1 else out


@register(
    "InstanceNorm",
    arg_names=["data", "gamma", "beta"],
    coerce={"eps": coerce_float},
    defaults={"eps": 1e-3},
)
def instance_norm(data, gamma, beta, eps=1e-3):
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(
        bshape
    ) + beta.reshape(bshape)


@register(
    "L2Normalization",
    arg_names=["data"],
    coerce={"eps": coerce_float},
    defaults={"eps": 1e-10, "mode": "instance"},
)
def l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, data.ndim))
    else:
        raise MXNetError(f"unknown mode {mode!r}")
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


@register(
    "LRN",
    arg_names=["data"],
    coerce={"alpha": coerce_float, "beta": coerce_float,
            "knorm": coerce_float, "nsize": coerce_int},
    defaults={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0},
)
def lrn(data, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    """Local response norm across channels (src/operator/lrn-inl.h)."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2))
    windows = sum(
        padded[:, i : i + data.shape[1]] for i in range(nsize)
    )
    return data / jnp.power(knorm + alpha / nsize * windows, beta)


# --------------------------------------------------------------- dropout


@register(
    "Dropout",
    arg_names=["data"],
    coerce={"p": coerce_float},
    defaults={"p": 0.5, "mode": "training"},
    needs_rng=True,
    needs_mode=True,
    aliases=("dropout",),
)
def dropout(data, p=0.5, mode="training", rng=None, is_train=False):
    if not is_train and mode != "always":
        return data
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, data.shape)
    return jnp.where(mask, data / keep, jnp.zeros_like(data))


# ---------------------------------------------------------- softmax family


def _softmax_axis(v):
    return coerce_int(v)


@register(
    "softmax",
    arg_names=["data"],
    coerce={"axis": _softmax_axis, "temperature": coerce_float},
    defaults={"axis": -1, "temperature": 1.0},
)
def softmax(data, axis=-1, temperature=1.0):
    return jax.nn.softmax(data / temperature, axis=axis)


@register(
    "log_softmax",
    arg_names=["data"],
    coerce={"axis": _softmax_axis, "temperature": coerce_float},
    defaults={"axis": -1, "temperature": 1.0},
)
def log_softmax(data, axis=-1, temperature=1.0):
    return jax.nn.log_softmax(data / temperature, axis=axis)


@register(
    "SoftmaxActivation",
    arg_names=["data"],
    defaults={"mode": "instance"},
)
def softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(
        data.shape
    )


# ---------------------------------------------------- output (loss) ops
#
# These reproduce the reference's "output op" pattern: forward is identity
# or softmax; backward REPLACES the incoming gradient with the loss
# gradient. Implemented with custom_vjp so jax.vjp-driven executors get
# reference semantics with ones as head gradient.


def _softmax_output_impl(data, label, grad_scale, ignore_label, multi_output,
                         use_ignore, preserve_shape, normalization,
                         smooth_alpha, out_grad):
    del out_grad
    if multi_output:
        prob = jax.nn.softmax(data, axis=1)
    elif preserve_shape:
        prob = jax.nn.softmax(data, axis=-1)
    else:
        prob = jax.nn.softmax(
            data.reshape(data.shape[0], -1), axis=-1
        ).reshape(data.shape)
    return prob


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7, 8, 9))
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False,
                    preserve_shape=False, normalization="null",
                    smooth_alpha=0.0, out_grad=False):
    return _softmax_output_impl(
        data, label, grad_scale, ignore_label, multi_output, use_ignore,
        preserve_shape, normalization, smooth_alpha, out_grad
    )


def _softmax_output_fwd(data, label, *nd):
    prob = _softmax_output(data, label, *nd)
    return prob, (prob, label)


def _softmax_output_bwd(grad_scale, ignore_label, multi_output, use_ignore,
                        preserve_shape, normalization, smooth_alpha,
                        out_grad, res, g):
    prob, label = res
    if multi_output:
        # data (N, C, d...), label (N, d...): softmax over axis 1
        nclass = prob.shape[1]
        lab = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(lab, nclass, axis=1, dtype=prob.dtype)
        grad = prob - onehot
        if use_ignore:
            valid = (label != ignore_label).astype(prob.dtype)
            grad = grad * jnp.expand_dims(valid, 1)
    elif label.shape == prob.shape:
        # soft labels
        grad = prob - label
        valid = None
    else:
        nclass = prob.shape[-1]
        lab = label.astype(jnp.int32).reshape(prob.shape[:-1])
        onehot = jax.nn.one_hot(lab, nclass, dtype=prob.dtype)
        if smooth_alpha > 0:
            onehot = onehot * (1 - smooth_alpha) + smooth_alpha / (
                nclass - 1
            ) * (1 - onehot)
        grad = prob - onehot
        if use_ignore:
            valid = (lab != int(ignore_label)).astype(prob.dtype)
            grad = grad * valid[..., None]

    scale = grad_scale
    if normalization == "batch":
        grad = grad / prob.shape[0]
    elif normalization == "valid":
        if use_ignore:
            if multi_output:
                cnt = jnp.sum((label != ignore_label).astype(prob.dtype))
            else:
                cnt = jnp.sum(
                    (label.astype(jnp.int32) != int(ignore_label)).astype(
                        prob.dtype
                    )
                )
            grad = grad / jnp.maximum(cnt, 1.0)
        else:
            grad = grad / prob.shape[0]
    grad = grad * scale
    if out_grad:
        grad = grad * g
    return grad, jnp.zeros_like(label)


_softmax_output.defvjp(_softmax_output_fwd, _softmax_output_bwd)


_NORM_MAP = {0: "null", 1: "batch", 2: "valid",
             "null": "null", "batch": "batch", "valid": "valid"}


@register(
    "SoftmaxOutput",
    arg_names=["data", "label"],
    coerce={
        "grad_scale": coerce_float,
        "ignore_label": coerce_float,
        "multi_output": coerce_bool,
        "use_ignore": coerce_bool,
        "preserve_shape": coerce_bool,
        "normalization": lambda v: _NORM_MAP[v],
        "smooth_alpha": coerce_float,
        "out_grad": coerce_bool,
    },
    defaults={"grad_scale": 1.0, "ignore_label": -1.0,
              "multi_output": False, "use_ignore": False,
              "preserve_shape": False, "normalization": "null",
              "smooth_alpha": 0.0, "out_grad": False},
    no_grad_inputs=("label",),
    aliases=("Softmax",),
)
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False,
                   preserve_shape=False, normalization="null",
                   smooth_alpha=0.0, out_grad=False):
    return _softmax_output(
        data, label, grad_scale, ignore_label, multi_output, use_ignore,
        preserve_shape, normalization, smooth_alpha, out_grad
    )


def _regression_output(name, fwd, bwd, aliases=()):
    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def _core(data, label, grad_scale=1.0):
        return fwd(data)

    def _core_fwd(data, label, grad_scale):
        out = fwd(data)
        return out, (out, label)

    def _core_bwd(grad_scale, res, g):
        out, label = res
        num_output = 1
        for s in label.shape[1:]:
            num_output *= s
        grad = grad_scale / num_output * bwd(out, label.reshape(out.shape))
        return grad, jnp.zeros_like(label)

    _core.defvjp(_core_fwd, _core_bwd)

    @register(
        name,
        arg_names=["data", "label"],
        coerce={"grad_scale": coerce_float},
        defaults={"grad_scale": 1.0},
        no_grad_inputs=("label",),
        aliases=aliases,
    )
    def _op(data, label, grad_scale=1.0):
        return _core(data, label, grad_scale)

    return _op


_regression_output(
    "LinearRegressionOutput", lambda x: x, lambda o, l: o - l
)
_regression_output(
    "MAERegressionOutput", lambda x: x, lambda o, l: jnp.sign(o - l)
)
_regression_output(
    "LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: o - l
)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _make_loss(data, grad_scale=1.0, normalization="null"):
    return data


def _make_loss_fwd(data, grad_scale, normalization):
    return data, data.shape


def _make_loss_bwd(grad_scale, normalization, shape, g):
    grad = jnp.full(shape, grad_scale)
    if normalization == "batch":
        grad = grad / shape[0]
    return (grad,)


_make_loss.defvjp(_make_loss_fwd, _make_loss_bwd)


@register(
    "MakeLoss",
    arg_names=["data"],
    coerce={"grad_scale": coerce_float,
            "normalization": lambda v: _NORM_MAP.get(v, v)},
    defaults={"grad_scale": 1.0, "normalization": "null"},
    aliases=("make_loss",),
)
def make_loss(data, grad_scale=1.0, normalization="null", valid_thresh=0.0):
    return _make_loss(data, grad_scale, normalization)


def _ctc_arg_names(params):
    names = ["data", "label"]
    if coerce_bool(params.get("use_data_lengths", False)):
        names.append("data_lengths")
    if coerce_bool(params.get("use_label_lengths", False)):
        names.append("label_lengths")
    return names


@register(
    "CTCLoss",
    arg_names_fn=_ctc_arg_names,
    coerce={"use_data_lengths": coerce_bool,
            "use_label_lengths": coerce_bool,
            "blank_label": lambda v: str(v)},
    defaults={"use_data_lengths": False, "use_label_lengths": False,
              "blank_label": "first"},
    no_grad_inputs=("label", "data_lengths", "label_lengths"),
    aliases=("ctc_loss", "WarpCTC"),
)
def ctc_loss(*inputs, use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """Connectionist Temporal Classification loss (reference
    plugin/warpctc + contrib ctc_loss). data is (T, N, C) activations
    (softmax applied internally, the warpctc convention); label is
    (N, L). With blank_label='first' (default) the blank is id 0,
    classes are 1..C-1, and label padding is 0; with 'last' the blank
    is C-1 and label padding is any NEGATIVE id (the reference's -1
    convention). `use_data_lengths`/`use_label_lengths` add the
    corresponding (N,) length inputs, masking padded frames/labels.
    Returns per-example costs (N,); gradients flow to data via jax
    autodiff of the log-alpha recursion (optax's CTC).
    """
    try:
        import optax
    except ImportError as exc:  # pragma: no cover - env without optax
        raise MXNetError(
            "CTCLoss needs the optax package for its CTC core "
            "(pip install optax)") from exc

    if blank_label not in ("first", "last"):
        raise MXNetError(
            f"CTCLoss: blank_label must be 'first' or 'last', got "
            f"{blank_label!r}")
    # positional inputs follow _ctc_arg_names' order (the lengths are
    # present exactly when the corresponding use_* flag is set)
    want = 2 + int(use_data_lengths) + int(use_label_lengths)
    if len(inputs) != want:
        raise MXNetError(
            f"CTCLoss: expected {want} inputs "
            f"({', '.join(_ctc_arg_names({'use_data_lengths': use_data_lengths, 'use_label_lengths': use_label_lengths}))}), "
            f"got {len(inputs)}")
    data, label = inputs[0], inputs[1]
    idx = 2
    data_lengths = label_lengths = None
    if use_data_lengths:
        data_lengths = inputs[idx]
        idx += 1
    if use_label_lengths:
        label_lengths = inputs[idx]

    T, N, C = data.shape
    logits = jnp.transpose(data, (1, 0, 2))  # (N, T, C)
    if use_data_lengths:
        t_idx = jnp.arange(T, dtype=jnp.float32)[None, :]
        logit_pads = (t_idx >= data_lengths.astype(
            jnp.float32).reshape(-1, 1)).astype(logits.dtype)
    else:
        logit_pads = jnp.zeros((N, T), dtype=logits.dtype)
    lab = label.astype(jnp.int32)
    if blank_label == "first":
        blank_id = 0
        pads = (lab <= 0)
    else:
        blank_id = C - 1
        pads = (lab < 0)
    if use_label_lengths:
        l_idx = jnp.arange(lab.shape[1], dtype=jnp.int32)[None, :]
        pads = pads | (l_idx >= label_lengths.astype(
            jnp.int32).reshape(-1, 1))
    # padded slots must hold a safe id for the gather inside optax
    lab = jnp.where(pads, blank_id, lab)
    return optax.ctc_loss(logits, logit_pads, lab,
                          pads.astype(logits.dtype),
                          blank_id=blank_id)


@register(
    "softmax_cross_entropy",
    arg_names=["data", "label"],
    no_grad_inputs=("label",),
)
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, lab[:, None], axis=-1)
    return -jnp.sum(picked).reshape((1,))


@register(
    "SVMOutput",
    arg_names=["data", "label"],
    coerce={"margin": coerce_float, "regularization_coefficient": coerce_float,
            "use_linear": coerce_bool},
    defaults={"margin": 1.0, "regularization_coefficient": 1.0,
              "use_linear": False},
    no_grad_inputs=("label",),
)
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    return _svm_output(data, label, margin, regularization_coefficient,
                       use_linear)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _svm_output(data, label, margin, reg_coef, use_linear):
    return data


def _svm_fwd(data, label, margin, reg_coef, use_linear):
    return data, (data, label)


def _svm_bwd(margin, reg_coef, use_linear, res, g):
    data, label = res
    lab = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, data.shape[1], dtype=data.dtype)
    # hinge loss gradient (svm_output-inl.h): L1 or squared hinge
    signed = jnp.where(onehot > 0, -data, data)
    viol = (margin + signed) > 0
    if use_linear:
        grad = jnp.where(viol, jnp.where(onehot > 0, -1.0, 1.0), 0.0)
    else:
        grad = jnp.where(
            viol,
            2.0 * (margin + signed) * jnp.where(onehot > 0, -1.0, 1.0),
            0.0,
        )
    return grad * reg_coef, jnp.zeros_like(label)


_svm_output.defvjp(_svm_fwd, _svm_bwd)


# ------------------------------------------------------------- sequence ops


def _seq_mask_from_length(length, maxlen, batch, dtype):
    steps = jnp.arange(maxlen, dtype=jnp.float32)[:, None]
    return (steps < length.astype(jnp.float32)[None, :]).astype(dtype)


@register(
    "SequenceMask",
    arg_names=["data", "sequence_length"],
    coerce={"use_sequence_length": coerce_bool, "value": coerce_float},
    defaults={"use_sequence_length": False, "value": 0.0},
    no_grad_inputs=("sequence_length",),
)
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0):
    """(T, N, ...) masking (src/operator/sequence_mask-inl.h)."""
    if not use_sequence_length or sequence_length is None:
        return data
    mask = _seq_mask_from_length(
        sequence_length, data.shape[0], data.shape[1], data.dtype
    )
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return data * mask + value * (1 - mask)


@register(
    "SequenceLast",
    arg_names=["data", "sequence_length"],
    coerce={"use_sequence_length": coerce_bool},
    defaults={"use_sequence_length": False},
    no_grad_inputs=("sequence_length",),
)
def sequence_last(data, sequence_length=None, use_sequence_length=False):
    if not use_sequence_length or sequence_length is None:
        return data[-1]
    idx = (sequence_length.astype(jnp.int32) - 1).clip(0)
    return jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0
    )[0]


@register(
    "SequenceReverse",
    arg_names=["data", "sequence_length"],
    coerce={"use_sequence_length": coerce_bool},
    defaults={"use_sequence_length": False},
    no_grad_inputs=("sequence_length",),
)
def sequence_reverse(data, sequence_length=None, use_sequence_length=False):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)
    lens = sequence_length.astype(jnp.int32)  # (N,)
    # index steps: for t < len: len-1-t else t
    idx = jnp.where(
        steps[:, None] < lens[None, :],
        lens[None, :] - 1 - steps[:, None],
        steps[:, None],
    )
    return jnp.take_along_axis(
        data, idx.reshape(idx.shape + (1,) * (data.ndim - 2)), axis=0
    )


# ------------------------------------------------------------ misc layers


@register(
    "UpSampling",
    coerce={"scale": coerce_int, "num_filter": coerce_int,
            "num_args": coerce_int},
    defaults={"sample_type": "nearest"},
)
def upsampling(*args, scale=2, sample_type="nearest", num_filter=0,
               num_args=None, multi_input_mode="concat", workspace=512):
    data = args[0]
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
        if len(args) > 1:
            outs = [out]
            for extra in args[1:]:
                s = out.shape[2] // extra.shape[2]
                outs.append(
                    jnp.repeat(jnp.repeat(extra, s, axis=2), s, axis=3)
                )
            return jnp.concatenate(outs, axis=1)
        return out
    if sample_type == "bilinear":
        weight = args[1]
        dn = lax.conv_dimension_numbers(
            data.shape, weight.shape, ("NCHW", "IOHW", "NCHW")
        )
        k = 2 * scale - scale % 2
        p = (k - scale) // 2  # matches DeconvolutionParam in upsampling
        return lax.conv_transpose(
            data, weight, strides=(scale, scale),
            padding=[(k - 1 - p, k - 1 - p)] * 2,
            dimension_numbers=dn,
        )
    raise MXNetError(f"unknown sample_type {sample_type!r}")


@register(
    "IdentityAttachKLSparseReg",
    arg_names=["data"],
    coerce={"sparseness_target": coerce_float, "penalty": coerce_float,
            "momentum": coerce_float},
    defaults={"sparseness_target": 0.1, "penalty": 0.001, "momentum": 0.9},
)
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9):
    return data

"""Operator library: one registry, jax lowerings.

Importing this package registers every op (the analog of static
registration in the reference's src/operator/*.cc files).
"""
from . import registry  # noqa: F401
from . import elemwise  # noqa: F401
from . import broadcast_reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import indexing  # noqa: F401
from . import init_sample  # noqa: F401
from . import ordering  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn_op  # noqa: F401
from . import custom  # noqa: F401
from . import vision  # noqa: F401
from . import parallel_ops  # noqa: F401

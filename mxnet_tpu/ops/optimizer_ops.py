"""Fused optimizer-update ops.

Covers reference src/operator/optimizer_op-inl.h (sgd_update,
sgd_mom_update, adam_update, rmsprop_update, rmspropalex_update). Each is
one fused XLA computation — weight/state update in a single kernel, the
analog of the reference's fused mshadow expressions. Executors and the
Optimizer classes both route through these.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register
from ..base import coerce_float

_F = {
    "lr": coerce_float,
    "wd": coerce_float,
    "rescale_grad": coerce_float,
    "clip_gradient": coerce_float,
    "momentum": coerce_float,
    "beta1": coerce_float,
    "beta2": coerce_float,
    "epsilon": coerce_float,
    "gamma1": coerce_float,
    "gamma2": coerce_float,
    "clip_weights": coerce_float,
}


def _prep_grad(grad, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g


@register(
    "sgd_update",
    arg_names=["weight", "grad"],
    coerce=_F,
    defaults={"wd": 0.0, "rescale_grad": 1.0, "clip_gradient": -1.0},
)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    return weight - lr * (g + wd * weight)


@register(
    "sgd_mom_update",
    arg_names=["weight", "grad", "mom"],
    num_outputs=2,
    coerce=_F,
    defaults={"momentum": 0.0, "wd": 0.0, "rescale_grad": 1.0,
              "clip_gradient": -1.0},
)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    """Returns (weight', mom') — reference mutates mom in place; the
    functional form returns both (optimizer_op-inl.h:64-100)."""
    g = _prep_grad(grad, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register(
    "adam_update",
    arg_names=["weight", "grad", "mean", "var"],
    num_outputs=3,
    coerce=_F,
    defaults={"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8, "wd": 0.0,
              "rescale_grad": 1.0, "clip_gradient": -1.0},
)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_weight = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_weight, new_mean, new_var


@register(
    "rmsprop_update",
    arg_names=["weight", "grad", "n"],
    num_outputs=2,
    coerce=_F,
    defaults={"gamma1": 0.95, "epsilon": 1e-8, "wd": 0.0,
              "rescale_grad": 1.0, "clip_gradient": -1.0,
              "clip_weights": -1.0},
)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_weight = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_weight = jnp.clip(new_weight, -clip_weights, clip_weights)
    return new_weight, new_n


@register(
    "rmspropalex_update",
    arg_names=["weight", "grad", "n", "g", "delta"],
    num_outputs=4,
    coerce=_F,
    defaults={"gamma1": 0.95, "gamma2": 0.9, "epsilon": 1e-8, "wd": 0.0,
              "rescale_grad": 1.0, "clip_gradient": -1.0,
              "clip_weights": -1.0},
)
def rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """Graves-style RMSProp (optimizer_op-inl.h rmspropalex)."""
    gr = _prep_grad(grad, rescale_grad, clip_gradient) + wd * weight
    new_n = (1 - gamma1) * jnp.square(gr) + gamma1 * n
    new_g = (1 - gamma1) * gr + gamma1 * g
    new_delta = gamma2 * delta - lr * gr / jnp.sqrt(
        new_n - jnp.square(new_g) + epsilon
    )
    new_weight = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_weight = jnp.clip(new_weight, -clip_weights, clip_weights)
    return new_weight, new_n, new_g, new_delta

"""Indexing ops: Embedding, take, batch_take, one_hot, pick.

Covers reference src/operator/tensor/indexing_op.{h,cc,cu}. Gathers lower
to XLA gather; the Embedding backward becomes a scatter-add XLA emits from
the vjp — no hand-written AddTakeGrad kernel needed.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register
from ..base import coerce_float, coerce_int


@register(
    "Embedding",
    arg_names=["data", "weight"],
    coerce={"input_dim": coerce_int, "output_dim": coerce_int},
    no_grad_inputs=("data",),
)
def embedding(data, weight, input_dim=0, output_dim=0, dtype="float32"):
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


@register(
    "take",
    arg_names=["a", "indices"],
    coerce={"axis": coerce_int},
    defaults={"axis": 0, "mode": "clip"},
    no_grad_inputs=("indices",),
)
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "clip":
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    elif mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    return jnp.take(a, idx, axis=axis)


@register("batch_take", arg_names=["a", "indices"], no_grad_inputs=("indices",))
def batch_take(a, indices):
    idx = indices.astype(jnp.int32)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register(
    "one_hot",
    arg_names=["indices"],
    coerce={
        "depth": coerce_int,
        "on_value": coerce_float,
        "off_value": coerce_float,
    },
    defaults={"on_value": 1.0, "off_value": 0.0, "dtype": "float32"},
    no_grad_inputs=("indices",),
)
def one_hot(indices, depth=0, on_value=1.0, off_value=0.0, dtype="float32"):
    idx = indices.astype(jnp.int32)
    eye = jnp.arange(depth, dtype=jnp.int32)
    hot = (idx[..., None] == eye).astype(jnp.dtype(dtype))
    return hot * on_value + (1.0 - hot) * off_value


@register(
    "pick",
    arg_names=["data", "index"],
    coerce={
        "axis": lambda v: None if v in (None, "None", "") else coerce_int(v),
        "keepdims": lambda v: v in (True, "1", "true", "True"),
    },
    defaults={"axis": -1, "keepdims": False},
    no_grad_inputs=("index",),
)
def pick(data, index, axis=-1, keepdims=False):
    if axis is None:
        flat = data.reshape(-1)
        out = jnp.take(flat, index.astype(jnp.int32))
        return out
    idx = jnp.expand_dims(index.astype(jnp.int32), axis=axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register(
    "where",
    arg_names=["condition", "x", "y"],
    no_grad_inputs=("condition",),
)
def where(condition, x, y):
    cond = condition
    if cond.shape != x.shape and cond.ndim == 1:
        # reference allows a batch-length condition vector
        # (control_flow_op.h)
        cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(cond != 0, x, y)

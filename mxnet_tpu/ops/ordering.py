"""Ordering ops: sort, argsort, topk.

Covers reference src/operator/tensor/ordering_op-inl.h + sort_op.h (which
wrap thrust/cub device sorts). XLA's sort/top_k lower natively on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import MXNetError, coerce_bool, coerce_int


_AXIS = lambda v: None if v in (None, "None", "") else coerce_int(v)


@register(
    "sort",
    arg_names=["data"],
    coerce={"axis": _AXIS, "is_ascend": coerce_bool},
    defaults={"axis": -1, "is_ascend": True},
)
def sort(data, axis=-1, is_ascend=True):
    if axis is None:
        out = jnp.sort(data.reshape(-1), axis=0)
    else:
        out = jnp.sort(data, axis=axis)
        axis_ = axis
    if not is_ascend:
        out = jnp.flip(out, axis=0 if axis is None else axis)
    return out


@register(
    "argsort",
    arg_names=["data"],
    coerce={"axis": _AXIS, "is_ascend": coerce_bool},
    defaults={"axis": -1, "is_ascend": True},
    no_grad_inputs=("data",),
)
def argsort(data, axis=-1, is_ascend=True):
    if axis is None:
        data = data.reshape(-1)
        axis = 0
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.float32)


def _topk_num_outputs(params):
    ret = params.get("ret_typ", "indices")
    return 2 if ret == "both" else 1


@register(
    "topk",
    arg_names=["data"],
    coerce={"axis": _AXIS, "k": coerce_int, "is_ascend": coerce_bool},
    defaults={"axis": -1, "k": 1, "ret_typ": "indices", "is_ascend": False},
    num_outputs_fn=_topk_num_outputs,
)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False):
    if axis is None:
        data = data.reshape(-1)
        axis = 0
    axis = axis % data.ndim
    moved = jnp.moveaxis(data, axis, -1)
    if is_ascend:
        vals, idx = jax.lax.top_k(-moved, k)
        vals = -vals
    else:
        vals, idx = jax.lax.top_k(moved, k)
    vals = jnp.moveaxis(vals, -1, axis)
    idxf = jnp.moveaxis(idx, -1, axis).astype(jnp.float32)
    if ret_typ == "value":
        return vals
    if ret_typ == "indices":
        return idxf
    if ret_typ == "both":
        return vals, idxf
    if ret_typ == "mask":
        mask = jnp.zeros_like(moved)
        mask = jnp.put_along_axis(
            mask, idx, 1.0, axis=-1, inplace=False
        )
        return jnp.moveaxis(mask, -1, axis)
    raise MXNetError(f"unknown ret_typ {ret_typ!r}")

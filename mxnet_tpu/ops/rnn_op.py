"""Fused multi-layer RNN op — the cuDNN-RNN analog, TPU-style.

Capability parity with the reference `RNN` op (src/operator/rnn-inl.h:71-93
RNNParam; real implementation src/operator/cudnn_rnn-inl.h:127-267 —
LSTM/GRU/vanilla via cuDNN with a single packed parameter blob). The
TPU-native design:

- The whole sequence's input projections run as ONE batched matmul per
  layer/direction ((T*N, in) @ (in, G*H)) — large, MXU-shaped work —
  BEFORE the time loop, so the `lax.scan` body only carries the (N, H) @
  (H, G*H) recurrent matmul. This is the standard XLA RNN recipe; there
  is no cuDNN "fused kernel" to call, the fusion IS the scan + XLA.
- Parameters live in one flat vector with the same conceptual layout as
  cuDNN's packed blob (all weights layer-major/direction-inner, then all
  biases): `param_layout()` below is shared with
  rnn/rnn_cell.py:FusedRNNCell.unpack_weights/pack_weights so the fused
  ⇄ unfused conversion is consistent by construction.
- Bidirectional = scan the time-reversed sequence and flip the result;
  inter-layer dropout (cuDNN semantics: between layers only) uses the
  op-level rng.

Gate orders match the reference FusedRNNCell gate names
(python/mxnet/rnn/rnn_cell.py: lstm [i f c o], gru [r z o]).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register
from ..base import MXNetError, coerce_bool, coerce_float, coerce_int

MODE_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def param_layout(input_size, state_size, num_layers, bidirectional, mode):
    """Flat-parameter layout: list of (kind, layer, dir, part) -> (offset,
    shape), plus total size. kind in {'w','b'}, part in {'i2h','h2h'}.

    Layout rule (mirrors cuDNN packing, cudnn_rnn-inl.h): all weight
    matrices first — layer-major, direction-inner, i2h before h2h — then
    all bias vectors in the same order.
    """
    h = state_size
    gh = MODE_GATES[mode] * h
    dirs = 2 if bidirectional else 1
    entries = {}
    off = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else h * dirs
        for d in range(dirs):
            entries[("w", layer, d, "i2h")] = (off, (gh, in_size))
            off += gh * in_size
            entries[("w", layer, d, "h2h")] = (off, (gh, h))
            off += gh * h
    for layer in range(num_layers):
        for d in range(dirs):
            entries[("b", layer, d, "i2h")] = (off, (gh,))
            off += gh
            entries[("b", layer, d, "h2h")] = (off, (gh,))
            off += gh
    return entries, off


def rnn_param_size(input_size, state_size, num_layers=1,
                   bidirectional=False, mode="lstm"):
    """Total flat parameter count (reference FusedRNNCell weight size)."""
    return param_layout(
        input_size, state_size, num_layers, bidirectional, mode)[1]


def _layer_scan(x, h0, c0, w_hh, b_hh, mode):
    """Scan one direction of one layer. x: (T, N, G*H) pre-projected
    inputs (i2h matmul + i2h bias already applied)."""
    if mode == "lstm":
        def step(carry, xt):
            hprev, cprev = carry
            g = xt + hprev @ w_hh.T + b_hh
            i, f, c, o = jnp.split(g, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            c = jnp.tanh(c)
            o = jax.nn.sigmoid(o)
            cnext = f * cprev + i * c
            hnext = o * jnp.tanh(cnext)
            return (hnext, cnext), hnext

        (hf, cf), ys = lax.scan(step, (h0, c0), x)
        return ys, hf, cf
    if mode == "gru":
        def step(hprev, xt):
            hp = hprev @ w_hh.T + b_hh
            xr, xz, xn = jnp.split(xt, 3, axis=-1)
            hr, hz, hn = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            hnext = (1.0 - z) * n + z * hprev
            return hnext, hnext

        hf, ys = lax.scan(step, h0, x)
        return ys, hf, None
    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))

    def step(hprev, xt):
        hnext = act(xt + hprev @ w_hh.T + b_hh)
        return hnext, hnext

    hf, ys = lax.scan(step, h0, x)
    return ys, hf, None


@register(
    "RNN",
    arg_names=["data", "parameters", "state", "state_cell"],
    coerce={
        "state_size": coerce_int,
        "num_layers": coerce_int,
        "bidirectional": coerce_bool,
        "p": coerce_float,
        "state_outputs": coerce_bool,
        "lstm_state_clip_nan": coerce_bool,
    },
    defaults={
        "num_layers": 1,
        "bidirectional": False,
        "p": 0.0,
        "state_outputs": False,
    },
    needs_rng=True,
    needs_mode=True,
    num_outputs_fn=lambda p: (
        1 if not p.get("state_outputs")
        else (3 if p.get("mode") == "lstm" else 2)
    ),
)
def rnn(data, parameters, state, state_cell=None, *, state_size, mode,
        num_layers=1, bidirectional=False, p=0.0, state_outputs=False,
        rng=None, is_train=False, **_ignored):
    """data: (T, N, input) TNC; parameters: flat 1-D blob (param_layout);
    state: (L*dirs, N, H) initial hidden; state_cell: same (lstm only).
    Returns output (T, N, H*dirs) [, final state [, final cell]]."""
    if mode not in MODE_GATES:
        raise MXNetError(f"RNN: unknown mode {mode!r}")
    t, n, input_size = data.shape
    h = state_size
    dirs = 2 if bidirectional else 1
    entries, total = param_layout(
        input_size, h, num_layers, bidirectional, mode)
    if parameters.shape != (total,):
        raise MXNetError(
            f"RNN: parameters must have shape ({total},) for "
            f"input_size={input_size} state_size={h} num_layers="
            f"{num_layers} mode={mode!r} bidirectional={bidirectional}; "
            f"got {parameters.shape}"
        )

    # begin_state() defaults are zeros with batch dim 1 (forward-only shape
    # inference can't resolve the reference's 0-as-unknown); broadcast here.
    full = (num_layers * dirs, n, h)
    if state.shape != full:
        state = jnp.broadcast_to(state, full)
    if mode == "lstm" and state_cell.shape != full:
        state_cell = jnp.broadcast_to(state_cell, full)

    def par(key):
        off, shape = entries[key]
        size = 1
        for s in shape:
            size *= s
        return parameters[off: off + size].reshape(shape)

    x = data
    finals_h, finals_c = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            w_ih = par(("w", layer, d, "i2h"))
            w_hh = par(("w", layer, d, "h2h"))
            b_ih = par(("b", layer, d, "i2h"))
            b_hh = par(("b", layer, d, "h2h"))
            sidx = layer * dirs + d
            h0 = state[sidx]
            c0 = state_cell[sidx] if mode == "lstm" else None
            xd = x[::-1] if d == 1 else x
            # one big MXU matmul for the whole sequence's input projection
            xp = xd @ w_ih.T + b_ih
            ys, hf, cf = _layer_scan(xp, h0, c0, w_hh, b_hh, mode)
            if d == 1:
                ys = ys[::-1]
            outs.append(ys)
            finals_h.append(hf)
            if mode == "lstm":
                finals_c.append(cf)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if is_train and p > 0.0 and layer < num_layers - 1:
            keep = 1.0 - p
            mask = jax.random.bernoulli(
                jax.random.fold_in(rng, layer), keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0)

    if not state_outputs:
        return x
    hn = jnp.stack(finals_h, axis=0)
    if mode == "lstm":
        cn = jnp.stack(finals_c, axis=0)
        return x, hn, cn
    return x, hn

"""`Custom` op: Python-defined operators inside compiled graphs.

Capability parity with the reference custom-op machinery
(src/operator/custom/custom-inl.h + python/mxnet/operator.py:396-855):
a CustomOpProp subclass registered under an op_type string supplies
list_arguments / list_outputs / infer_shape and a CustomOp whose
forward/backward run as Python. TPU-native mechanism: the Python
callbacks execute host-side through `jax.pure_callback` (the analog of
the reference's kAsync exec type that moves Python callbacks off the
engine worker, include/mxnet/operator.h:84), and the custom backward is
wired in with `jax.custom_vjp` so `jax.grad`/Executor backward flow
through the user's backward() exactly like the reference's engine calls
the registered backward entry.

Note XLA cannot fuse across a pure_callback: each Custom node is a
host round-trip. That is the same boundary the reference has (custom
ops run on the CPU in Python, with device<->host copies around them).
"""
from __future__ import annotations

import functools

import jax
import numpy as np

from .registry import register
from ..base import MXNetError

_PROP_REGISTRY: dict[str, type] = {}


def register_prop(reg_name, prop_cls):
    if _PROP_REGISTRY.get(reg_name) is not prop_cls:
        # re-registration must not serve stale prop instances (or, for
        # C-registered ops, stale function pointers) out of the cache
        _make_prop.cache_clear()
    _PROP_REGISTRY[reg_name] = prop_cls


def get_prop_cls(reg_name):
    try:
        return _PROP_REGISTRY[reg_name]
    except KeyError:
        raise MXNetError(
            f"unknown custom op type {reg_name!r}; register a "
            "CustomOpProp with mx.operator.register first"
        ) from None


@functools.lru_cache(maxsize=None)
def _make_prop(op_type, kwargs_items):
    cls = get_prop_cls(op_type)
    prop = cls(**dict(kwargs_items))
    prop._op_type = op_type
    prop._kwargs = dict(kwargs_items)
    return prop


def _prop_from_params(params):
    kwargs = {
        k: v for k, v in params.items() if k != "op_type"
    }
    return _make_prop(
        params["op_type"], tuple(sorted(kwargs.items()))
    )


def _custom_arg_names(params):
    return list(_prop_from_params(params).list_arguments())


def _custom_num_outputs(params):
    return len(_prop_from_params(params).list_outputs())


def custom_fn(*inputs, rng=None, is_train=False, **params):
    """Trace-time body of the Custom op."""
    prop = _prop_from_params(params)
    if prop.list_auxiliary_states():
        raise MXNetError(
            "Custom ops with auxiliary states are not supported yet"
        )
    n_out = len(prop.list_outputs())
    in_shapes = [tuple(x.shape) for x in inputs]
    in_shapes2, out_shapes, _ = _infer_shapes(prop, in_shapes)
    in_dtypes = [x.dtype for x in inputs]
    types = prop.infer_type([np.dtype(d) for d in in_dtypes])
    out_dtypes = list(types[1])
    out_structs = [
        jax.ShapeDtypeStruct(s, d)
        for s, d in zip(out_shapes, out_dtypes)
    ]
    train_flag = bool(is_train)

    def _new_op():
        from ..context import cpu

        return prop.create_operator(cpu(), in_shapes, in_dtypes)

    def fwd_callback(*xs):
        from ..ndarray import NDArray, array

        op = _new_op()
        in_data = [array(np.asarray(x)) for x in xs]
        out_data = [
            array(np.zeros(s, d))
            for s, d in zip(out_shapes, out_dtypes)
        ]
        op.forward(
            is_train=train_flag,
            req=["write"] * len(out_data),
            in_data=in_data,
            out_data=out_data,
            aux=[],
        )
        return tuple(
            np.asarray(o.asnumpy(), dtype=d)
            for o, d in zip(out_data, out_dtypes)
        )

    @jax.custom_vjp
    def f(*ins):
        out = jax.pure_callback(fwd_callback, tuple(out_structs), *ins)
        return tuple(out)

    def f_fwd(*ins):
        out = f(*ins)
        return out, (ins, out)

    def f_bwd(res, gs):
        ins, outs = res
        in_structs = tuple(
            jax.ShapeDtypeStruct(tuple(x.shape), x.dtype) for x in ins
        )

        def bwd_callback(*flat):
            from ..ndarray import array

            n_g = n_out
            n_i = len(ins)
            out_grad = [array(np.asarray(x)) for x in flat[:n_g]]
            in_data = [
                array(np.asarray(x)) for x in flat[n_g: n_g + n_i]
            ]
            out_data = [array(np.asarray(x)) for x in flat[n_g + n_i:]]
            op = _new_op()
            in_grad = [
                array(np.zeros(tuple(x.shape),
                               np.dtype(x.dtype)))
                for x in in_data
            ]
            op.backward(
                req=["write"] * len(in_grad),
                out_grad=out_grad,
                in_data=in_data,
                out_data=out_data,
                in_grad=in_grad,
                aux=[],
            )
            return tuple(
                np.asarray(g.asnumpy(), dtype=x.dtype)
                for g, x in zip(in_grad, ins)
            )

        grads = jax.pure_callback(
            bwd_callback, in_structs, *gs, *ins, *outs
        )
        return tuple(grads)

    f.defvjp(f_fwd, f_bwd)
    out = f(*inputs)
    return out if n_out > 1 else out[0]


def _infer_shapes(prop, in_shapes):
    """Normalize prop.infer_shape's 2- or 3-tuple return."""
    ret = prop.infer_shape(list(in_shapes))
    if len(ret) == 2:
        ins, outs = ret
        auxs = []
    else:
        ins, outs, auxs = ret
    return (
        [tuple(s) for s in ins],
        [tuple(s) for s in outs],
        [tuple(s) for s in auxs],
    )


register(
    "Custom",
    arg_names=None,
    arg_names_fn=_custom_arg_names,
    num_outputs_fn=_custom_num_outputs,
    needs_rng=True,
    needs_mode=True,
)(custom_fn)


# shape-infer rule: let the prop fill unknown input shapes (the reference
# calls CustomOpProp.infer_shape from the InferShape pass)
from . import shape_infer as _shape_infer


@_shape_infer.rule("Custom")
def _custom_rule(params, ins):
    prop = _prop_from_params(params)
    known = [s for s in ins]
    new_ins, _, _ = _infer_shapes(prop, known)
    return list(new_ins), None

"""Fault tolerance: checkpoint auto-resume + fault injection.

The reference's failure story is thin (SURVEY.md §5: ps-lite heartbeat
surfaced via KVStore.get_num_dead_node, is_recovery restart flag —
kvstore_dist.h:159-167 — and nothing else); the survey directs the
rebuild to close the gap with checkpoint-and-restart orchestration.

- `latest_checkpoint(prefix)` discovers the newest saved epoch.
- `fit_auto_resume(...)` wraps Module.fit: resumes params/epoch from
  the newest checkpoint, saves every epoch, and — because every epoch
  is durable — a crashed/preempted run restarted with the same command
  continues where it left off. On multi-host, every process loads the
  same checkpoint so workers restart consistently (the is_recovery
  analog without a parameter server to re-join).
- `FaultInjector` (env MXNET_TPU_FAULT_INJECT="epoch:N") kills training
  at epoch N — the fault-injection harness used by the resume tests.
"""
from __future__ import annotations

import glob
import os
import re

from . import model as _model
from .base import MXNetError


def latest_checkpoint(prefix):
    """Newest saved epoch for `prefix`, or None."""
    pat = re.compile(
        re.escape(os.path.basename(prefix)) + r"-(\d{4})\.params$"
    )
    best = None
    for path in glob.glob(prefix + "-*.params"):
        m = pat.search(os.path.basename(path))
        if m:
            ep = int(m.group(1))
            best = ep if best is None else max(best, ep)
    return best


class FaultInjector(object):
    """Deterministic crash injection for resilience tests. Spec comes
    from MXNET_TPU_FAULT_INJECT ('epoch:N'); fires once."""

    def __init__(self, spec=None):
        self.spec = spec if spec is not None else os.environ.get(
            "MXNET_TPU_FAULT_INJECT", ""
        )

    def maybe_fail(self, epoch):
        if not self.spec:
            return
        kind, _, val = self.spec.partition(":")
        if kind == "epoch" and epoch == int(val):
            raise RuntimeError(
                f"[fault-injection] simulated failure at epoch {epoch}"
            )


def fit_auto_resume(module, train_data, prefix, num_epoch,
                    eval_data=None, fault_injector=None, **fit_kwargs):
    """Module.fit with per-epoch durable checkpoints and automatic
    resume from the newest one. Returns the epoch training ended at."""
    if fault_injector is None:
        fault_injector = FaultInjector()
    begin_epoch = 0
    arg_params = aux_params = None
    resumed = latest_checkpoint(prefix)
    if resumed is not None:
        _, arg_params, aux_params = _model.load_checkpoint(
            prefix, resumed
        )
        begin_epoch = resumed
    if begin_epoch >= num_epoch:
        return begin_epoch

    injected = fault_injector

    def epoch_cb(epoch, symbol, arg, aux):
        _model.save_checkpoint(
            prefix, epoch + 1, symbol, arg or {}, aux or {}
        )
        injected.maybe_fail(epoch + 1)

    module.fit(
        train_data, eval_data=eval_data,
        begin_epoch=begin_epoch, num_epoch=num_epoch,
        arg_params=arg_params, aux_params=aux_params,
        allow_missing=False,
        epoch_end_callback=[epoch_cb],
        **fit_kwargs,
    )
    return num_epoch

"""Fault tolerance: checkpoint auto-resume + fault injection.

The reference's failure story is thin (SURVEY.md §5: ps-lite heartbeat
surfaced via KVStore.get_num_dead_node, is_recovery restart flag —
kvstore_dist.h:159-167 — and nothing else); the survey directs the
rebuild to close the gap with checkpoint-and-restart orchestration.

- `latest_checkpoint(prefix)` discovers the newest saved epoch.
- `fit_auto_resume(...)` wraps Module.fit: resumes params/epoch from
  the newest checkpoint, saves every epoch, and — because every epoch
  is durable — a crashed/preempted run restarted with the same command
  continues where it left off. On multi-host, every process loads the
  same checkpoint so workers restart consistently (the is_recovery
  analog without a parameter server to re-join).
- When `train_data` speaks the resume protocol (mxnet_tpu.data), the
  data-stream position is ALSO durable: `<prefix>-data-state.json` is
  atomically rewritten every batch, so a run killed mid-epoch resumes
  at the exact batch it died on and replays the bit-identical
  remaining sequence (docs/data.md resume contract; params still
  restart from the last epoch boundary — they are per-epoch durable).
- `FaultInjector` (env MXNET_TPU_FAULT_INJECT="epoch:N" or "step:N")
  kills training at epoch N / global step N — the fault-injection
  harness used by the resume tests and ci/check_input_stall.py.
- MXNET_TPU_FAULT_INJECT="kill:step:N" is the HARD variant: instead
  of raising (which unwinds `finally:` blocks, flushes buffers, runs
  atexit hooks — none of which a preempted TPU host gets to do) it
  SIGKILLs the live process at step N. No Python teardown executes.
  This is what the elastic-training soak (ci/check_elastic.py) injects:
  surviving that proves durability came from state persisted BEFORE
  the step, not from a graceful shutdown path.
- MXNET_TPU_FAULT_INJECT="nan:step:N[:param]" is the NUMERICS fault:
  instead of killing the process it poisons one gradient tensor with
  NaN on-device at fused step N (parse_nan_inject, consumed by
  FusedTrainStep at trace time). The run keeps going — the point is
  to exercise mxnet_tpu.numerics detection + first-bad-op attribution
  (ci/check_numerics.py).
"""
from __future__ import annotations

import glob
import os
import re

from . import model as _model
from .base import MXNetError
from .telemetry import flight as _flight


def latest_checkpoint(prefix):
    """Newest saved epoch for `prefix`, or None."""
    pat = re.compile(
        re.escape(os.path.basename(prefix)) + r"-(\d{4})\.params$"
    )
    best = None
    for path in glob.glob(prefix + "-*.params"):
        m = pat.search(os.path.basename(path))
        if m:
            ep = int(m.group(1))
            best = ep if best is None else max(best, ep)
    return best


def data_state_path(prefix):
    """Where fit_auto_resume persists the input-stream position."""
    return prefix + "-data-state.json"


def parse_nan_inject(spec=None):
    """Parse the numerics fault spec 'nan:step:N[:param]' from `spec`
    or MXNET_TPU_FAULT_INJECT. Returns (step, param_or_None), or None
    when the spec is absent/not a nan fault. The kill-style 'epoch:N' /
    'step:N' specs return None here, and 'nan:...' harmlessly matches
    neither branch of FaultInjector — the two consumers are disjoint."""
    if spec is None:
        spec = os.environ.get("MXNET_TPU_FAULT_INJECT", "")
    parts = spec.split(":")
    if len(parts) < 3 or parts[0] != "nan" or parts[1] != "step":
        return None
    try:
        step = int(parts[2])
    except ValueError:
        raise MXNetError(f"bad nan fault spec {spec!r}: step must be "
                         "an integer ('nan:step:N[:param]')")
    param = parts[3] if len(parts) > 3 and parts[3] else None
    return (step, param)


class FaultInjector(object):
    """Deterministic crash injection for resilience tests. Spec comes
    from MXNET_TPU_FAULT_INJECT: 'epoch:N' fires after the checkpoint
    of epoch N is durable; 'step:N' fires when the global batch
    counter reaches N (mid-epoch — the hard resume case). 'kill:step:N'
    is the no-teardown form: SIGKILL to our own pid instead of a
    Python exception. Fires once."""

    def __init__(self, spec=None):
        self.spec = spec if spec is not None else os.environ.get(
            "MXNET_TPU_FAULT_INJECT", ""
        )
        self._steps = 0

    def _parse(self):
        kind, _, val = self.spec.partition(":")
        if kind == "kill":
            # "kill:step:N" — the mode is the second field, SIGKILL
            # the delivery. Only step-keyed kills exist: epoch
            # boundaries are already durable, killing there is the
            # easy case the soak is not interested in.
            sub, _, n = val.partition(":")
            if sub != "step":
                raise MXNetError(
                    f"bad kill fault spec {self.spec!r}: expected "
                    "'kill:step:N'")
            return "kill", n
        return kind, val

    def maybe_fail(self, epoch):
        if not self.spec:
            return
        kind, val = self._parse()
        if kind == "epoch" and epoch == int(val):
            # last-N-spans + full stats snapshot on disk BEFORE the
            # crash propagates (MXNET_TELEMETRY_FLIGHT_DIR; no-op off)
            _flight.maybe_dump(f"fault_injector:{self.spec}")
            raise RuntimeError(
                f"[fault-injection] simulated failure at epoch {epoch}"
            )

    def note_step(self):
        """One training batch completed; fires the 'step:N' spec when
        the global counter reaches N. Call AFTER the batch's state is
        durable — the resumed run must not re-see the batch that was
        live when the fault hit."""
        self._steps += 1
        if not self.spec:
            return
        kind, val = self._parse()
        if kind == "step" and self._steps == int(val):
            _flight.maybe_dump(f"fault_injector:{self.spec}")
            raise RuntimeError(
                f"[fault-injection] simulated failure at step "
                f"{self._steps}"
            )
        if kind == "kill" and self._steps == int(val):
            # flight record first — it is the only artifact a
            # SIGKILLed process leaves behind by choice
            _flight.maybe_dump(f"fault_injector:{self.spec}")
            import signal

            os.kill(os.getpid(), signal.SIGKILL)


def fit_auto_resume(module, train_data, prefix, num_epoch,
                    eval_data=None, fault_injector=None,
                    data_state=True, **fit_kwargs):
    """Module.fit with per-epoch durable checkpoints and automatic
    resume from the newest one. Returns the epoch training ended at.

    `data_state=True` (default) additionally checkpoints the input
    stream every batch when `train_data` has state_dict/load_state_dict
    (mxnet_tpu.data loaders): on restart the loader is wound to the
    exact (epoch, position) it died at BEFORE fit begins, so the
    killed epoch's remaining batches replay bit-identically."""
    if fault_injector is None:
        fault_injector = FaultInjector()
    begin_epoch = 0
    arg_params = aux_params = None
    resumed = latest_checkpoint(prefix)
    if resumed is not None:
        _, arg_params, aux_params = _model.load_checkpoint(
            prefix, resumed
        )
        begin_epoch = resumed
    if begin_epoch >= num_epoch:
        return begin_epoch

    injected = fault_injector
    track_data = data_state and hasattr(train_data, "state_dict") \
        and hasattr(train_data, "load_state_dict")
    state_path = data_state_path(prefix)

    if track_data:
        from .data.state import read_state

        st = read_state(state_path)
        # only rewind to saved data state that is AHEAD of the param
        # checkpoint we resume from — stale state from an older run
        # (lower epoch) must not drag the stream backwards
        if st is not None and int(st["epoch"]) >= begin_epoch:
            train_data.load_state_dict(st)

    batch_cbs = []
    user_batch_cb = fit_kwargs.pop("batch_end_callback", None)
    if user_batch_cb is not None:
        batch_cbs.extend(user_batch_cb if isinstance(user_batch_cb, list)
                         else [user_batch_cb])

    if track_data:
        from .data.state import save_state

        def data_state_cb(param):
            # durable BEFORE note_step can fire: a kill at step N
            # leaves position N on disk, so the resume starts at
            # batch N — never re-consuming nor skipping one
            save_state(train_data, state_path)
            injected.note_step()

        batch_cbs.append(data_state_cb)
    elif injected.spec.startswith("step"):
        def step_cb(param):
            injected.note_step()

        batch_cbs.append(step_cb)

    if "numerics" not in fit_kwargs:
        from . import numerics as _numerics
        from . import utils as _utils

        if _utils.getenv("MXNET_NUMERICS"):
            # auto-resumed runs get a run log next to the checkpoints
            # by default: the log's open() writes a resume marker, so
            # one JSONL file tells the whole kill/restart story
            fit_kwargs["numerics"] = _numerics.NumericsMonitor(
                run_log=_utils.getenv("MXNET_NUMERICS_RUNLOG")
                or (prefix + "-runlog.jsonl"))

    def epoch_cb(epoch, symbol, arg, aux):
        _model.save_checkpoint(
            prefix, epoch + 1, symbol, arg or {}, aux or {}
        )
        injected.maybe_fail(epoch + 1)

    module.fit(
        train_data, eval_data=eval_data,
        begin_epoch=begin_epoch, num_epoch=num_epoch,
        arg_params=arg_params, aux_params=aux_params,
        allow_missing=False,
        epoch_end_callback=[epoch_cb],
        batch_end_callback=batch_cbs or None,
        **fit_kwargs,
    )
    return num_epoch

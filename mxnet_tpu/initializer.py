"""Weight initializers.

Analog of the reference initializer registry
(python/mxnet/initializer.py:14-470): an `Initializer` is callable on
(InitDesc|name, NDArray) and dispatches on name patterns exactly like the
reference (`_init_weight` for `*weight`, `*bias`, `*gamma`, ... at
initializer.py:54-96), with attr-driven override via `InitDesc.attrs`
(`__init__` attr). TPU note: initializers fill host numpy then device_put
once — initialization is a one-time host->HBM transfer, not a jit'd
computation, matching how the reference fills NDArrays imperatively.
"""
from __future__ import annotations

import json
import math
import re

import numpy as np

from .base import MXNetError
from .random import np_rng

_INIT_REGISTRY: dict[str, type] = {}


def register(klass):
    """Register an initializer class under its lowercased name (analog of
    python/mxnet/initializer.py `register` + `alias`)."""
    name = klass.__name__.lower()
    _INIT_REGISTRY[name] = klass
    for alias in getattr(klass, "aliases", ()):
        _INIT_REGISTRY[alias.lower()] = klass
    return klass


class InitDesc(str):
    """Name + attrs descriptor handed to initializers (reference
    python/mxnet/initializer.py:30-46)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base: dispatch by name suffix; subclasses override _init_weight."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, InitDesc):
            desc = InitDesc(str(desc))
        if desc.global_init is None:
            desc.global_init = self
        init = desc.attrs.get("__init__", "")
        if init:
            # Variable-attached initializer: invoked via _init_weight
            # regardless of the name suffix (reference initializer.py:76-79)
            klass, kwargs = json.loads(init)
            create(klass, **kwargs)._init_weight(desc, arr)
        else:
            self._init_impl(desc, arr)

    def _init_impl(self, name, arr):
        if name.endswith("upsampling"):
            self._init_bilinear(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("_parameters"):
            # fused-RNN packed blob (ops/rnn_op.py names it
            # <name>_parameters, like the reference cudnn RNN op's
            # single parameter space) — weight-style init
            self._init_weight(name, arr)
        elif name.endswith(("_state", "_state_cell")):
            # RNN initial hidden/cell state inputs (ops/rnn_op.py
            # auto-created variables) start at zero
            self._init_zero(name, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(name, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(name, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(name, arr)
        else:
            self._init_default(name, arr)

    # ------------------------------------------------------------ fills
    def _set(self, arr, value):
        arr[:] = np.asarray(value, dtype=arr.dtype)

    def _init_bilinear(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("Initializer must override _init_weight")

    def _init_default(self, name, arr):
        raise MXNetError(
            f"Unknown initialization pattern for {name}. Default "
            "initialization is now limited to *weight/*bias/*gamma/*beta. "
            "Use mx.sym.Variable(init=...) to set initialization pattern."
        )


@register
class Zero(Initializer):
    aliases = ("zeros",)

    def _init_weight(self, _, arr):
        arr[:] = 0.0

    _init_default = _init_weight


@register
class One(Initializer):
    aliases = ("ones",)

    def _init_weight(self, _, arr):
        arr[:] = 1.0

    _init_default = _init_weight


@register
class Constant(Initializer):
    """Fill with a constant value unconditionally — unlike Zero/One (which
    keep the reference's suffix dispatch so a *global* Zero/One initializer
    still zeroes biases and ones gammas), an explicitly requested Constant
    has no other sensible meaning for any parameter name."""

    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value

    _init_impl = _init_weight


@register
class Uniform(Initializer):
    """U(-scale, scale) (reference initializer.py:214)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(
            arr, np_rng().uniform(-self.scale, self.scale, arr.shape)
        )


@register
class Normal(Initializer):
    """N(0, sigma) (reference initializer.py:230)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, np_rng().normal(0.0, self.sigma, arr.shape))


@register
class Orthogonal(Initializer):
    """Orthogonal basis weights (reference initializer.py:246: scale and
    rand_type='uniform'|'normal')."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np_rng().uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np_rng().normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else v
        self._set(arr, self.scale * res.reshape(arr.shape))


@register
class Xavier(Initializer):
    """Xavier/Glorot (reference initializer.py:278: rnd_type, factor_type,
    magnitude)."""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(
            rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude
        )
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise MXNetError(
                f"Xavier initializer cannot init {name} with shape {shape};"
                " use init=mx.init.Constant or similar for 1D arrays"
            )
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("Incorrect factor type")
        scale = math.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, np_rng().uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._set(arr, np_rng().normal(0, scale, shape))
        else:
            raise MXNetError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """He init for PReLU nets (reference initializer.py:327)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        self._init_bilinear(name, arr)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init for LSTM layers; bias layout [i f c o]
    (reference initializer.py:386)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden: 2 * num_hidden] = self.forget_bias
        self._set(arr, b)

    _init_bias = _init_weight


@register
class FusedRNN(Initializer):
    """Initialize a packed fused-RNN parameter blob by unpacking it into
    per-gate weights, applying `init`, and repacking (reference
    initializer.py:412-470)."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = create(klass, **kwargs)
        super().__init__(
            init=init.dumps() if init is not None else None,
            num_hidden=num_hidden, num_layers=num_layers, mode=mode,
            bidirectional=bidirectional, forget_bias=forget_bias,
        )
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .rnn.rnn_cell import FusedRNNCell

        cell = FusedRNNCell(
            self._num_hidden, self._num_layers, self._mode,
            self._bidirectional, forget_bias=self._forget_bias,
            prefix="",
        )
        host = np.array(
            arr.asnumpy() if hasattr(arr, "asnumpy") else arr, copy=True
        )
        args = cell.unpack_weights({"parameters": host})
        global_init = getattr(desc, "global_init", None)
        for name in args:
            desc2 = InitDesc(name, global_init=global_init)
            # forget-gate bias gets the configured constant (reference
            # initializer.py:512-514)
            if self._mode == "lstm" and name.endswith("_f_bias"):
                args[name][:] = self._forget_bias
            elif self._init is None:
                fallback = global_init or Uniform(0.1)
                fallback(desc2, args[name])
            else:
                self._init(desc2, args[name])
        arr[:] = cell.pack_weights(args)["parameters"]


class Load:
    """Initialize from a dict of arrays, falling back to default_init
    (reference initializer.py:96-131)."""

    def __init__(self, param, default_init=None, verbose=False):
        qualified = {}
        for name, arr in param.items():
            if name.startswith("arg:") or name.startswith("aux:"):
                qualified[name[4:]] = arr
            else:
                qualified[name] = arr
        self.param = qualified
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            src = self.param[name]
            if tuple(src.shape) != tuple(arr.shape):
                raise MXNetError(
                    f"Parameter {name} cannot be initialized from loading. "
                    f"Shape mismatch, target {arr.shape} vs loaded "
                    f"{src.shape}"
                )
            arr[:] = src
        else:
            if self.default_init is None:
                raise MXNetError(
                    f"Cannot Initialize parameter {name}; not found in "
                    "loaded param and no default initializer"
                )
            self.default_init(name, arr)


class Mixed:
    """Regex-pattern-dispatched initializer list (reference
    initializer.py:134-166)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must be same length")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError(
            f"Parameter name {name} did not match any pattern. Add a "
            '".*" pattern at the end with default Initializer.'
        )


def create(name, **kwargs):
    """Create an initializer by registered name (or pass through)."""
    if isinstance(name, Initializer):
        return name
    key = name.lower()
    if key not in _INIT_REGISTRY:
        raise MXNetError(f"unknown initializer {name!r}")
    return _INIT_REGISTRY[key](**kwargs)

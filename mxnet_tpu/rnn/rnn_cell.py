"""Symbolic RNN cells.

Capability parity with the reference cell library
(python/mxnet/rnn/rnn_cell.py:317-881): RNNCell / LSTMCell / GRUCell
build one-timestep symbolic graphs that `unroll` chains over time;
FusedRNNCell emits the fused `RNN` op (the cuDNN-RNN analog — here a
`lax.scan` whose per-layer input projections are single MXU matmuls, see
ops/rnn_op.py) and converts to/from the unfused layout with
unpack_weights / pack_weights; Sequential / Bidirectional / Dropout /
Zoneout compose cells.

TPU-native deviation from the reference: `begin_state` default zero
states use batch dimension **1** (broadcast at use) instead of the
reference's 0-meaning-unknown, because shape inference here is forward
only (jax.eval_shape) — broadcasting a constant initial state is exact,
and a user-supplied begin_state with a real batch dimension is passed
through untouched.
"""
from __future__ import annotations

import numpy as np

from .. import symbol
from ..base import MXNetError
from ..ops.rnn_op import MODE_GATES, param_layout, rnn_param_size


class RNNParams(object):
    """Container for cell parameters; get() memoizes Variables by name
    (reference rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    """Abstract cell: __call__(inputs, states) -> (output, states)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        """List of dicts describing each state: {'shape': ..., '__layout__': ...}."""
        raise NotImplementedError

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ("",)

    def begin_state(self, func=None, **kwargs):
        """Initial states; default zeros with broadcastable batch dim 1."""
        assert not self._modified, (
            "After applying modifier cells (e.g. DropoutCell) the base "
            "cell cannot be called directly. Call the modifier cell instead."
        )
        if func is None:
            func = symbol.zeros
        states = []
        for info in self.state_info:
            self._init_counter += 1
            name = f"{self._prefix}begin_state_{self._init_counter}"
            state = func(name=name, shape=info["shape"], **kwargs)
            states.append(state)
        return states

    # ----------------------------------------------- fused<->unfused weights
    def unpack_weights(self, args):
        """Split gate-concatenated weights into per-gate entries
        (reference rnn_cell.py unpack_weights)."""
        args = dict(args)
        h = self._num_hidden
        for group_name in ("i2h", "h2h"):
            for t in ("weight", "bias"):
                name = f"{self._prefix}{group_name}_{t}"
                if name not in args:
                    continue
                arr = args.pop(name)
                for i, gate in enumerate(self._gate_names):
                    args[f"{self._prefix}{group_name}{gate}_{t}"] = (
                        arr[i * h: (i + 1) * h].copy()
                    )
        return args

    def pack_weights(self, args):
        args = dict(args)
        for group_name in ("i2h", "h2h"):
            for t in ("weight", "bias"):
                gates = [
                    f"{self._prefix}{group_name}{gate}_{t}"
                    for gate in self._gate_names
                ]
                if not all(g in args for g in gates):
                    continue
                args[f"{self._prefix}{group_name}_{t}"] = np.concatenate(
                    [np.asarray(args.pop(g)) for g in gates]
                )
        return args

    # ------------------------------------------------------------- unroll
    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        """Unroll the cell `length` steps (reference rnn_cell.py:254).

        inputs: None (auto Variables t%d_data), a list of per-step
        symbols, or one symbol with a time axis per `layout`.
        Returns (outputs, final_states); outputs is a list unless
        merge_outputs=True (then one symbol with the same layout).
        """
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [
                symbol.Variable(f"{input_prefix}t{i}_data")
                for i in range(length)
            ]
        elif isinstance(inputs, symbol.Symbol):
            assert len(inputs.list_outputs()) == 1, (
                "unroll doesn't allow grouped symbol as input"
            )
            inputs = symbol.SliceChannel(
                inputs, axis=axis, num_outputs=length, squeeze_axis=1
            )
            inputs = [inputs[i] for i in range(length)]
        else:
            assert len(inputs) == length
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = [
                symbol.expand_dims(o, axis=axis) for o in outputs
            ]
            outputs = symbol.Concat(*outputs, dim=axis)
        return outputs, states

    # ------------------------------------------------------------ helpers
    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return symbol.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """Vanilla RNN: h' = act(W_i2h x + b + W_h2h h + b) (reference
    rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (1, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = symbol.FullyConnected(
            data=inputs, weight=self._iW, bias=self._iB,
            num_hidden=self._num_hidden, name=f"{name}i2h",
        )
        h2h = symbol.FullyConnected(
            data=states[0], weight=self._hW, bias=self._hB,
            num_hidden=self._num_hidden, name=f"{name}h2h",
        )
        output = self._get_activation(
            i2h + h2h, self._activation, name=f"{name}out"
        )
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell; gate order [i f c o] matches the fused layout
    (reference rnn_cell.py LSTMCell; gate order rnn_cell.py:497)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        from .. import initializer as init

        self._iB = self.params.get(
            "i2h_bias", init=init.LSTMBias(forget_bias=forget_bias)
        )
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [
            {"shape": (1, self._num_hidden), "__layout__": "NC"},
            {"shape": (1, self._num_hidden), "__layout__": "NC"},
        ]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = symbol.FullyConnected(
            data=inputs, weight=self._iW, bias=self._iB,
            num_hidden=self._num_hidden * 4, name=f"{name}i2h",
        )
        h2h = symbol.FullyConnected(
            data=states[0], weight=self._hW, bias=self._hB,
            num_hidden=self._num_hidden * 4, name=f"{name}h2h",
        )
        gates = i2h + h2h
        slice_gates = symbol.SliceChannel(
            gates, num_outputs=4, axis=1, name=f"{name}slice"
        )
        in_gate = symbol.Activation(slice_gates[0], act_type="sigmoid")
        forget_gate = symbol.Activation(slice_gates[1], act_type="sigmoid")
        in_transform = symbol.Activation(slice_gates[2], act_type="tanh")
        out_gate = symbol.Activation(slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * symbol.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell; gate order [r z o] matches the fused layout (reference
    rnn_cell.py GRUCell)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (1, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        prev_state_h = states[0]
        i2h = symbol.FullyConnected(
            data=inputs, weight=self._iW, bias=self._iB,
            num_hidden=self._num_hidden * 3, name=f"{name}i2h",
        )
        h2h = symbol.FullyConnected(
            data=prev_state_h, weight=self._hW, bias=self._hB,
            num_hidden=self._num_hidden * 3, name=f"{name}h2h",
        )
        i2h_r, i2h_z, i2h = symbol.SliceChannel(
            i2h, num_outputs=3, axis=1, name=f"{name}i2h_slice"
        )
        h2h_r, h2h_z, h2h = symbol.SliceChannel(
            h2h, num_outputs=3, axis=1, name=f"{name}h2h_slice"
        )
        reset_gate = symbol.Activation(
            i2h_r + h2h_r, act_type="sigmoid", name=f"{name}r_act"
        )
        update_gate = symbol.Activation(
            i2h_z + h2h_z, act_type="sigmoid", name=f"{name}z_act"
        )
        next_h_tmp = symbol.Activation(
            i2h + reset_gate * h2h, act_type="tanh", name=f"{name}h_act"
        )
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_state_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Multi-layer fused RNN over the `RNN` op (reference rnn_cell.py
    FusedRNNCell, which maps to cuDNN; here the op is a lax.scan — see
    ops/rnn_op.py). Only usable via unroll()."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        if mode not in MODE_GATES:
            raise MXNetError(f"unknown RNN mode {mode!r}")
        initializer = None
        if mode == "lstm":
            from .. import initializer as init

            initializer = init.FusedRNN(
                None, num_hidden, num_layers, mode, bidirectional,
                forget_bias,
            )
        self._parameter = self.params.get("parameters", init=initializer)
        self._directions = (
            ["l", "r"] if bidirectional else ["l"]
        )

    @property
    def state_info(self):
        b = self._num_layers * (2 if self._bidirectional else 1)
        n = (
            [
                {"shape": (b, 1, self._num_hidden), "__layout__": "LNC"},
                {"shape": (b, 1, self._num_hidden), "__layout__": "LNC"},
            ]
            if self._mode == "lstm"
            else [{"shape": (b, 1, self._num_hidden), "__layout__": "LNC"}]
        )
        return n

    @property
    def _gate_names(self):
        return {
            "rnn_relu": ("",),
            "rnn_tanh": ("",),
            "lstm": ("_i", "_f", "_c", "_o"),
            "gru": ("_r", "_z", "_o"),
        }[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def _input_size_from_total(self, total):
        """Solve the layer-0 input size from the flat blob length."""
        h = self._num_hidden
        g = self._num_gates
        L = self._num_layers
        dirs = 2 if self._bidirectional else 1
        # total = dirs*g*h*(I + h) + (L-1)*dirs*g*h*(h*dirs + h) + 2*g*h*L*dirs
        rest = (
            (L - 1) * dirs * g * h * (h * dirs + h)
            + 2 * g * h * L * dirs
            + dirs * g * h * h
        )
        rem = total - rest
        assert rem % (dirs * g * h) == 0, (
            f"invalid fused parameter size {total}"
        )
        return rem // (dirs * g * h)

    def unpack_weights(self, args):
        """Flat blob -> per-gate numpy arrays named
        {prefix}{l|r}{layer}_{i2h,h2h}{gate}_{weight,bias} — the same
        naming the equivalent unfuse()d cell stack uses after its own
        unpack_weights, so fused and unfused parameters interconvert
        (reference rnn_cell.py FusedRNNCell.unpack_weights)."""
        args = dict(args)
        arr = np.asarray(args.pop(self._prefix + "parameters"))
        input_size = self._input_size_from_total(arr.size)
        entries, total = param_layout(
            input_size, self._num_hidden, self._num_layers,
            self._bidirectional, self._mode,
        )
        assert total == arr.size
        h = self._num_hidden
        for (kind, layer, d, part), (off, shape) in entries.items():
            size = int(np.prod(shape))
            t = "weight" if kind == "w" else "bias"
            block = arr[off: off + size].reshape(shape)
            base = f"{self._prefix}{self._directions[d]}{layer}_{part}"
            for i, gate in enumerate(self._gate_names):
                args[f"{base}{gate}_{t}"] = (
                    block[i * h: (i + 1) * h].copy()
                )
        return args

    def pack_weights(self, args):
        args = dict(args)
        g0 = self._gate_names[0]
        probe = np.asarray(args[f"{self._prefix}l0_i2h{g0}_weight"])
        input_size = probe.shape[1]
        entries, total = param_layout(
            input_size, self._num_hidden, self._num_layers,
            self._bidirectional, self._mode,
        )
        arr = np.zeros((total,), dtype=np.float32)
        for (kind, layer, d, part), (off, shape) in entries.items():
            t = "weight" if kind == "w" else "bias"
            base = f"{self._prefix}{self._directions[d]}{layer}_{part}"
            block = np.concatenate(
                [
                    np.asarray(args.pop(f"{base}{gate}_{t}"),
                               dtype=np.float32)
                    for gate in self._gate_names
                ]
            )
            size = int(np.prod(shape))
            arr[off: off + size] = block.reshape(-1)
        args[self._prefix + "parameters"] = arr
        return args

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "FusedRNNCell cannot be stepped. Please use unroll"
        )

    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [
                symbol.Variable(f"{input_prefix}t{i}_data")
                for i in range(length)
            ]
        if isinstance(inputs, symbol.Symbol):
            assert len(inputs.list_outputs()) == 1, (
                "unroll doesn't allow grouped symbol as input"
            )
            if axis == 1:
                inputs = symbol.SwapAxis(inputs, dim1=0, dim2=1)
        else:
            assert len(inputs) == length
            inputs = [symbol.expand_dims(i, axis=0) for i in inputs]
            inputs = symbol.Concat(*inputs, dim=0)
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state

        kwargs = dict(
            state_size=self._num_hidden,
            num_layers=self._num_layers,
            bidirectional=self._bidirectional,
            p=self._dropout,
            state_outputs=self._get_next_state,
            mode=self._mode,
            name=self._prefix + "rnn",
        )
        if self._mode == "lstm":
            rnn = symbol.RNN(
                data=inputs, parameters=self._parameter,
                state=states[0], state_cell=states[1], **kwargs
            )
        else:
            rnn = symbol.RNN(
                data=inputs, parameters=self._parameter,
                state=states[0], **kwargs
            )
        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]

        if merge_outputs is None:
            merge_outputs = False
        if not merge_outputs:
            outputs = symbol.SliceChannel(
                outputs, axis=0, num_outputs=length, squeeze_axis=1
            )
            outputs = [outputs[i] for i in range(length)]
        elif axis == 1:
            outputs = symbol.SwapAxis(outputs, dim1=0, dim2=1)
        return outputs, states

    def unfuse(self):
        """Equivalent SequentialRNNCell of unfused cells (reference
        rnn_cell.py FusedRNNCell.unfuse)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda pre: RNNCell(
                self._num_hidden, activation="relu", prefix=pre
            ),
            "rnn_tanh": lambda pre: RNNCell(
                self._num_hidden, activation="tanh", prefix=pre
            ),
            "lstm": lambda pre: LSTMCell(self._num_hidden, prefix=pre),
            "gru": lambda pre: GRUCell(self._num_hidden, prefix=pre),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(
                    BidirectionalCell(
                        get_cell(f"{self._prefix}l{i}_"),
                        get_cell(f"{self._prefix}r{i}_"),
                        output_prefix=f"{self._prefix}bi_{i}_",
                    )
                )
            else:
                stack.add(get_cell(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(
                    self._dropout, prefix=f"{self._prefix}_dropout{i}_"
                ))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in order each step (reference rnn_cell.py
    SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._override_cell_params = params is not None
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            assert cell._own_params, (
                "Either specify params for SequentialRNNCell or child "
                "cells, not both."
            )
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        assert not self._modified
        return [
            state for c in self._cells for state in c.begin_state(**kwargs)
        ]

    def unpack_weights(self, args):
        for c in self._cells:
            args = c.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for c in self._cells:
            args = c.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p: p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        """Chain child unrolls (so unroll-only children like
        BidirectionalCell compose); intermediate stages pass per-step
        lists, only the last stage honors merge_outputs."""
        self.reset()
        if begin_state is None:
            begin_state = self.begin_state()
        p = 0
        next_states = []
        last = len(self._cells) - 1
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p: p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states,
                input_prefix=input_prefix, layout=layout,
                merge_outputs=merge_outputs if i == last else None,
            )
            next_states.extend(states)
        return inputs, next_states

    def reset(self):
        super().reset()
        for c in getattr(self, "_cells", ()):
            c.reset()


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cells; unroll-only (reference rnn_cell.py
    BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        self._override_cell_params = params is not None
        if self._override_cell_params:
            assert l_cell._own_params and r_cell._own_params, (
                "Either specify params for BidirectionalCell or child "
                "cells, not both."
            )
            l_cell.params._params.update(self.params._params)
            r_cell.params._params.update(self.params._params)
        self.params._params.update(l_cell.params._params)
        self.params._params.update(r_cell.params._params)
        self._cells = [l_cell, r_cell]

    def unpack_weights(self, args):
        return self._cells[0].unpack_weights(
            self._cells[1].unpack_weights(args)
        )

    def pack_weights(self, args):
        return self._cells[0].pack_weights(
            self._cells[1].pack_weights(args)
        )

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cell cannot be stepped. Please use unroll"
        )

    @property
    def state_info(self):
        return [info for c in self._cells for info in c.state_info]

    def begin_state(self, **kwargs):
        assert not self._modified
        return [
            state for c in self._cells for state in c.begin_state(**kwargs)
        ]

    def unroll(self, length, inputs=None, begin_state=None,
               input_prefix="", layout="NTC", merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if inputs is None:
            inputs = [
                symbol.Variable(f"{input_prefix}t{i}_data")
                for i in range(length)
            ]
        elif isinstance(inputs, symbol.Symbol):
            inputs = symbol.SliceChannel(
                inputs, axis=axis, num_outputs=length, squeeze_axis=1
            )
            inputs = [inputs[i] for i in range(length)]
        else:
            assert len(inputs) == length
        if begin_state is None:
            begin_state = self.begin_state()
        states = begin_state
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l],
            layout=layout, merge_outputs=False,
        )
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[n_l:], layout=layout, merge_outputs=False,
        )
        r_outputs = list(reversed(r_outputs))
        outputs = [
            symbol.Concat(
                l_o, r_o, dim=1,
                name=f"{self._output_prefix}t{i}",
            )
            for i, (l_o, r_o) in enumerate(zip(l_outputs, r_outputs))
        ]
        if merge_outputs:
            outputs = [
                symbol.expand_dims(o, axis=axis) for o in outputs
            ]
            outputs = symbol.Concat(*outputs, dim=axis)
        states = l_states + r_states
        return outputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells that wrap another cell (reference rnn_cell.py
    ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)

    def __call__(self, inputs, states):
        raise NotImplementedError


class DropoutCell(BaseRNNCell):
    """Applies dropout on the input (reference rnn_cell.py DropoutCell)."""

    def __init__(self, dropout=0.0, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = symbol.Dropout(data=inputs, p=self.dropout)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization on a base cell (reference rnn_cell.py
    ZoneoutCell): with probability z keep the previous state."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), (
            "FusedRNNCell doesn't support zoneout. Please unfuse first."
        )
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (
            self.base_cell, self.zoneout_outputs, self.zoneout_states
        )
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            return symbol.Dropout(
                symbol.ones_like(like), p=p
            )

        prev_output = (
            self.prev_output
            if self.prev_output is not None
            else symbol.zeros_like(next_output)
        )
        output = (
            symbol.where(
                mask(p_outputs, next_output), next_output, prev_output
            )
            if p_outputs != 0.0
            else next_output
        )
        states = (
            [
                symbol.where(mask(p_states, new_s), new_s, old_s)
                for new_s, old_s in zip(next_states, states)
            ]
            if p_states != 0.0
            else next_states
        )
        self.prev_output = output
        return output, states

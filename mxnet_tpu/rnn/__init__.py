"""Symbolic RNN cell library (capability parity with the reference
python/mxnet/rnn/: rnn_cell.py cells, io.py BucketSentenceIter, rnn.py
checkpoint helpers)."""
from .rnn_cell import (
    RNNParams,
    BaseRNNCell,
    RNNCell,
    LSTMCell,
    GRUCell,
    FusedRNNCell,
    SequentialRNNCell,
    BidirectionalCell,
    ModifierCell,
    DropoutCell,
    ZoneoutCell,
)
from .io import BucketSentenceIter, encode_sentences
from .rnn import (
    save_rnn_checkpoint,
    load_rnn_checkpoint,
    do_rnn_checkpoint,
)

"""RNN checkpoint helpers (reference python/mxnet/rnn/rnn.py):
save/load model checkpoints with cell weights unpacked into the
canonical (unfused, per-gate) layout so fused and unfused models are
checkpoint-compatible."""
from __future__ import annotations

from .. import model as _model
from .. import ndarray as nd


def _as_cell_list(cells):
    return cells if isinstance(cells, (list, tuple)) else [cells]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """Save a checkpoint with RNN weights unpacked (reference
    rnn/rnn.py save_rnn_checkpoint)."""
    host = {k: v.asnumpy() if hasattr(v, "asnumpy") else v
            for k, v in arg_params.items()}
    for cell in _as_cell_list(cells):
        host = cell.unpack_weights(host)
    arg_np = {k: nd.array(v) for k, v in host.items()}
    _model.save_checkpoint(prefix, epoch, symbol, arg_np, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load a checkpoint and pack RNN weights for the given cells
    (reference rnn/rnn.py load_rnn_checkpoint)."""
    sym, arg, aux = _model.load_checkpoint(prefix, epoch)
    host = {k: v.asnumpy() if hasattr(v, "asnumpy") else v
            for k, v in arg.items()}
    for cell in _as_cell_list(cells):
        host = cell.pack_weights(host)
    arg = {k: nd.array(v) for k, v in host.items()}
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback doing save_rnn_checkpoint (reference
    rnn/rnn.py do_rnn_checkpoint)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback

"""Analytic-first cost model: FLOPs + bytes-moved, padding-aware.

The Kaufman et al. TPU performance-model lesson (PAPERS.md): most of a
graph's cost on TPU is explained by two analytic terms — MXU FLOPs and
HBM bytes — *provided* the byte term accounts for tiling: the VPU/MXU
consume (sublane, lane) tiles of (8, 128) for f32 (16 for 2-byte, 32
for 1-byte dtypes), so a tensor whose minor dims don't fill a tile
pays for the padded tile anyway. `padding_waste` makes that visible,
and the layout chooser below is exactly "which orientation wastes
fewer padded bytes at the conv/pool tensors".

FLOPs reuse the analytic 2-per-MAC convention of `utils.flops`
(matmul-class ops only); the byte term covers every node.
"""
from __future__ import annotations

import numpy as np

TILE_LANES = 128


def tile_sublanes(dtype):
    """Sublane count of the (sublane, lane) register tile: 8 rows of
    f32, doubling as the element narrows (bf16 -> 16, int8 -> 32)."""
    itemsize = np.dtype(dtype).itemsize
    return max(8, 32 // max(itemsize, 1))


def _ceil_to(x, m):
    return ((int(x) + m - 1) // m) * m


def padded_elems(shape, dtype):
    """Element count after padding the two minor dims up to the tile
    grid (lane dim -> 128, sublane dim -> dtype sublanes). Scalars and
    1-D tensors occupy one sublane row."""
    shape = tuple(int(d) for d in shape)
    if not shape:
        return TILE_LANES * 1
    lanes = _ceil_to(shape[-1], TILE_LANES)
    if len(shape) == 1:
        return lanes
    sub = _ceil_to(shape[-2], tile_sublanes(dtype))
    out = lanes * sub
    for d in shape[:-2]:
        out *= d
    return out


def _nbytes(shape, dtype, padded):
    itemsize = np.dtype(dtype).itemsize
    if padded:
        return padded_elems(shape, dtype) * itemsize
    n = itemsize
    for d in shape:
        n *= int(d)
    return n


def graph_costs(symbol, **input_shapes):
    """Per-node analytic costs at the given input shapes.

    Returns {"total_flops", "total_bytes", "padded_bytes",
    "padding_waste", "by_node": {name: {flops, bytes, padded_bytes}}}.
    `bytes` per node = inputs read + outputs written (the op's minimum
    HBM traffic, ignoring fusion); `padding_waste` is the fraction of
    padded traffic that is tile fill, 0 when every tensor tiles
    exactly."""
    from ..symbol import _graph_infer, _topo
    from ..utils.flops import count_flops

    known = {k: tuple(v) for k, v in input_shapes.items()}
    shapes, dtypes = _graph_infer(symbol._outputs, known, {},
                                  partial=True)
    flops_by_node = count_flops(symbol, **input_shapes)["by_op"]

    by_node = {}
    total_bytes = 0
    total_padded = 0
    for n in _topo(symbol._outputs):
        if n.is_variable:
            continue
        params = n.op.normalize_params(n.attrs)
        n_out = n.op.resolved_num_outputs(params)
        tensors = [(src, i) for src, i in n.inputs]
        tensors += [(n, i) for i in range(n_out)]
        raw = padded = 0
        for key in tensors:
            s = shapes.get(key)
            if s is None:
                continue
            dt = np.dtype(dtypes.get(key, np.float32))
            raw += _nbytes(s, dt, padded=False)
            padded += _nbytes(s, dt, padded=True)
        by_node[n.name] = {
            "flops": float(flops_by_node.get(n.name, 0.0)),
            "bytes": raw,
            "padded_bytes": padded,
        }
        total_bytes += raw
        total_padded += padded
    waste = (1.0 - total_bytes / total_padded) if total_padded else 0.0
    return {
        "total_flops": sum(v["flops"] for v in by_node.values()),
        "total_bytes": total_bytes,
        "padded_bytes": total_padded,
        "padding_waste": waste,
        "by_node": by_node,
    }


# ----------------------------------------------- time estimates (s)
# HBM-class streaming bandwidth by platform — the same byte-model
# constants the autotuner's analytic multistep choice uses
_PLATFORM_BANDWIDTH = {"tpu": 8e11}
_DEFAULT_BANDWIDTH = 2e11


def analytic_step_s(symbol, input_shapes, platform):
    """Analytic wall-seconds estimate of one forward: the graph
    streams its tile-padded bytes at the platform's HBM-class
    bandwidth (the byte term dominates on TPU for the memory-bound
    majority; the flop term is folded into the same constants)."""
    costs = graph_costs(symbol, **{k: tuple(v)
                                   for k, v in input_shapes.items()})
    bandwidth = _PLATFORM_BANDWIDTH.get(platform, _DEFAULT_BANDWIDTH)
    return max(costs["padded_bytes"] / bandwidth, 1e-7)


def calibrated_cost(symbol, input_shapes, platform=None,
                    kind="forward", store=None):
    """Best available step-time estimate, measured-first.

    Preference order is PINNED (ci/check_profiling.py asserts it):
      1. a measured record in the CalibrationStore for (canonical
         digest, platform, kind) — real device seconds harvested
         during serving/decoding warmup or fit epochs,
      2. the analytic byte model (`analytic_step_s`).

    Returns {"est_s", "source" ("measured"|"analytic"), "analytic_s",
    "measured_s", "digest", "platform", "kind"} — both estimates are
    always present when computable, `est_s` is the preferred one."""
    if platform is None:
        import jax

        platform = jax.default_backend()
    digest = symbol.canonical_signature()
    if store is None:
        from ..profiling import calibration_store

        store = calibration_store()
    measured = store.measured_seconds(digest, platform, kind)
    try:
        analytic = analytic_step_s(symbol, input_shapes, platform)
    except Exception:
        analytic = None  # uninferable shapes: measured-only or nothing
    if measured is not None:
        est, source = measured, "measured"
    elif analytic is not None:
        est, source = analytic, "analytic"
    else:
        est, source = None, "none"
    return {
        "est_s": est,
        "source": source,
        "analytic_s": analytic,
        "measured_s": measured,
        "digest": digest,
        "platform": platform,
        "kind": kind,
    }


# ------------------------------------------------------- layout choice
def _conv_pool_tensors(symbol, input_shapes):
    """(shape, dtype) of every data/output tensor at 2-D Convolution /
    Pooling nodes — the tensors a layout rewrite would reorient."""
    from ..symbol import _graph_infer, _topo

    known = {k: tuple(v) for k, v in input_shapes.items()}
    shapes, dtypes = _graph_infer(symbol._outputs, known, {},
                                  partial=True)
    out = []
    for n in _topo(symbol._outputs):
        if n.is_variable or n.op.name not in ("Convolution", "Pooling"):
            continue
        params = n.op.normalize_params(n.attrs)
        if str(params.get("layout") or "NCHW") != "NCHW":
            continue
        for key in [n.inputs[0], (n, 0)]:
            s = shapes.get(key)
            if s is not None and len(s) == 4:
                out.append((s, np.dtype(dtypes.get(key, np.float32))))
    return out


def layout_padded_bytes(symbol, input_shapes, layout):
    """Padded HBM bytes of the conv/pool activations under `layout`
    ("NCHW" or "NHWC"); shapes given in NCHW."""
    total = 0
    for s, dt in _conv_pool_tensors(symbol, input_shapes):
        if layout == "NHWC":
            s = (s[0], s[2], s[3], s[1])
        total += _nbytes(s, dt, padded=True)
    return total


def choose_layout(symbol, input_shapes, platform):
    """Analytic layout pick: NHWC only where it is the native tiling
    (TPU) AND the padded-byte model agrees it does not lose (C on the
    128-lane dim usually wins for C >= 32; tiny-C stem layers can go
    either way, the model decides)."""
    if platform != "tpu":
        return "NCHW"
    nchw = layout_padded_bytes(symbol, input_shapes, "NCHW")
    if nchw == 0:
        return "NCHW"  # no conv/pool tensors to reorient
    nhwc = layout_padded_bytes(symbol, input_shapes, "NHWC")
    return "NHWC" if nhwc <= nchw else "NCHW"

"""Autotuner: per-(canonical graph, platform) tuning choices.

Picks the three knobs the rest of the stack already understands —
`layout` (the opt-in layout pass), `multistep_k` (steps fused per
dispatch, module/executor_group multistep), `bucket_grid` (the
(batch,) padding grid the serving tier warms) — analytic-first from
`cost_model`, optionally refined by an on-device measurement
(`measure=True` binds the graph and times real forwards).

Choices persist as JSON at MXNET_TUNING_CACHE (default
~/.cache/mxnet_tpu/tuning.json) keyed by `"{canonical_digest}:
{platform}"`, so a graph tuned once is tuned forever: the digest is
the canonical-pipeline signature, meaning every differently-built
isomorphic variant of a network maps to the one cached record.
"""
from __future__ import annotations

import json
import os
import threading
import time

# fused-multistep dispatch window the measured refinement targets: big
# enough to amortize host dispatch, small enough to keep host metrics
# fresh (~one progress-bar tick)
_TARGET_WINDOW_S = 2e-3
_MULTISTEP_CHOICES = (1, 2, 4, 8, 16, 32)


def _default_cache_path():
    from ..utils import getenv

    return os.path.expanduser(str(getenv("MXNET_TUNING_CACHE")))


def _pow2_grid(n):
    """Powers of two up to and including the first >= n."""
    out = [1]
    while out[-1] < int(n):
        out.append(out[-1] * 2)
    return out


class Autotuner:
    """choose() -> {"layout", "multistep_k", "bucket_grid"} for a
    (symbol, shapes, platform), cached across processes."""

    def __init__(self, cache_path=None):
        self.cache_path = cache_path or _default_cache_path()
        self._lock = threading.Lock()
        # records chosen by this process; every save persists the full
        # set, so a lost disk write is healed by the next one
        self._local = {}

    # ------------------------------------------------------ persistence
    def _load(self):
        try:
            with open(self.cache_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}

    def _save(self, table):
        from ..utils.persist import atomic_write_json

        # atomic vs concurrent tuners (tmp + fsync + os.replace)
        atomic_write_json(self.cache_path, table)

    # ----------------------------------------------------------- choice
    def choose(self, symbol, input_shapes, platform=None, measure=False):
        """Tuning record for `symbol` at `input_shapes` on `platform`
        (default: the active jax backend). Cached records win; a
        `measure=True` record wins over a cached analytic one."""
        from . import cost_model as _cm

        if platform is None:
            import jax

            platform = jax.default_backend()
        digest = symbol.canonical_signature()
        key = f"{digest}:{platform}"
        # disk I/O happens OUTSIDE self._lock: _load is a read of an
        # atomically-replaced file and needs no exclusion, and holding
        # a lock across filesystem latency stalls every other tuning
        # thread. The lock guards only the in-memory merge below.
        cached = self._load().get(key)
        if cached is None:
            with self._lock:
                cached = self._local.get(key)
        if cached is not None and cached.get("source") == "measured":
            return cached
        # calibration upgrade: a measured forward time harvested into
        # the CalibrationStore (profiling) refines an analytic record
        # for free — no on-device measurement run needed here
        calibrated_s = _calibration_forward_s(digest, platform)
        if cached is not None and not measure:
            if (calibrated_s is not None
                    and cached.get("source") == "analytic"):
                record = dict(cached)
                record["multistep_k"] = _k_for_window(calibrated_s)
                record["measured_forward_s"] = calibrated_s
                record["source"] = "calibrated"
                self._persist(key, record)
                return record
            return cached

        shapes = {k: tuple(v) for k, v in input_shapes.items()}
        record = {
            "layout": _cm.choose_layout(symbol, shapes, platform),
            "multistep_k": self._analytic_multistep(
                symbol, shapes, platform),
            "bucket_grid": _pow2_grid(self._batch_of(shapes)),
            "platform": platform,
            "source": "analytic",
        }
        if measure:
            step_s = _measured_forward_s(symbol, shapes)
            if step_s is not None:
                record["multistep_k"] = _k_for_window(step_s)
                record["measured_forward_s"] = step_s
                record["source"] = "measured"
        elif calibrated_s is not None:
            record["multistep_k"] = _k_for_window(calibrated_s)
            record["measured_forward_s"] = calibrated_s
            record["source"] = "calibrated"
        self._persist(key, record)
        return record

    def _persist(self, key, record):
        """Adopt `record` locally and best-effort save: merge this
        process's full record set over the current disk table and
        replace atomically. A concurrent external writer can win the
        race for one save, but the next save here re-merges
        everything in _local, so a lost record only costs a re-tune."""
        with self._lock:
            self._local[key] = record
            pending = dict(self._local)
        # disk merge OUTSIDE the lock (MX006: no I/O under locks)
        table = self._load()
        table.update(pending)
        try:
            self._save(table)
        except OSError:
            pass  # read-only cache dir: tuning still works, unpersisted

    @staticmethod
    def _batch_of(shapes):
        for s in shapes.values():
            if s:
                return max(int(s[0]), 1)
        return 1

    @staticmethod
    def _analytic_multistep(symbol, shapes, platform):
        """Steps per fused dispatch from the byte model
        (cost_model.analytic_step_s): fuse enough steps to fill the
        dispatch window. CPU keeps k=1 (dispatch is cheap,
        debuggability wins)."""
        if platform == "cpu":
            return 1
        from . import cost_model as _cm

        return _k_for_window(
            _cm.analytic_step_s(symbol, shapes, platform))


def _calibration_forward_s(digest, platform):
    """Measured forward seconds for (digest, platform) from the
    profiling CalibrationStore, or None (store missing/empty — the
    pre-calibration behavior is exactly the old analytic path)."""
    try:
        from ..profiling import calibration_store

        return calibration_store().measured_seconds(
            digest, platform, "forward")
    except Exception:
        return None


def choose_fusion_kernel(group_digest, platform):
    """'pallas' | 'lax' for one fusion group, from the kind="kernel" /
    "kernel_lax" CalibrationStore measurements pallas_codegen records
    at build time. Data-driven demotion only: the lax path must be
    measurably faster (>5%) to override the generated kernel; missing
    or partial measurements keep the kernel — the first build IS the
    measurement."""
    try:
        from ..profiling import calibration_store

        store = calibration_store()
        kernel_s = store.measured_seconds(
            group_digest, platform, "kernel")
        lax_s = store.measured_seconds(
            group_digest, platform, "kernel_lax")
    except Exception:
        return "pallas"
    if kernel_s is None or lax_s is None:
        return "pallas"
    return "lax" if lax_s < kernel_s * 0.95 else "pallas"


def _k_for_window(step_s):
    k = 1
    for cand in _MULTISTEP_CHOICES:
        if cand * step_s <= _TARGET_WINDOW_S:
            k = cand
    return k


def _measured_forward_s(symbol, input_shapes, repeats=5):
    """Median wall time of a real bound forward (the on-device
    refinement). Returns None when the symbol cannot be bound at these
    shapes (missing shapes, unsupported backend)."""
    try:
        from ..context import cpu, current_context

        try:
            ctx = current_context()
        except Exception:
            ctx = cpu()
        exe = symbol.simple_bind(ctx=ctx, grad_req="null",
                                 **input_shapes)
        exe.forward(is_train=False)[0].asnumpy()  # compile + settle
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            exe.forward(is_train=False)[0].asnumpy()
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2]
    except Exception:
        return None

"""Pass manager: ordered pipeline execution with a safety contract.

Relay's lesson (PAPERS.md): transforms are only trustworthy when the
infrastructure, not each transform author, enforces validity. After
EVERY pass the manager (1) compacts the graph — orphans a rewrite left
behind are swept by the same traversal the verifier uses to find them —
(2) re-checks the structural invariants (`Graph.validate`), and (3)
runs the PR 5 graph verifier on the pass output, so a transform can
never ship an invalid graph into the executor: it raises right here,
naming the pass.

`optimize_for_bind` is the executor entry point: behind
`MXNET_GRAPH_PASSES` (default on; "0"/"off" bypasses; a comma list
selects/orders passes explicitly, e.g. "dce,fold,cse,layout,
canonicalize"), memoized per (raw structure key, pipeline spec) so a
rebind/reshape of an already-seen graph pays a dict lookup, not a
pipeline run.

All counters live in module stats, exposed as
`graph_pass_stats()` / `reset_pass_stats()` and embedded by the
profiler as `graphPassStats`.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

from ..base import MXNetError
from ..telemetry import register_view as _register_view
from . import pallas_codegen as _pc
from . import transforms as _t
from .ir import Graph

# ------------------------------------------------------------- registry
# name -> (fn, default_on); insertion order defines pipeline order
_PASS_REGISTRY: "OrderedDict[str, tuple]" = OrderedDict()


def register_pass(name, fn=None, *, default_on=True):
    """Register a graph pass (`fn(graph) -> n_rewrites`). Usable as a
    decorator. Registration order fixes the default pipeline position;
    `default_on=False` passes run only when named in
    MXNET_GRAPH_PASSES (e.g. the layout rewrite)."""
    def _add(f):
        if name in _PASS_REGISTRY:
            raise MXNetError(f"graph pass {name!r} registered twice")
        _PASS_REGISTRY[name] = (f, default_on)
        return f

    return _add(fn) if fn is not None else _add


def list_passes():
    """Registered pass names in pipeline order."""
    return list(_PASS_REGISTRY)


register_pass("dce", _t.dce)
register_pass("fold", _t.fold)
register_pass("cse", _t.cse)
register_pass("layout", _t.layout_nhwc, default_on=False)
register_pass("canonicalize", _t.canonicalize)
register_pass("fusion_hints", _t.fusion_hints)
register_pass("pallas_codegen", _pc.pallas_codegen)


def default_pipeline():
    return [n for n, (_, on) in _PASS_REGISTRY.items() if on]


# ---------------------------------------------------------------- stats
_STATS_LOCK = threading.Lock()


def _zero_stats():
    return {
        "pipeline_runs": 0,
        "pipeline_cached": 0,
        "nodes_in": 0,
        "nodes_out": 0,
        "nodes_eliminated": 0,
        "folds": 0,
        "cse_hits": 0,
        "layout_rewrites": 0,
        "canonical_rewrites": 0,
        "fusion_groups": 0,
        "fusion_lowered": 0,
        "verify_failures": 0,
        "pass_time_us": {},
    }


_stats = _zero_stats()

# which top-level counter a pass's rewrite count feeds
_PASS_COUNTERS = {
    "dce": "nodes_eliminated",
    "fold": "folds",
    "cse": "cse_hits",
    "layout": "layout_rewrites",
    "canonicalize": "canonical_rewrites",
    "fusion_hints": "fusion_groups",
    "pallas_codegen": "fusion_lowered",
}


def graph_pass_stats():
    with _STATS_LOCK:
        out = dict(_stats)
        out["pass_time_us"] = dict(_stats["pass_time_us"])
    return out


def reset_pass_stats():
    global _stats
    with _STATS_LOCK:
        _stats = _zero_stats()


# live view in the central telemetry registry: /statusz and /metrics
# read the same counters dump_profile embeds as `graphPassStats`
_register_view("graphPassStats", graph_pass_stats,
               prom_prefix="graph_passes")


# -------------------------------------------------------------- manager
class PassManager:
    """Runs a pass list over a Graph with per-pass compaction,
    validation, and verification."""

    def __init__(self, passes=None, verify=True, collect_stats=True):
        names = list(passes) if passes is not None else default_pipeline()
        unknown = [n for n in names if n not in _PASS_REGISTRY]
        if unknown:
            raise MXNetError(
                f"unknown graph pass(es) {unknown}; registered: "
                f"{list_passes()} (MXNET_GRAPH_PASSES)")
        self.passes = [(n, _PASS_REGISTRY[n][0]) for n in names]
        self.verify = verify
        # collect_stats=False for KEY computation (canonical_digest):
        # the pipeline runs only to name the graph family, not to
        # optimize a bind — graphPassStats must stay a ledger of real
        # bind-time pipeline work (MXNET_GRAPH_PASSES=0 pins 0 runs
        # even though digests still canonicalize)
        self.collect_stats = collect_stats

    def run(self, graph):
        from ..analysis.graph_verify import verify_graph

        if self.collect_stats:
            with _STATS_LOCK:
                _stats["pipeline_runs"] += 1
                _stats["nodes_in"] += len(graph)
        for name, fn in self.passes:
            t0 = time.perf_counter()
            try:
                applied = int(fn(graph) or 0)
                # orphans stranded by the rewrite die here, so the
                # verifier below sees only the graph that would ship
                swept = graph.compact()
                graph.validate()
                issues = (verify_graph(graph, raise_on_issue=False)
                          if self.verify else [])
            except MXNetError:
                if self.collect_stats:
                    with _STATS_LOCK:
                        _stats["verify_failures"] += 1
                raise
            dt_us = int((time.perf_counter() - t0) * 1e6)
            if self.collect_stats:
                with _STATS_LOCK:
                    _stats["pass_time_us"][name] = (
                        _stats["pass_time_us"].get(name, 0) + dt_us)
                    counter = _PASS_COUNTERS.get(name)
                    if counter:
                        _stats[counter] += applied
                    if name != "dce":
                        _stats["nodes_eliminated"] += swept
            if issues:
                if self.collect_stats:
                    with _STATS_LOCK:
                        _stats["verify_failures"] += 1
                detail = "; ".join(
                    f"[{i.kind}] {i.message}" for i in issues)
                raise MXNetError(
                    f"graph pass {name!r} produced an invalid graph: "
                    f"{detail}")
        if self.collect_stats:
            with _STATS_LOCK:
                _stats["nodes_out"] += len(graph)
        return graph


# -------------------------------------------------------- entry points
def pipeline_spec():
    """Parse MXNET_GRAPH_PASSES: None = disabled, else pass-name list.
    The knob is registered in mxnet_tpu.utils; read raw to keep the
    bind path cheap."""
    raw = os.environ.get("MXNET_GRAPH_PASSES", "1").strip()
    if raw in ("0", "off", "false", "False", "none"):
        return None
    if raw in ("", "1", "on", "true", "True", "default"):
        return default_pipeline()
    return [p.strip() for p in raw.split(",") if p.strip()]


def optimize(symbol, passes=None, verify=True, collect_stats=True):
    """Run the pipeline over a Symbol, returning the optimized Symbol.
    (The Graph-level API is `PassManager.run` directly.)"""
    graph = Graph.from_symbol(symbol)
    PassManager(passes, verify=verify,
                collect_stats=collect_stats).run(graph)
    return graph.to_symbol()


# memo: raw structure key + pipeline spec -> optimized Symbol
_MEMO_LOCK = threading.Lock()
_memo: "OrderedDict" = OrderedDict()
_MEMO_CAP = 128


def optimize_for_bind(symbol):
    """Executor._build hook: the MXNET_GRAPH_PASSES pipeline, memoized.
    Returns `symbol` itself when disabled; the memo makes repeated
    binds of one graph (reshape revisits, bucketing sweeps) cost a
    lookup — the exec-cache's zero-steady-state-retrace discipline
    extends to zero steady-state pipeline runs."""
    spec = pipeline_spec()
    if spec is None:
        return symbol
    key = (symbol.structure_key(), tuple(spec))
    with _MEMO_LOCK:
        hit = _memo.get(key)
        if hit is not None:
            _memo.move_to_end(key)
    if hit is not None:
        with _STATS_LOCK:
            _stats["pipeline_cached"] += 1
        return hit
    optimized = optimize(symbol, passes=spec)
    with _MEMO_LOCK:
        _memo[key] = optimized
        while len(_memo) > _MEMO_CAP:
            _memo.popitem(last=False)
    return optimized


def clear_memo():
    with _MEMO_LOCK:
        _memo.clear()

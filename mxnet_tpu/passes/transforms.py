"""The built-in graph-to-graph transforms.

Each pass is a function `pass_fn(graph) -> int` mutating a
`passes.ir.Graph` in place and returning how many rewrites it applied
(0 = fixpoint). The manager compacts (sweeps orphans) and re-verifies
after every pass, so a pass may freely strand producers it rewired
around. Pipeline order (manager.DEFAULT_PIPELINE):

  dce          delete head-unreachable nodes (the verifier's
               `dead_node` finding, executed instead of reported)
  fold         evaluate constant-rooted subgraphs into
               `_graph_constant` leaves + algebraic identities
               (x*1, x/1, x+0, x-0)
  cse          merge structurally identical subexpressions
  layout       (opt-in) NCHW Convolution/Pooling -> NHWC, the
               TPU-native orientation, via inserted transposes
  canonicalize stable topo order, canonical op names, normalized
               params, dense renaming of auto-named nodes — runs LAST
               of the structural passes so names reflect the final
               graph (and a second pipeline run is a no-op)
  fusion_hints annotate single-consumer elementwise chains with
               `__fusion_group__` (advisory: surfaced to profiling
               and consumed by the codegen stage below)
  pallas_codegen
               absorb eligible trailing reductions into their chains
               and stamp each group `candidate:<digest>` or
               `fallback:<reason>` — the lowering verdict
               `plan_for`/Executor turn into generated Pallas kernels
               (pallas_codegen.py; docs/passes.md "From hints to
               kernels")

Invariants every pass preserves: variable nodes are never renamed,
created, or merged away (binding is by-name against the ORIGINAL
symbol); head count and order never change; head values are
numerically identical (fold/cse/dce cannot change a head's value,
layout wraps in transpose pairs that cancel).
"""
from __future__ import annotations

import re

from ..base import MXNetError

# Elementwise (shape-preserving, pointwise) ops for fusion grouping.
# Canonical registry names only — `canonicalize` rewrites aliases first,
# and `fusion_hints` resolves through the registry anyway.
ELEMWISE_OPS = frozenset({
    "relu", "sigmoid", "tanh", "exp", "log", "log1p", "expm1", "sqrt",
    "rsqrt", "square", "abs", "sign", "negative", "reciprocal",
    "softsign", "erf", "identity", "_copy", "cast", "clip",
    "Activation", "LeakyReLU", "smooth_l1",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "_power", "_maximum", "_minimum", "_mod",
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_power", "broadcast_maximum", "broadcast_minimum",
    "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
    "_div_scalar", "_rdiv_scalar", "_power_scalar", "_rpower_scalar",
    "_maximum_scalar", "_minimum_scalar",
})

# Ops that materialize a deterministic value from params alone.
CONST_SOURCE_OPS = frozenset({
    "_zeros", "_ones", "_full", "_arange", "_graph_constant",
})


def _fold_cap():
    from ..utils import getenv

    return int(getenv("MXNET_PASS_FOLD_MAX"))


# ------------------------------------------------------------------ dce
def dce(graph):
    """Dead-node elimination: `Graph.compact` runs the verifier's
    reachability traversal and deletes what it finds."""
    return graph.compact()


# ----------------------------------------------------------------- fold
def _is_foldable_op(gn):
    if gn.is_variable:
        return False
    try:
        od = gn.opdef()
    except MXNetError:
        return False
    return (not od.needs_rng and not od.needs_mode and not od.aux_names
            and od.name != "Custom"
            and od.resolved_num_outputs(od.normalize_params(gn.attrs))
            == 1)


def _shape_guard(gn, cap):
    """Pre-evaluation size guard for const-source ops: refuse to
    materialize a `shape` param bigger than the fold cap."""
    shape = gn.params().get("shape")
    if not shape:
        return True
    n = 1
    for d in shape:
        n *= int(d)
    return n <= cap


def fold(graph):
    """Constant folding: every op whose inputs are all constant-valued
    collapses into a `_graph_constant` leaf holding the evaluated
    result (as nested python lists, so it survives tojson round-trips).
    Plus the algebraic identities x*1, x/1, x+0, x-0 — except at graph
    heads, where removing the computing op would re-create the
    donation-alias hazard the verifier rejects (`x * 1` is its
    documented workaround)."""
    import numpy as np

    cap = _fold_cap()
    n = len(graph.nodes)
    is_const = [False] * n
    for i, gn in enumerate(graph.nodes):
        if not _is_foldable_op(gn):
            continue
        if gn.inputs:
            is_const[i] = all(is_const[s] for s, _ in gn.inputs)
        else:
            is_const[i] = (gn.op in CONST_SOURCE_OPS
                           and _shape_guard(gn, cap))

    # fold boundaries: const nodes with at least one input (a leafless
    # const source is already as cheap as a _graph_constant)
    targets = [i for i in range(n)
               if is_const[i] and graph.nodes[i].inputs]
    memo = {}

    def _eval(i):
        if i in memo:
            return memo[i]
        gn = graph.nodes[i]
        vals = [_eval(s) for s, _ in gn.inputs]
        memo[i] = gn.opdef().fn(*vals, **gn.params())
        return memo[i]

    folds = 0
    taken = {gn.name for gn in graph.nodes}
    for i in targets:
        try:
            val = np.asarray(_eval(i))
        except Exception:
            continue  # op rejected the const inputs — leave it traced
        if val.size > cap:
            continue
        gn = graph.nodes[i]
        gn.op = "_graph_constant"
        gn.attrs = {"value": val.tolist(), "dtype": val.dtype.name}
        gn.inputs = []
        # auto-style rename so canonicalize renumbers it like any other
        # auto-named node (keeping the replaced op's name would leak the
        # BUILD-TIME numbering into the canonical signature)
        name, k = f"graph_constant{i}", i
        while name in taken:
            k += len(graph.nodes)
            name = f"graph_constant{k}"
        taken.discard(gn.name)
        taken.add(name)
        gn.name = name
        folds += 1

    folds += _fold_identities(graph)
    return folds


_IDENTITY_OPS = {
    "_mul_scalar": 1.0, "_div_scalar": 1.0,
    "_plus_scalar": 0.0, "_minus_scalar": 0.0,
}


def _fold_identities(graph):
    head_nodes = {s for s, _ in graph.heads}
    redirect = {}
    for i, gn in enumerate(graph.nodes):
        neutral = _IDENTITY_OPS.get(gn.op)
        if neutral is None or i in head_nodes:
            continue
        if float(gn.params().get("scalar", neutral)) != neutral:
            continue
        src = gn.inputs[0]
        # chase through identities folded earlier in this sweep
        while src[0] in redirect:
            src = redirect[src[0]]
        redirect[i] = src
    if not redirect:
        return 0
    for gn in graph.nodes:
        gn.inputs = [redirect.get(s, (s, j)) for s, j in gn.inputs]
    graph.heads = [redirect.get(s, (s, j)) for s, j in graph.heads]
    return len(redirect)


# ------------------------------------------------------------------ cse
def cse(graph):
    """Common-subexpression elimination: nodes with the same op,
    normalized params, ctx-group, and (already-deduplicated) input
    wiring compute the same value — all consumers move to the first
    occurrence. Variables merge by name (binding is by-name, so two
    same-named variable nodes are one buffer regardless); stateful ops
    (rng draws, aux-carrying ops like BatchNorm) never merge."""
    from ..symbol import _canon

    canonical = {}
    replace = {}
    for i, gn in enumerate(graph.nodes):
        if gn.is_variable:
            key = ("var", gn.name, gn.is_aux)
        else:
            try:
                od = gn.opdef()
            except MXNetError:
                continue
            if od.needs_rng or od.aux_names:
                continue
            key = (
                "op", od.name, _canon(od.normalize_params(gn.attrs)),
                gn.extra.get("__ctx_group__"),
                tuple((replace.get(s, s), j) for s, j in gn.inputs),
            )
        if key in canonical:
            replace[i] = canonical[key]
        else:
            canonical[key] = i
    if not replace:
        return 0
    for gn in graph.nodes:
        gn.inputs = [(replace.get(s, s), j) for s, j in gn.inputs]
    graph.heads = [(replace.get(s, s), j) for s, j in graph.heads]
    return len(replace)


# --------------------------------------------------------------- layout
_NHWC_DATA = (0, 2, 3, 1)   # NCHW -> NHWC (and OIHW -> OHWI)
_NCHW_DATA = (0, 3, 1, 2)   # NHWC -> NCHW


def layout_nhwc(graph):
    """Opt-in NCHW->NHWC rewrite for 2-D Convolution/Pooling: on TPU,
    channels-last puts C on the 128-wide lane dimension, so the op
    skips XLA's internal relayout. Bind shapes are untouched — the op
    is wrapped in transpose pairs (data/weight in, output back out),
    and XLA cancels adjacent pairs between consecutive rewritten ops.
    Idempotent: a rewritten op carries layout='NHWC' and is skipped."""
    targets = []
    for i, gn in enumerate(graph.nodes):
        if gn.op not in ("Convolution", "Pooling"):
            continue
        params = gn.params()
        if str(params.get("layout") or "NCHW") != "NCHW":
            continue
        if len(params.get("kernel") or ()) != 2:
            continue  # rank unknown (global_pool) or not 2-D
        targets.append(i)
    if not targets:
        return 0

    from .ir import GraphNode

    consumers = graph.consumers()
    for i in targets:
        gn = graph.nodes[i]

        def _transpose(name, axes, src):
            graph.nodes.append(GraphNode(
                "transpose", name, attrs={"axes": axes}, inputs=[src]))
            return len(graph.nodes) - 1

        old_consumers = list(consumers[i])
        old_head_slots = [k for k, (s, _) in enumerate(graph.heads)
                          if s == i]
        tin = _transpose(f"{gn.name}_nhwc_data", _NHWC_DATA,
                         gn.inputs[0])
        gn.inputs[0] = (tin, 0)
        if gn.op == "Convolution":
            tw = _transpose(f"{gn.name}_nhwc_weight", _NHWC_DATA,
                            gn.inputs[1])
            gn.inputs[1] = (tw, 0)
        gn.attrs["layout"] = "NHWC"
        tout = _transpose(f"{gn.name}_nchw_out", _NCHW_DATA, (i, 0))
        for ci, pos in old_consumers:
            graph.nodes[ci].inputs[pos] = (tout, 0)
        for k in old_head_slots:
            graph.heads[k] = (tout, graph.heads[k][1])
    graph.toposort()
    return len(targets)


# --------------------------------------------------------- canonicalize
def canonicalize(graph):
    """Canonical form: (1) DFS-post-order node list from the heads — a
    pure function of the wiring, so construction order stops mattering;
    (2) alias op names -> canonical registry names; (3) params
    normalized (defaults filled, values coerced); (4) AUTO-NAMED op
    nodes renamed to dense per-op counters in topo order. User-named
    nodes and ALL variables keep their names (binding and the public
    output surface are by-name). Runs last of the structural passes, so
    the names — and the exec-cache key derived from them — describe the
    graph that actually executes."""
    from ..symbol import _canon

    graph.toposort()
    changed = 0
    for gn in graph.nodes:
        if gn.is_variable:
            continue
        try:
            od = gn.opdef()
        except MXNetError:
            continue
        if gn.op != od.name:
            gn.op = od.name
            changed += 1
        norm = od.normalize_params(gn.attrs)
        if _canon(norm) != _canon(gn.attrs):
            changed += 1
        gn.attrs = norm

    # rename pass: only names that LOOK auto-generated for their own op
    # (exactly `{base}{digits}` with base = _create's auto-name prefix)
    auto = []
    taken = set()
    for gn in graph.nodes:
        base = None if gn.is_variable else gn.op.lower().lstrip("_")
        if base is not None and re.fullmatch(
                re.escape(base) + r"\d+", gn.name):
            auto.append((gn, base))
        else:
            taken.add(gn.name)
    counters = {}
    assigned = set()
    for gn, base in auto:
        k = counters.get(base, 0)
        while f"{base}{k}" in taken or f"{base}{k}" in assigned:
            k += 1
        counters[base] = k + 1
        new = f"{base}{k}"
        assigned.add(new)
        if new != gn.name:
            gn.name = new
            changed += 1
    return changed


# -------------------------------------------------------- fusion hints
def fusion_hints(graph):
    """Annotate producer-consumer elementwise chains with a
    `__fusion_group__` tag (fg0, fg1, ... in topo order). A node joins
    its producer's group only when it is that producer's sole consumer
    and the producer is not a head — exactly the shape XLA fuses into
    one kernel. Advisory: tags surface in serialized graphs and
    `graphPassStats`, and are NOT part of the exec-cache key (Symbol
    structure_key ignores extra attrs), so hints never fragment the
    cache."""
    consumers = graph.consumers()
    head_nodes = {s for s, _ in graph.heads}

    def _elementwise(gn):
        if gn.is_variable:
            return False
        try:
            return gn.opdef().name in ELEMWISE_OPS
        except MXNetError:
            return False

    group = {}
    members = []
    for i, gn in enumerate(graph.nodes):
        if not _elementwise(gn):
            continue
        g = None
        for s, _ in gn.inputs:
            if (s in group and len(consumers[s]) == 1
                    and s not in head_nodes):
                g = group[s]
                break
        if g is None:
            g = len(members)
            members.append([])
        group[i] = g
        members[g].append(i)

    changed = 0
    real = [m for m in members if len(m) >= 2]
    tags = {}
    for gid, m in enumerate(real):
        for i in m:
            tags[i] = f"fg{gid}"
    for i, gn in enumerate(graph.nodes):
        want = tags.get(i)
        have = gn.extra.get("__fusion_group__")
        if want != have:
            changed += 1
            if want is None:
                del gn.extra["__fusion_group__"]
            else:
                gn.extra["__fusion_group__"] = want
    # report group count (stable), not churn: re-running is a no-op and
    # returns 0 only when tags were already in place
    return changed and len(real)

"""Graph IR for the pass pipeline: the Symbol JSON node-list form.

A `Graph` is the mutable, index-based twin of `Symbol.tojson()`: a flat
node list (op name or None for variables, node name, python-valued
params, input wiring as ``(node_index, output_index)`` pairs) plus the
head list. It exists because passes need two things the live `Symbol`
cannot give them:

  - **dead nodes**: a Symbol is defined by its heads, so its topo walk
    can never contain an unreachable node — but a serialized graph can,
    and rewrites (fold/CSE rewiring) orphan producers all the time. The
    node-list form keeps orphans addressable until `compact()` sweeps
    them (the DCE pass, sharing one traversal with the verifier's
    dead-node check — analysis/graph_verify.dead_node_indices).
  - **cheap rewiring**: replacing a node or redirecting consumers is an
    index update, not a graph rebuild.

Round-trips preserve everything binding depends on: variable names
(binding is by-name), aux flags, extra attrs, param python values
(NEVER stringified — a Custom op's callable params survive), head order
and multi-output wiring.
"""
from __future__ import annotations

import json

from ..base import MXNetError


class GraphNode:
    """One node record: `op` is the registry op NAME (string), or None
    for a variable."""

    __slots__ = ("op", "name", "attrs", "inputs", "is_aux", "extra")

    def __init__(self, op, name, attrs=None, inputs=None, is_aux=False,
                 extra=None):
        self.op = op
        self.name = name
        self.attrs = dict(attrs or {})
        self.inputs = [tuple(i) for i in (inputs or [])]
        self.is_aux = bool(is_aux)
        self.extra = dict(extra or {})

    @property
    def is_variable(self):
        return self.op is None

    def opdef(self):
        from ..ops import registry as _registry

        if self.op is None:
            return None
        return _registry.get(self.op)

    def params(self):
        """Normalized (default-filled, coerced) op params."""
        od = self.opdef()
        return od.normalize_params(self.attrs) if od else {}

    def num_outputs(self):
        od = self.opdef()
        if od is None:
            return 1
        return od.resolved_num_outputs(od.normalize_params(self.attrs))

    def copy(self):
        return GraphNode(self.op, self.name, dict(self.attrs),
                         list(self.inputs), self.is_aux,
                         dict(self.extra))

    def __repr__(self):
        return (f"<GraphNode {self.op or 'null'} {self.name!r} "
                f"inputs={self.inputs}>")


class Graph:
    """Flat node-list graph: `nodes` (GraphNode records, inputs refer to
    list indices) + `heads` ([(node_index, output_index)])."""

    def __init__(self, nodes=None, heads=None):
        self.nodes = list(nodes or [])
        self.heads = [tuple(h) for h in (heads or [])]

    # ------------------------------------------------------ construction
    @classmethod
    def from_symbol(cls, symbol):
        from ..symbol import _topo

        order = _topo(symbol._outputs)
        index = {id(n): i for i, n in enumerate(order)}
        nodes = []
        for n in order:
            nodes.append(GraphNode(
                None if n.is_variable else n.op.name,
                n.name,
                attrs=n.attrs,
                inputs=[(index[id(src)], i) for src, i in n.inputs],
                is_aux=n.is_aux,
                extra=n._extra_attrs,
            ))
        heads = [(index[id(n)], i) for n, i in symbol._outputs]
        return cls(nodes, heads)

    @classmethod
    def from_json(cls, data):
        """Parse a serialized node-list graph (Symbol.tojson format),
        KEEPING unreachable nodes (symbol.loads silently drops them —
        here they stay addressable so DCE can delete and count them)."""
        if isinstance(data, str):
            data = json.loads(data)
        nodes = []
        for jn in data.get("nodes", []):
            attrs = dict(jn.get("attrs", jn.get("attr", {}) or {}))
            is_aux = attrs.pop("__is_aux__", "False") in (
                "True", "1", "true")
            extra = {k: v for k, v in attrs.items()
                     if k.startswith("__")}
            params = {k: v for k, v in attrs.items()
                      if not k.startswith("__")}
            op = None if jn["op"] == "null" else jn["op"]
            nodes.append(GraphNode(
                op, jn["name"], attrs=params,
                inputs=[(int(i), int(j)) for i, j, *_ in jn["inputs"]],
                is_aux=is_aux, extra=extra))
        heads = [(int(i), int(j)) for i, j, *_ in data.get("heads", [])]
        return cls(nodes, heads)

    def to_symbol(self):
        from ..symbol import Node, Symbol

        built = []
        for gn in self.nodes:
            node = Node(gn.opdef(), gn.name, attrs=dict(gn.attrs),
                        is_aux=gn.is_aux)
            node._extra_attrs = dict(gn.extra)
            node.inputs = [(built[i], j) for i, j in gn.inputs]
            built.append(node)
        return Symbol([(built[i], j) for i, j in self.heads])

    def to_json_dict(self):
        """Structural dict in the Symbol.tojson layout (for the graph
        verifier and debugging). Param VALUES are carried as-is — this
        dict is for structural checks, not on-disk serialization (use
        `to_symbol().tojson()` for that)."""
        jnodes = []
        for gn in self.nodes:
            attrs = dict(gn.attrs)
            attrs.update(gn.extra)
            if gn.is_aux:
                attrs["__is_aux__"] = "True"
            jn = {
                "op": "null" if gn.is_variable else gn.op,
                "name": gn.name,
                "inputs": [[i, j, 0] for i, j in gn.inputs],
            }
            if attrs:
                jn["attrs"] = attrs
            jnodes.append(jn)
        return {
            "nodes": jnodes,
            "arg_nodes": [i for i, gn in enumerate(self.nodes)
                          if gn.is_variable],
            "heads": [[i, j, 0] for i, j in self.heads],
        }

    # --------------------------------------------------------- structure
    def consumers(self):
        """node index -> list of (consumer_index, input_position)."""
        out = {i: [] for i in range(len(self.nodes))}
        for ci, gn in enumerate(self.nodes):
            for pos, (src, _) in enumerate(gn.inputs):
                out[src].append((ci, pos))
        return out

    def validate(self):
        n = len(self.nodes)
        for i, gn in enumerate(self.nodes):
            for src, _ in gn.inputs:
                if not (0 <= src < n):
                    raise MXNetError(
                        f"graph node #{i} ({gn.name!r}) references "
                        f"nonexistent input #{src}")
                if src >= i:
                    raise MXNetError(
                        f"graph node #{i} ({gn.name!r}) references "
                        f"non-topological input #{src}")
        for src, _ in self.heads:
            if not (0 <= src < n):
                raise MXNetError(f"graph head references nonexistent "
                                 f"node #{src}")

    def compact(self):
        """Remove nodes unreachable from the heads (one traversal,
        shared with the verifier's dead-node check). Returns the number
        of nodes removed; input indices are re-densified in place."""
        from ..analysis.graph_verify import dead_node_indices

        dead = dead_node_indices(
            [[src for src, _ in gn.inputs] for gn in self.nodes],
            [src for src, _ in self.heads])
        if not dead:
            return 0
        remap = {}
        kept = []
        for i, gn in enumerate(self.nodes):
            if i in dead:
                continue
            remap[i] = len(kept)
            kept.append(gn)
        for gn in kept:
            gn.inputs = [(remap[src], j) for src, j in gn.inputs]
        self.heads = [(remap[src], j) for src, j in self.heads]
        removed = len(self.nodes) - len(kept)
        self.nodes = kept
        return removed

    def toposort(self):
        """Reorder `self.nodes` into DFS post-order from the heads
        (dead nodes, if any, keep their relative order at the tail).
        The order is a pure function of the wiring — two isomorphic
        graphs sort identically regardless of how they were built."""
        n = len(self.nodes)
        order = []
        seen = [False] * n
        # iterative DFS matching symbol._topo's visit order
        for h, _ in self.heads:
            stack = [(h, False)]
            while stack:
                i, expanded = stack.pop()
                if expanded:
                    order.append(i)
                    continue
                if seen[i]:
                    continue
                seen[i] = True
                stack.append((i, True))
                for src, _ in reversed(self.nodes[i].inputs):
                    if not seen[src]:
                        stack.append((src, False))
        for i in range(n):
            if not seen[i]:
                order.append(i)
        remap = {old: new for new, old in enumerate(order)}
        self.nodes = [self.nodes[i] for i in order]
        for gn in self.nodes:
            gn.inputs = [(remap[src], j) for src, j in gn.inputs]
        self.heads = [(remap[src], j) for src, j in self.heads]
        return self

    def op_count(self):
        """Number of executed (non-variable) nodes."""
        return sum(1 for gn in self.nodes if not gn.is_variable)

    def signature(self):
        """Hashable structural signature of the FULL node-list form
        (includes extra attrs and dead nodes — unlike
        Symbol.structure_key, which sees only the live graph). Used by
        idempotence checks: pipeline(g).signature() must be a fixpoint."""
        from ..symbol import _canon

        entries = []
        for gn in self.nodes:
            entries.append((
                gn.op or "null", gn.name, _canon(gn.attrs),
                _canon(gn.extra), gn.is_aux, tuple(gn.inputs),
            ))
        return (tuple(entries), tuple(self.heads))

    def copy(self):
        return Graph([gn.copy() for gn in self.nodes], list(self.heads))

    def __len__(self):
        return len(self.nodes)

    def __repr__(self):
        return (f"<Graph {len(self.nodes)} nodes "
                f"({self.op_count()} ops), {len(self.heads)} heads>")

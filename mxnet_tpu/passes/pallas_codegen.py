"""Pallas codegen: lower `__fusion_group__` chains to generated kernels.

`fusion_hints` (PR 6) finds single-consumer elementwise chains and tags
them — annotation only, no kernel was ever generated. This stage is the
lowering step (the TVM/Glow move, PAPERS.md): it consumes those tags and
emits one generated Pallas kernel per group from a small template
library, with a composed lax-path twin that is ALWAYS available.

Two halves, two call sites:

  pallas_codegen(graph)   the registered pass. Absorbs an eligible
                          trailing full reduction into its producer
                          chain, then stamps every group's output node
                          with `__fusion_codegen__`:

                            candidate:<digest>   structurally lowerable
                            fallback:<reason>    counted static reject
                                                 (disabled / too_small /
                                                 unsupported_op:<name>)

                          The stamp is platform-independent on purpose:
                          the canonical graph digest (disk exec-cache,
                          AOT bundles) must not change with the backend.

  plan_for(symbol, ...)   executor-side lowering of an OPTIMIZED
                          symbol: resolves each candidate to a built,
                          parity-verified kernel or a counted fallback
                          reason (platform / irregular_shapes /
                          unsupported_dtype / calibrated_slower /
                          parity), and returns the node-index routing
                          plus the exec-cache key component — fused and
                          fallback binds never collide on one program.

Templates (all (8, 128)-tile-aware through cost_model.tile_sublanes):

  elementwise     same-shape chain, tiled (sublanes, 128) grid when the
                  2-D view divides the f32 register tile, whole-array
                  single block in interpret mode otherwise
  reduction       chain + absorbed axis=None reduce: one block, the
                  kernel writes the (1, 1) scalar
  scale_bias_act  the mul -> add -> activation special case of the
                  elementwise emitter (classified so the stats view and
                  the calibration records can tell it apart)

Every generated kernel is verified in interpret mode against its lax
twin at build time (<= 1e-6, fwd; bwd is the lax twin's vjp by
construction via custom_vjp) and both paths are timed into the
profiling `CalibrationStore` under kind="kernel" / "kernel_lax" — the
autotuner's `choose_fusion_kernel` reads them back, so fuse-vs-fallback
is a measured decision, never a guess. Groups that do not lower are
never dropped silently: each carries a counted reason in the
`fusionStats` view (Prometheus prefix `fusion`).

Env knobs (registered in mxnet_tpu/utils): MXNET_FUSION_CODEGEN,
MXNET_FUSION_MIN_GROUP, MXNET_FUSION_INTERPRET; MXNET_DECODE_KERNEL is
folded into the same `codegen_config()` so the decode tier's kernel
choice and graph codegen share one switch surface.
"""
from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass

import numpy as np

from ..base import MXNetError
from ..telemetry import register_view as _register_view
from .cost_model import TILE_LANES, tile_sublanes
from .transforms import ELEMWISE_OPS

# trailing reductions absorbable into a chain: axis=None (full) only —
# the reduction template reduces its single block down to one scalar
REDUCE_OPS = frozenset({"sum", "mean", "max", "min"})

# the scale_bias_act classifier's per-position op sets
_MUL_OPS = frozenset({"broadcast_mul", "elemwise_mul", "_mul_scalar"})
_ADD_OPS = frozenset({"broadcast_add", "elemwise_add", "_plus_scalar"})
_ACT_OPS = frozenset({"relu", "sigmoid", "tanh", "Activation"})

PARITY_RTOL = 1e-6
PARITY_ATOL = 1e-6


class _Unsupported(Exception):
    """Raised by an emitter when a group cannot take its template; the
    message is the counted fallback reason."""


# ---------------------------------------------------------------- config
@dataclass(frozen=True)
class CodegenConfig:
    """The one switch surface for kernel generation (env-derived)."""

    enabled: bool       # MXNET_FUSION_CODEGEN
    min_group: int      # MXNET_FUSION_MIN_GROUP
    interpret: bool     # MXNET_FUSION_INTERPRET (force interpret mode)
    decode_kernel: str  # MXNET_DECODE_KERNEL (decoding tier choice)


def codegen_config():
    """Read the codegen knobs (fresh each call — they are env vars)."""
    from .. import utils as _utils

    return CodegenConfig(
        enabled=bool(_utils.getenv("MXNET_FUSION_CODEGEN")),
        min_group=int(_utils.getenv("MXNET_FUSION_MIN_GROUP")),
        interpret=bool(_utils.getenv("MXNET_FUSION_INTERPRET")),
        decode_kernel=str(_utils.getenv("MXNET_DECODE_KERNEL")),
    )


# ----------------------------------------------------------------- state
_LOCK = threading.RLock()
# digest -> {"tag", "ops", "template", "decision", "reason"} — latest
# decision per group; the no-silent-drops ledger ci/check_fusion.py
# audits (groups_seen == groups_lowered + groups_fallback)
_GROUPS = {}
_COUNTS = {"kernels_built": 0, "parity_checks": 0, "parity_failures": 0}
# (digest, ext aval sig, interpret) -> ("ok", callable) | ("demoted",
# reason) — kernels build (and parity-verify, and time) once per
# process+shape, so repeat binds are table lookups
_KERNELS = {}
_CAL_RECORDED = set()   # (digest, platform): one timing record each


def fusion_stats():
    """Aggregate codegen counters (`fusionStats` view / Prometheus
    `fusion_*`): groups seen/lowered/fallback, per-reason fallback
    counts, per-template kernel counts, parity totals."""
    with _LOCK:
        groups = [dict(v) for v in _GROUPS.values()]
        counts = dict(_COUNTS)
    reasons = {}
    templates = {}
    lowered = 0
    for g in groups:
        if g["decision"] == "pallas":
            lowered += 1
            templates[g["template"]] = templates.get(g["template"], 0) + 1
        else:
            reasons[g["reason"]] = reasons.get(g["reason"], 0) + 1
    out = {
        "groups_seen": len(groups),
        "groups_lowered": lowered,
        "groups_fallback": len(groups) - lowered,
        "fallback_reasons": reasons,
        "templates": templates,
    }
    out.update(counts)
    return out


def fusion_group_records():
    """Per-group drill-down: {digest: {tag, ops, template, decision,
    reason}} — the FAQ's "why did my group fall back" answer."""
    with _LOCK:
        return {d: dict(v) for d, v in _GROUPS.items()}


def reset_fusion_stats():
    """Test/CI hook: forget decisions, kernels, and counters."""
    with _LOCK:
        _GROUPS.clear()
        _KERNELS.clear()
        _CAL_RECORDED.clear()
        for k in _COUNTS:
            _COUNTS[k] = 0


_register_view("fusionStats", fusion_stats, prom_prefix="fusion")


def _note_group(digest, tag, ops, template, decision, reason=None):
    with _LOCK:
        _GROUPS[digest] = {"tag": tag, "ops": tuple(ops),
                           "template": template, "decision": decision,
                           "reason": reason}


# ------------------------------------------------------- group structure
def _groups_in(nodes):
    """{tag: [member indices, topo order]} over a node sequence whose
    records expose `.extra` (passes.ir.GraphNode)."""
    groups = {}
    for i, gn in enumerate(nodes):
        tag = gn.extra.get("__fusion_group__")
        if tag is not None:
            groups.setdefault(tag, []).append(i)
    return groups


def _absorb_reductions(graph, groups):
    """Extend each chain by its sole-consumer trailing FULL reduction
    (axis=None, exclude off): the reduction template then computes the
    chain and its scalar in one kernel. Mirrors the fusion_hints join
    rule — sole consumer, producer not a head — so the group stays a
    chain with one external output."""
    consumers = graph.consumers()
    heads = {s for s, _ in graph.heads}
    changed = 0
    for tag, members in groups.items():
        out = members[-1]
        if out in heads or len(consumers[out]) != 1:
            continue
        ci, _ = consumers[out][0]
        gn = graph.nodes[ci]
        if gn.is_variable or gn.extra.get("__fusion_group__"):
            continue
        try:
            od = gn.opdef()
        except MXNetError:
            continue
        if od.name not in REDUCE_OPS:
            continue
        params = gn.params()
        if params.get("axis") is not None or params.get("exclude"):
            continue
        if any(s != out for s, _ in gn.inputs):
            continue
        gn.extra["__fusion_group__"] = tag
        members.append(ci)
        changed += 1
    return changed


def _group_spec(nodes, members):
    """Normalize a chain into (spec, ext): spec is one
    (op_name, params, wired_inputs) per member, wired entries are
    ("m", member_pos) for in-group values and ("x", ext_pos) for
    external tensors; ext lists the external (node_index, out_index)
    keys in first-use order."""
    pos = {m: j for j, m in enumerate(members)}
    ext, ext_index, spec = [], {}, []
    for m in members:
        gn = nodes[m]
        wired = []
        for src, oi in gn.inputs:
            if src in pos:
                wired.append(("m", pos[src]))
            else:
                key = (src, oi)
                if key not in ext_index:
                    ext_index[key] = len(ext)
                    ext.append(key)
                wired.append(("x", ext_index[key]))
        spec.append((gn.opdef().name, gn.params(), tuple(wired)))
    return spec, ext


def group_digest(spec, n_ext):
    """Deterministic structural digest of one group: ops, canonical
    params, internal wiring, external arity. Shapes are NOT part of it
    — calibration records aggregate over shapes per group."""
    from ..symbol import _canon

    payload = tuple((op, _canon(params), wired)
                    for op, params, wired in spec)
    return hashlib.sha256(repr((payload, n_ext)).encode()).hexdigest()[:16]


def _template_of(spec):
    ops = [s[0] for s in spec]
    if ops[-1] in REDUCE_OPS:
        return "reduction"
    if (len(ops) == 3 and ops[0] in _MUL_OPS and ops[1] in _ADD_OPS
            and ops[2] in _ACT_OPS):
        return "scale_bias_act"
    return "elementwise"


def _static_reason(nodes, members, cfg):
    """Platform-independent eligibility (the pass-time half of the
    decision). None = candidate."""
    if not cfg.enabled:
        return "disabled"
    n_elem = 0
    for m in members:
        gn = nodes[m]
        try:
            od = gn.opdef()
        except MXNetError:
            return "unsupported_op:unknown"
        if od is None:
            return "unsupported_op:variable"
        name = od.name
        if name in ELEMWISE_OPS:
            n_elem += 1
        elif name in REDUCE_OPS:
            if m != members[-1]:
                return f"unsupported_op:{name}"
        else:
            return f"unsupported_op:{name}"
        if od.needs_rng or od.needs_mode or od.aux_names:
            return f"unsupported_op:{name}"
        if od.resolved_num_outputs(gn.params()) != 1:
            return f"unsupported_op:{name}"
    if n_elem < cfg.min_group:
        return "too_small"
    return None


# ------------------------------------------------------------- the pass
def pallas_codegen(graph):
    """The registered pipeline stage (runs after fusion_hints): absorb
    trailing reductions, then stamp every group's output node with its
    lowering verdict (`candidate:<digest>` / `fallback:<reason>`).
    Returns the candidate count (0 = fixpoint, the manager's
    idempotence idiom)."""
    cfg = codegen_config()
    groups = _groups_in(graph.nodes)
    changed = _absorb_reductions(graph, groups)
    stamps = {}
    n_candidates = 0
    for tag in sorted(groups):
        members = sorted(groups[tag])
        out = members[-1]
        reason = _static_reason(graph.nodes, members, cfg)
        if reason is None:
            spec, ext = _group_spec(graph.nodes, members)
            stamps[out] = f"candidate:{group_digest(spec, len(ext))}"
            n_candidates += 1
        else:
            stamps[out] = f"fallback:{reason}"
    for i, gn in enumerate(graph.nodes):
        want = stamps.get(i)
        have = gn.extra.get("__fusion_codegen__")
        if want != have:
            changed += 1
            if want is None:
                del gn.extra["__fusion_codegen__"]
            else:
                gn.extra["__fusion_codegen__"] = want
    return changed and n_candidates


# ------------------------------------------------------ lax twin + vjp
def group_lax_fn(spec):
    """Compose the group's registry op fns into ONE callable over the
    external inputs — the always-available lax fallback path, and the
    vjp reference of every generated kernel."""
    from ..ops import registry as _registry

    steps = [(_registry.get(op).fn, dict(params), wired)
             for op, params, wired in spec]

    def lax_fn(*ext_vals):
        vals = []
        for fn, params, wired in steps:
            ins = [ext_vals[w[1]] if w[0] == "x" else vals[w[1]]
                   for w in wired]
            vals.append(fn(*ins, **params))
        return vals[-1]

    return lax_fn


def _make_fused_callable(lax_fn, kernel_call):
    """Differentiable fused entry: forward through the generated
    kernel, backward through the lax twin's vjp (the parallel/attention
    custom_vjp pattern — gradients are exact because fwd parity is)."""
    import jax

    @jax.custom_vjp
    def fused(*xs):
        return kernel_call(*xs)

    def fwd(*xs):
        return kernel_call(*xs), xs

    def bwd(res, g):
        _, vjp = jax.vjp(lax_fn, *res)
        return vjp(g)

    fused.defvjp(fwd, bwd)
    return fused


# ------------------------------------------------------ kernel emitters
def _norm2d(shape):
    """(rows, cols) 2-D view: minor dim on lanes, everything else on
    sublanes (the cost-model tiling convention)."""
    shape = tuple(int(d) for d in shape)
    if not shape:
        return (1, 1)
    if len(shape) == 1:
        return (1, shape[0])
    r = 1
    for d in shape[:-1]:
        r *= d
    return (r, shape[-1])


def _tiling(r, c, dtype, interpret):
    """(block, grid) over the 2-D view: (sublanes, 128) tiles when the
    view divides the register tile; whole-array single block in
    interpret mode; unsupported otherwise (real-TPU ragged tails fall
    back to lax rather than pad inside a generated kernel)."""
    sub = tile_sublanes(dtype)
    if r % sub == 0 and c % TILE_LANES == 0:
        return (sub, TILE_LANES), (r // sub, c // TILE_LANES)
    if interpret:
        return (r, c), (1, 1)
    raise _Unsupported("irregular_shapes")


def _elementwise_kernel(spec, ext_avals, out_aval, interpret):
    """Tiled elementwise-chain kernel: every external input shares the
    output shape, each grid step evaluates the whole chain on one
    (sublanes, 128) block."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    out_shape = tuple(out_aval.shape)
    for s, _ in ext_avals:
        if tuple(s) != out_shape:
            raise _Unsupported("irregular_shapes")
    r, c = _norm2d(out_shape)
    block, grid = _tiling(r, c, out_aval.dtype, interpret)
    chain = group_lax_fn(spec)

    def kernel(*refs):
        refs[-1][...] = chain(*[ref[...] for ref in refs[:-1]])

    call = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec(block, lambda i, j: (i, j))
                  for _ in ext_avals],
        out_specs=pl.BlockSpec(block, lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((r, c), out_aval.dtype),
        interpret=interpret,
    )

    def run(*vals):
        flat = [jnp.reshape(v, (r, c)) for v in vals]
        return jnp.reshape(call(*flat), out_shape)

    return run


def _scale_bias_act_kernel(spec, ext_avals, out_aval, interpret):
    """Fused scale+bias+activation: the mul -> add -> activation chain
    (tensor or scalar-param scale/bias). Validates the pattern, then
    shares the tiled elementwise emitter — the fusion win is identical
    (one HBM round-trip instead of three), the classification feeds the
    stats view and the per-template calibration records."""
    if _template_of(spec) != "scale_bias_act":
        raise _Unsupported("irregular_shapes")
    return _elementwise_kernel(spec, ext_avals, out_aval, interpret)


def _reduction_kernel(spec, ext_avals, out_aval, interpret):
    """Chain + absorbed axis=None reduction in one kernel: a single
    whole-array block evaluates the elementwise body and writes the
    (1, 1) scalar (exact — no padded lanes enter the reduction)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    shapes = {tuple(s) for s, _ in ext_avals}
    if len(shapes) != 1:
        raise _Unsupported("irregular_shapes")
    r, c = _norm2d(shapes.pop())
    if not interpret and (r % tile_sublanes(out_aval.dtype)
                          or c % TILE_LANES):
        raise _Unsupported("irregular_shapes")
    chain = group_lax_fn(spec)

    def kernel(*refs):
        val = chain(*[ref[...] for ref in refs[:-1]])
        refs[-1][0, 0] = jnp.reshape(val, ())

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, 1), out_aval.dtype),
        interpret=interpret,
    )

    def run(*vals):
        flat = [jnp.reshape(v, (r, c)) for v in vals]
        return jnp.reshape(call(*flat), tuple(out_aval.shape))

    return run


_EMITTERS = {
    "elementwise": _elementwise_kernel,
    "scale_bias_act": _scale_bias_act_kernel,
    "reduction": _reduction_kernel,
}


# ------------------------------------------------- parity + calibration
def _seeded_inputs(ext_avals, digest):
    """Concrete parity inputs, seeded from the group digest: floats in
    [0.5, 1.5] (away from activation kinks and division zeros), small
    positive ints elsewhere."""
    rs = np.random.RandomState(int(digest[:8], 16) & 0x7FFFFFFF)
    out = []
    for s, d in ext_avals:
        if np.issubdtype(d, np.floating):
            out.append(rs.uniform(0.5, 1.5, s).astype(d))
        else:
            out.append(rs.randint(1, 5, s).astype(d))
    return out


def _parity_and_time(kernel_call, lax_fn, ext_avals, digest):
    """(ok, kernel_s, lax_s): interpret-mode kernel output vs the lax
    twin on seeded concrete inputs, both wall-timed."""
    ins = _seeded_inputs(ext_avals, digest)
    t0 = time.perf_counter()
    got = np.asarray(kernel_call(*ins))
    t_kernel = time.perf_counter() - t0
    t0 = time.perf_counter()
    want = np.asarray(lax_fn(*ins))
    t_lax = time.perf_counter() - t0
    ok = (got.shape == want.shape
          and np.allclose(got, want, rtol=PARITY_RTOL, atol=PARITY_ATOL))
    return ok, t_kernel, t_lax


def _record_calibration(digest, platform, t_kernel, t_lax):
    """Measured kernel-vs-lax seconds into the CalibrationStore
    (kind="kernel" / "kernel_lax") — once per (group, platform,
    process). Advisory: failures never block a build."""
    key = (digest, platform)
    with _LOCK:
        if key in _CAL_RECORDED:
            return
        _CAL_RECORDED.add(key)
    try:
        from ..profiling import calibration_store

        store = calibration_store()
        store.record(digest, platform, "kernel", t_kernel)
        store.record(digest, platform, "kernel_lax", t_lax)
    except Exception:
        pass


def _tuned_choice(digest, platform):
    try:
        from .tuner import choose_fusion_kernel

        return choose_fusion_kernel(digest, platform)
    except Exception:
        return "pallas"


def _build_and_verify(spec, ext_avals, digest, template, cfg, platform):
    """Build one group's kernel for one shape signature: emit, verify
    interpret-mode parity vs the lax twin, time both into calibration,
    wrap in custom_vjp. Returns ("ok", callable) or ("demoted",
    reason)."""
    import jax

    lax_fn = group_lax_fn(spec)
    try:
        out_aval = jax.eval_shape(
            lax_fn, *[jax.ShapeDtypeStruct(s, d) for s, d in ext_avals])
    except Exception:
        return ("demoted", "irregular_shapes")
    if (not all(np.issubdtype(d, np.floating) for _, d in ext_avals)
            or not np.issubdtype(np.dtype(out_aval.dtype), np.floating)):
        return ("demoted", "unsupported_dtype")
    interpret = bool(cfg.interpret) or platform != "tpu"
    emit = _EMITTERS[template]
    try:
        kernel = emit(spec, ext_avals, out_aval, interpret)
        parity_kernel = kernel if interpret else \
            emit(spec, ext_avals, out_aval, True)
    except _Unsupported as e:
        return ("demoted", str(e))
    except Exception:
        return ("demoted", "irregular_shapes")
    try:
        ok, t_kernel, t_lax = _parity_and_time(
            parity_kernel, lax_fn, ext_avals, digest)
    except Exception:
        return ("demoted", "parity")
    with _LOCK:
        _COUNTS["parity_checks"] += 1
        if not ok:
            _COUNTS["parity_failures"] += 1
    if not ok:
        return ("demoted", "parity")
    _record_calibration(digest, platform, t_kernel, t_lax)
    with _LOCK:
        _COUNTS["kernels_built"] += 1
    return ("ok", _make_fused_callable(lax_fn, kernel))


# -------------------------------------------------------------- planning
@dataclass(frozen=True)
class CodegenPlan:
    """Executor routing for one optimized symbol: `skip` are node
    indices computed INSIDE a fused kernel, `fused` maps each group's
    output index to (callable, external (index, out_i) keys), and
    `cache_component` is the exec-cache key term recording every
    group's final decision."""

    skip: frozenset
    fused: dict
    cache_component: tuple


_EMPTY_PLAN = CodegenPlan(frozenset(), {}, ())


def _lower_group(graph, members, digest, cfg, platform, order,
                 shapes, dtypes):
    """Final per-group decision for one bind. Returns
    ("pallas", (callable, ext)) or ("fallback", reason)."""
    spec, ext = _group_spec(graph.nodes, members)
    if platform != "tpu" and not cfg.interpret:
        return ("fallback", "platform"), spec
    # MXNET_FUSION_INTERPRET forces the generated kernel even where
    # the store says lax wins (interpret-mode timings WOULD say that
    # everywhere — the flag exists to exercise the kernel path anyway)
    if not cfg.interpret and _tuned_choice(digest, platform) == "lax":
        return ("fallback", "calibrated_slower"), spec
    if shapes is None:
        return ("fallback", "irregular_shapes"), spec
    avals = []
    for src, oi in ext:
        s = shapes.get((order[src], oi))
        if s is None:
            return ("fallback", "irregular_shapes"), spec
        dt = np.dtype(dtypes.get((order[src], oi), np.float32))
        avals.append((tuple(int(d) for d in s), dt))
    template = _template_of(spec)
    key = (digest, tuple(avals), bool(cfg.interpret))
    with _LOCK:
        cached = _KERNELS.get(key)
    if cached is None:
        cached = _build_and_verify(spec, avals, digest, template, cfg,
                                   platform)
        with _LOCK:
            _KERNELS[key] = cached
    status, payload = cached
    if status != "ok":
        return ("fallback", payload), spec
    return ("pallas", (payload, ext)), spec


def plan_for(symbol, input_shapes=None):
    """Codegen plan for an OPTIMIZED (pipeline-stamped) symbol.

    `input_shapes` maps variable names to shapes (args + auxs — the
    executor's bind signature); without it every candidate falls back
    with reason "irregular_shapes". Node indices refer to
    `symbol._topo` order — identical to the executor's trace order and
    to `Graph.from_symbol`. The returned `cache_component` joins the
    exec-cache key, so a fused program and its fallback twin can never
    collide."""
    from ..symbol import _graph_infer, _topo
    from .ir import Graph

    graph = Graph.from_symbol(symbol)
    groups = _groups_in(graph.nodes)
    if not groups:
        return _EMPTY_PLAN
    import jax

    platform = jax.default_backend()
    cfg = codegen_config()
    order = _topo(symbol._outputs)
    shapes = dtypes = None
    if input_shapes:
        try:
            shapes, dtypes = _graph_infer(
                symbol._outputs,
                {k: tuple(v) for k, v in input_shapes.items()}, {},
                partial=True)
        except Exception:
            shapes = dtypes = None
    skip, fused, component = set(), {}, []
    for tag in sorted(groups):
        members = sorted(groups[tag])
        out = members[-1]
        stamp = graph.nodes[out].extra.get("__fusion_codegen__", "")
        if not cfg.enabled:
            # live check, independent of the stamp: optimize_for_bind
            # memoizes the stamped graph, so a candidate stamp may
            # predate the knob flip — the OFF switch must win anyway
            spec, ext = _group_spec(graph.nodes, members)
            digest = group_digest(spec, len(ext))
            decision = ("fallback", "disabled")
        elif stamp.startswith("candidate:"):
            digest = stamp[len("candidate:"):]
            decision, spec = _lower_group(
                graph, members, digest, cfg, platform, order, shapes,
                dtypes)
        else:
            spec, ext = _group_spec(graph.nodes, members)
            digest = group_digest(spec, len(ext))
            if stamp.startswith("fallback:"):
                decision = ("fallback", stamp[len("fallback:"):])
            else:
                # tagged by fusion_hints but never stamped (codegen
                # stage off in the pipeline spec): counted, not dropped
                decision = ("fallback", "unplanned")
        ops = [s[0] for s in spec]
        if decision[0] == "pallas":
            fn, ext_keys = decision[1]
            skip.update(members[:-1])
            fused[out] = (fn, tuple(ext_keys))
            component.append((tag, f"pallas:{digest}"))
            _note_group(digest, tag, ops, _template_of(spec), "pallas")
        else:
            component.append((tag, f"fallback:{decision[1]}"))
            _note_group(digest, tag, ops, _template_of(spec),
                        "fallback", decision[1])
    return CodegenPlan(frozenset(skip), fused, tuple(component))

"""mxnet_tpu.passes: graph-optimization pass pipeline + tuning.

The Relay-style layer between Symbol construction and the executor
(ROADMAP item 2): graph-to-graph transforms over the node-list IR
(`ir.Graph`), run by a `PassManager` that compacts and re-verifies
after every pass, wired into `Executor._build` ahead of the exec-cache
lookup (MXNET_GRAPH_PASSES, default on) so the cache keys on the
optimized canonical graph — isomorphic-but-differently-built networks
collide onto one compiled program. `cost_model`/`Autotuner` pick
layout / multistep-k / bucket-grid per (canonical graph, platform),
analytic-first, persisted at MXNET_TUNING_CACHE.

See docs/passes.md for the pass catalog and custom-pass registration.
"""
from __future__ import annotations

import hashlib

from . import cost_model, ir, pallas_codegen, transforms, tuner  # noqa: F401
from .ir import Graph, GraphNode  # noqa: F401
from .pallas_codegen import (  # noqa: F401
    CodegenConfig,
    CodegenPlan,
    codegen_config,
    fusion_group_records,
    fusion_stats,
    plan_for,
    reset_fusion_stats,
)
from .manager import (  # noqa: F401
    PassManager,
    clear_memo,
    default_pipeline,
    graph_pass_stats,
    list_passes,
    optimize,
    optimize_for_bind,
    pipeline_spec,
    register_pass,
    reset_pass_stats,
)
from .tuner import Autotuner  # noqa: F401


def canonical_digest(symbol):
    """Stable hex digest of the canonicalized graph — the
    cross-process analog of `Symbol.structure_key()` (which contains
    unpicklable leaves). Runs the full default pipeline, so any two
    graphs the pipeline maps to one canonical form share a digest.
    Keys the tuning cache (tuner.py). Stats are suppressed: this is a
    KEY computation, not bind-time optimization work, so
    graphPassStats stays a ledger of real pipeline runs."""
    js = optimize(symbol, collect_stats=False).tojson()
    return hashlib.sha256(js.encode("utf-8")).hexdigest()[:16]

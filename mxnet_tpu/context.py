"""Device context.

Analog of the reference `Context` (include/mxnet/base.h:116-207) with a
first-class `tpu` device type beside cpu/gpu/cpu_pinned. A Context maps to
a concrete `jax.Device`; when the requested platform is absent (e.g. tests
on a CPU host mesh) the context degrades to the default jax backend so the
same user code runs everywhere — mirroring how the reference falls back
when built without CUDA.
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError


class Context:
    """Device handle: cpu/gpu/tpu/cpu_pinned + id, backed by a jax.Device.

    The reference Context (include/mxnet/base.h:116-207) with tpu
    first-class."""
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}

    _default_ctx = threading.local()

    def __init__(self, device_type, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in Context.devstr2type:
                raise MXNetError(f"unknown device type {device_type!r}")
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    # -- jax device resolution ------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device, degrading gracefully."""
        want = {"cpu": "cpu", "cpu_pinned": "cpu", "gpu": "gpu", "tpu": "tpu"}[
            self.device_type
        ]
        devs = _devices_for_platform(want)
        if not devs:
            devs = jax.devices()  # fall back to default backend
        return devs[self.device_id % len(devs)]

    def __enter__(self):
        if not hasattr(Context._default_ctx, "stack"):
            Context._default_ctx.stack = []
        Context._default_ctx.stack.append(self)
        return self

    def __exit__(self, *_):
        Context._default_ctx.stack.pop()


def _devices_for_platform(platform: str):
    # process-LOCAL devices: under jax.distributed each process may only
    # place data on its own devices (global jax.devices() lists peers'
    # devices too, which are not addressable here)
    try:
        return [
            d for d in jax.local_devices() if d.platform == platform
        ] or jax.devices(platform)
    except RuntimeError:
        # Experimental TPU tunnels may register under a different platform
        # name; treat any non-cpu accelerator as satisfying 'tpu'.
        if platform == "tpu":
            accel = [
                d for d in jax.local_devices() if d.platform != "cpu"
            ]
            return accel
        return []


def cpu(device_id: int = 0) -> Context:
    """CPU context."""
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """GPU context (resolves to the accelerator; alias tier)."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    """TPU context."""
    return Context("tpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    """Pinned-host context (maps to cpu under jax)."""
    return Context("cpu_pinned", device_id)


def current_context() -> Context:
    """Innermost `with Context(...)` scope, else the default."""
    stack = getattr(Context._default_ctx, "stack", None)
    if stack:
        return stack[-1]
    return default_context()


def default_context() -> Context:
    """Default = tpu when an accelerator is visible, else cpu."""
    accel = [d for d in jax.devices() if d.platform != "cpu"]
    return Context("tpu", 0) if accel else Context("cpu", 0)


def num_devices(device_type: str = "tpu") -> int:
    """Process-local device count for a device type."""
    devs = _devices_for_platform(device_type)
    return len(devs)


def set_memory_fraction(fraction, preallocate=None):
    """HBM pool sizing knob (counterpart of the reference's
    MXNET_GPU_MEM_POOL_RESERVE, src/storage/pooled_storage_manager.h:
    28-47). The XLA runtime owns the device allocator, so this maps to
    its client options — it must run BEFORE the first jax backend
    initialization in the process; afterwards it raises.

    Also reachable via env: MXNET_TPU_MEM_FRACTION (read at import).
    """
    import os

    import jax

    if jax._src.xla_bridge._backends:  # backend already materialized
        from .base import MXNetError

        raise MXNetError(
            "set_memory_fraction must be called before the first "
            "device use (the XLA client reads it at initialization)")
    os.environ["XLA_PYTHON_CLIENT_MEM_FRACTION"] = str(float(fraction))
    if preallocate is not None:
        os.environ["XLA_PYTHON_CLIENT_PREALLOCATE"] = (
            "true" if preallocate else "false")


def memory_stats(ctx=None):
    """Device-memory introspection (counterpart of the reference's
    pooled storage manager stats, src/storage/pooled_storage_manager.h:
    28-47 — there the pool is hand-managed; here allocation belongs to
    the XLA runtime, and this surfaces its per-device counters).

    Returns a dict (bytes_in_use, peak_bytes_in_use, bytes_limit, ...
    as provided by the PJRT backend) or {} on backends without memory
    accounting (CPU).
    """
    c = ctx if ctx is not None else current_context()
    dev = c.jax_device() if isinstance(c, Context) else c
    try:
        stats = dev.memory_stats()
    except Exception:
        return {}
    return dict(stats or {})


# MXNET_TPU_MEM_FRACTION: declarative form of set_memory_fraction,
# honored when the backend is not yet initialized (import-time here is
# before any device use in normal programs).
def _apply_mem_fraction_env():
    import os

    frac = os.environ.get("MXNET_TPU_MEM_FRACTION")
    if frac and not jax._src.xla_bridge._backends:
        os.environ.setdefault("XLA_PYTHON_CLIENT_MEM_FRACTION", frac)


_apply_mem_fraction_env()

"""Op-level device timelines: XLA trace durations → graph nodes.

The profiler's device capture (`profiler._collect_device_events`)
yields raw Chrome trace events: one `ph=="X"` slice per executed HLO,
named after the fused computation, with the original op path in the
event args (`long_name` / `tf_op` / `name` metadata XLA copies from
HLO op_metadata). The executor wraps every graph op in
`jax.named_scope(node_name)`, so that path carries OUR node names:
`jit(run_graph)/convolution0/convolution.3` attributes to
`convolution0`.

`aggregate_device_events` folds slices into per-node totals;
`ingest_device_events` accumulates across captures into the
process-wide table behind the `deviceTimelineStats` registry view
(/statusz top-K table, dump_profile embed). Attribution never
round-trips the device: it is pure JSON crunching at dump time."""
from __future__ import annotations

import os
import threading

from ..telemetry import register_view as _register_view

_lock = threading.Lock()
# node label -> {"count", "total_us", "max_us"}
_ops: "dict[str, dict]" = {}
_totals = {"events": 0, "captures": 0, "device_pids": set()}

_DEFAULT_TOPK = 20

# metadata keys XLA variously uses for the HLO op path, best first
_PATH_KEYS = ("long_name", "tf_op", "name", "op_name", "hlo_op")


def _topk():
    try:
        return max(1, int(os.environ.get("MXNET_PROFILING_TOPK",
                                         _DEFAULT_TOPK)))
    except ValueError:
        return _DEFAULT_TOPK


def attribute_event(ev):
    """Graph-node label for one trace slice: first path segment of the
    op metadata that is neither a jit wrapper nor an xla detail —
    with the executor's named_scope, that IS the node name. Falls back
    to the slice's own name (the fusion label)."""
    args = ev.get("args") or {}
    for key in _PATH_KEYS:
        path = args.get(key)
        if not isinstance(path, str) or not path:
            continue
        for seg in path.split("/"):
            seg = seg.strip()
            if not seg or seg.startswith(("jit(", "jvp(", "vjp(",
                                          "transpose(", "pjit")):
                continue
            # first segment under the jit wrappers: the named_scope
            # node name when present, else the raw HLO id — both are
            # the most framework-meaningful label available
            return seg
    name = ev.get("name")
    return str(name) if name else None


def aggregate_device_events(events):
    """Fold Chrome trace slices into {label: {count, total_us,
    max_us}}. Only complete slices (ph=='X' with a dur) carry device
    time; everything else (metadata, counters, B/E host pairs) is
    ignored."""
    out = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur = ev.get("dur")
        if not isinstance(dur, (int, float)) or dur < 0:
            continue
        label = attribute_event(ev)
        if not label:
            continue
        rec = out.get(label)
        if rec is None:
            rec = out[label] = {"count": 0, "total_us": 0.0,
                                "max_us": 0.0}
        rec["count"] += 1
        rec["total_us"] += float(dur)
        if dur > rec["max_us"]:
            rec["max_us"] = float(dur)
    return out


def ingest_device_events(events):
    """Merge one capture's slices into the process-wide table (the
    profiler calls this from dump_profile, so the view snapshot in the
    same dump already includes the capture being written)."""
    agg = aggregate_device_events(events)
    pids = {ev.get("pid") for ev in events
            if isinstance(ev.get("pid"), int)}
    with _lock:
        for label, rec in agg.items():
            cur = _ops.get(label)
            if cur is None:
                _ops[label] = dict(rec)
            else:
                cur["count"] += rec["count"]
                cur["total_us"] += rec["total_us"]
                if rec["max_us"] > cur["max_us"]:
                    cur["max_us"] = rec["max_us"]
        _totals["events"] += sum(r["count"] for r in agg.values())
        _totals["captures"] += 1 if events else 0
        _totals["device_pids"] |= pids
    return agg


def timeline_stats():
    """`deviceTimelineStats` view: top-K ops by total device time.
    {"ops": {label: {count, total_us, max_us, mean_us}}, "totals":
    {...}}; empty until a capture was ingested."""
    with _lock:
        if not _ops:
            return {}
        items = sorted(_ops.items(), key=lambda kv: -kv[1]["total_us"])
        k = _topk()
        ops = {}
        for label, rec in items[:k]:
            ops[label] = {
                "count": rec["count"],
                "total_us": round(rec["total_us"], 3),
                "max_us": round(rec["max_us"], 3),
                "mean_us": round(rec["total_us"] / rec["count"], 3),
            }
        return {
            "ops": ops,
            "totals": {
                "distinct_ops": len(_ops),
                "shown": len(ops),
                "events": _totals["events"],
                "captures": _totals["captures"],
                "devices": len(_totals["device_pids"]),
                "device_time_us": round(
                    sum(r["total_us"] for r in _ops.values()), 3),
            },
        }


def reset_timeline():
    with _lock:
        _ops.clear()
        _totals["events"] = 0
        _totals["captures"] = 0
        _totals["device_pids"] = set()


_register_view("deviceTimelineStats", timeline_stats,
               prom_prefix="device_timeline", omit_empty=True)

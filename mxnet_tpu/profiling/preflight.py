"""HBM pre-flight: "will this bind fit?" answered BEFORE the OOM.

`preflight_bind` runs inside `Executor._build` before any tracing: it
estimates the bind's device-memory footprint from information that is
free at that point — argument/aux buffer bytes, gradient buffers
(grad_req != null), optimizer state as a multiple of gradient bytes
(MXNET_PROFILING_OPT_FACTOR, default 2.0 = Adam's m+v), and
activations as the tile-padded output bytes of every non-variable
node (doubled under training for the saved forward values) — and
compares it against the device memory cap. Footprint over cap emits a
structured `HBMPreflightWarning` carrying the full report with
parameter-level attribution; MXNET_PROFILING_HBM_STRICT=1 upgrades it
to `HBMPreflightError`. Either way ZERO device programs were traced —
the whole point is to answer before XLA commits memory.

The cap comes from MXNET_PROFILING_DEVICE_MEM_BYTES when set (tests,
or machines where jax under-reports), else `device.memory_stats()`
(`bytes_limit`); CPU jax returns None there, so on CPU the check
silently records the report and never warns — exactly the degraded
behavior a host-memory backend wants.

A sharded bind divides each parameter's bytes by the product of the
mesh-axis sizes its fitted PartitionSpec actually uses (best-effort;
an unresolvable name stays replicated = conservative)."""
from __future__ import annotations

import math
import os
import threading
import warnings

_lock = threading.Lock()
_last = None  # most recent report dict (deviceStats embeds it)

_TOP_PARAMS = 8


class HBMPreflightWarning(UserWarning):
    """Estimated bind footprint exceeds the device memory cap. The
    `report` attribute holds the full breakdown (same dict as
    `last_preflight()`)."""

    def __init__(self, report):
        self.report = report
        gib = 1 << 30
        super().__init__(
            "HBM pre-flight: bind footprint ~"
            f"{report['total_bytes'] / gib:.2f} GiB exceeds device "
            f"memory {report['cap_bytes'] / gib:.2f} GiB "
            f"(params {report['param_bytes'] / gib:.2f} + grads "
            f"{report['grad_bytes'] / gib:.2f} + opt "
            f"{report['opt_bytes'] / gib:.2f} + activations "
            f"{report['activation_bytes'] / gib:.2f}); largest: "
            + ", ".join(f"{n}={b / gib:.3f}GiB"
                        for n, b in report["top_params"]))


class HBMPreflightError(RuntimeError):
    """Strict-mode pre-flight failure (MXNET_PROFILING_HBM_STRICT=1)."""

    def __init__(self, report):
        self.report = report
        super().__init__(str(HBMPreflightWarning(report)))


def _strict():
    # registered in mxnet_tpu.utils; raw read keeps bind import-light
    return os.environ.get("MXNET_PROFILING_HBM_STRICT", "0").lower() \
        in ("1", "true", "on")


def _opt_factor():
    try:
        return float(os.environ.get("MXNET_PROFILING_OPT_FACTOR",
                                    "2.0"))
    except ValueError:
        return 2.0


def _device_cap():
    """Device memory cap in bytes, or None when unknowable (CPU)."""
    env = os.environ.get("MXNET_PROFILING_DEVICE_MEM_BYTES")
    if env:
        try:
            cap = int(env)
            return cap if cap > 0 else None
        except ValueError:
            pass
    try:
        import jax

        stats = jax.devices()[0].memory_stats()
        if stats:
            return int(stats.get("bytes_limit", 0)) or None
    except Exception:
        pass
    return None


def _nbytes(shape, dtype):
    import numpy as np

    n = np.dtype(dtype).itemsize
    for d in shape:
        n *= int(d)
    return n


def _shard_divisor(plan, name, ndim):
    """Product of mesh-axis sizes the plan's fitted spec for `name`
    uses — the per-device storage divisor. 1 (replicated) on any
    failure: over-estimating is the safe direction for a pre-flight."""
    if plan is None:
        return 1
    try:
        spec = plan.spec_for(name, ndim)
        sizes = plan.axis_sizes
        div = 1
        for entry in tuple(spec):
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if ax is not None:
                    div *= int(sizes.get(ax, 1))
        return max(div, 1)
    except Exception:
        return 1


def _activation_bytes(symbol, input_shapes, training):
    """Tile-padded bytes of every non-variable node output — the live
    intermediate set XLA must place somewhere. Training doubles it:
    the backward pass keeps forward values alive (mirror off)."""
    from ..passes import cost_model as _cm
    from ..symbol import _graph_infer, _topo

    known = {k: tuple(v) for k, v in input_shapes.items()}
    shapes, dtypes = _graph_infer(symbol._outputs, known, {},
                                  partial=True)
    total = 0
    for n in _topo(symbol._outputs):
        if n.is_variable:
            continue
        params = n.op.normalize_params(n.attrs)
        for i in range(n.op.resolved_num_outputs(params)):
            s = shapes.get((n, i))
            if s is None:
                continue
            dt = dtypes.get((n, i)) or "float32"
            total += _cm.padded_elems(s, dt) * _np_itemsize(dt)
    return total * (2 if training else 1)


def _np_itemsize(dtype):
    import numpy as np

    return np.dtype(dtype).itemsize


def preflight_bind(symbol, args, grad_req, auxs=None, plan=None,
                   data_names=()):
    """Estimate a bind's footprint and warn/raise when it exceeds the
    device cap (module docstring). `args`/`auxs` map name -> (shape,
    dtype); `grad_req` maps name -> req string; `data_names` marks
    inputs excluded from the parameter attribution table. Returns the
    report dict (also kept as `last_preflight()`); never traces."""
    auxs = auxs or {}
    params = {}          # name -> per-device bytes
    grad_bytes = 0
    data_like = set(data_names)
    for name, (shape, dtype) in args.items():
        b = _nbytes(shape, dtype)
        b //= _shard_divisor(plan, name, len(shape))
        params[name] = b
        if grad_req.get(name, "null") != "null":
            grad_bytes += b
    for name, (shape, dtype) in auxs.items():
        params[name] = (_nbytes(shape, dtype)
                        // _shard_divisor(plan, name, len(shape)))
    param_bytes = sum(params.values())
    opt_bytes = int(grad_bytes * _opt_factor()) if grad_bytes else 0
    training = any(v != "null" for v in grad_req.values())
    try:
        act_bytes = _activation_bytes(
            symbol, {n: s for n, (s, _) in args.items()}, training)
    except Exception:
        act_bytes = 0  # uninferable graph: report what is known
    # batch-sharded activations: every data-like mesh axis splits them
    if plan is not None:
        try:
            sizes = plan.axis_sizes
            div = max(
                math.prod(sizes.get(a, 1) for a in plan.batch_axes()),
                1)
            act_bytes //= div
        except Exception:
            pass

    total = param_bytes + grad_bytes + opt_bytes + act_bytes
    cap = _device_cap()
    attributable = {n: b for n, b in params.items()
                    if n not in data_like}
    top = sorted(attributable.items(), key=lambda kv: -kv[1])
    report = {
        "param_bytes": param_bytes,
        "grad_bytes": grad_bytes,
        "opt_bytes": opt_bytes,
        "activation_bytes": act_bytes,
        "total_bytes": total,
        "cap_bytes": cap,
        "fits": (cap is None) or (total <= cap),
        "training": training,
        "sharded": plan is not None,
        "top_params": top[:_TOP_PARAMS],
        "n_params": len(params),
    }
    global _last
    with _lock:
        _last = report
    if cap is not None and total > cap:
        if _strict():
            raise HBMPreflightError(report)
        warnings.warn(HBMPreflightWarning(report), stacklevel=3)
    return report


def last_preflight():
    """Most recent pre-flight report (None before any bind)."""
    with _lock:
        return dict(_last) if _last is not None else None


def reset_preflight():
    global _last
    with _lock:
        _last = None

"""mxnet_tpu.profiling — device-side observability.

PR 7's telemetry layer sees the host (metrics, spans, endpoints); this
package sees the DEVICE. Three capabilities, each feeding the central
telemetry registry so /metrics, /statusz, and dump_profile expose them
with zero extra wiring:

  executable accounting (device_stats)
      Every jit built through the framework's chokepoints — the exec
      cache's per-mode programs, `sharding.lower.jit_sharded`, the
      decode engine's prefill/decode grid — is wrapped in an
      `InstrumentedJit` that compiles ahead-of-time on first call per
      input signature, captures `compiled.memory_analysis()` (argument
      / output / temp / generated-code bytes) + `cost_analysis()`
      (flops, bytes accessed) + wall trace/compile time, and then
      dispatches through the captured executable (ONE compile — the
      record is free). Records key on canonical digest + kind;
      `deviceStats` is the registry view.

  HBM pre-flight (preflight)
      Before a bind traces anything, estimate params + grads + opt
      state + activations against the device memory cap and emit a
      structured `HBMPreflightWarning` (or raise under
      MXNET_PROFILING_HBM_STRICT=1) with parameter-level attribution —
      the "will this fit?" answer BEFORE the OOM, not after.

  measured-cost calibration (calibration)
      `CalibrationStore` persists (canonical digest, platform, kind) →
      measured seconds, harvested automatically during serving /
      decoding warmup and `fit` epochs (the background refinement
      ROADMAP item 2 asks for). `passes.cost_model.calibrated_cost`
      blends it with the analytic model: measured wins when present,
      analytic otherwise (the Kaufman-et-al. learned-model recipe,
      PAPERS.md, reduced to its lookup table).

Plus `timeline`: the op-level device-time aggregator that attributes
XLA trace durations back to graph nodes (the executor wraps every op
in `jax.named_scope(node_name)`, so HLO metadata carries our names).

Everything is on by default and CPU-safe; MXNET_PROFILING=0 restores
raw jit dispatch everywhere.
"""
from __future__ import annotations

from .calibration import CalibrationStore, calibration_store
from .device_stats import (InstrumentedJit, device_stats, instrument,
                           profiling_enabled, records_for,
                           reset_device_stats)
from .preflight import (HBMPreflightError, HBMPreflightWarning,
                        last_preflight, preflight_bind)
from .timeline import (aggregate_device_events, ingest_device_events,
                       timeline_stats)

__all__ = [
    "CalibrationStore", "calibration_store",
    "InstrumentedJit", "device_stats", "instrument",
    "profiling_enabled", "records_for", "reset_device_stats",
    "HBMPreflightError", "HBMPreflightWarning",
    "last_preflight", "preflight_bind",
    "aggregate_device_events", "ingest_device_events",
    "timeline_stats",
]

"""Per-executable accounting: HBM footprint, compile time, flops.

`instrument(jitted, digest=..., kind=...)` wraps a `jax.jit` callable
in an `InstrumentedJit`. The wrapper compiles ahead-of-time on the
first call of each input signature (`fn.lower(*args).compile()` — the
dp_step AOT idiom, generalized), records the executable's
`memory_analysis()` / `cost_analysis()` / wall trace+compile seconds
into the process-wide record table, and then dispatches every call
through the captured `Compiled`. One compile total: the record costs
nothing the plain jit would not have paid.

Fallbacks keep the wrapper strictly weaker than jit, never stronger:
a tracer argument (nested trace), an unhashable signature, a failed
lower/compile, or an aval drift at call time (a differently-sized
final batch) all re-dispatch through the raw jit — the dp_step
`except (TypeError, ValueError)` convention. MXNET_PROFILING=0
bypasses everything.

Records key on (digest, kind): `digest` is the executable family (the
exec cache hands its entry digest; the decode engine a config hash;
jit_sharded a caller label), `kind` the program flavor ("fwd",
"train_step", "decode@8", ...). Multiple signatures of one family
merge: compile/trace seconds accumulate, byte/flop fields keep the
largest signature seen (the footprint that matters for HBM planning).

The `deviceStats` registry view serves /statusz and dump_profile;
native Prometheus instruments cover the scrape path.
"""
from __future__ import annotations

import os
import threading
import time

import jax

from ..telemetry import register_view as _register_view
from ..telemetry import registry as _treg

_DEFAULT_MAX_SIGS = 64

_lock = threading.Lock()
# (digest, kind) -> record dict (see _new_record)
_records: "dict[tuple, dict]" = {}
_totals = {"fallbacks": 0, "compile_errors": 0,
           "compiles": 0,      # real XLA compiles this process paid
           "disk_loads": 0}    # executables restored AOT from the
                               # exec_cache_disk tier (compile_s≈0)


def _disk_tier():
    """The exec_cache_disk module when a cache dir / bundle overlay is
    mounted, else None — the single gate every disk hook goes
    through, so an unset MXNET_EXEC_CACHE_DIR costs one attr check."""
    try:
        from .. import exec_cache_disk as _disk

        return _disk if _disk.tier_active() else None
    except Exception:
        return None

# native Prometheus companions of the deviceStats snapshot
_EXECUTABLES = _treg.gauge(
    "mxnet_tpu_profiling_executables",
    "Distinct device executables captured by the profiling layer")
_COMPILE_SECONDS = _treg.counter(
    "mxnet_tpu_profiling_compile_seconds_total",
    "Wall seconds spent in XLA compilation, by program kind")
_HBM_PEAK = _treg.gauge(
    "mxnet_tpu_profiling_executable_hbm_bytes_peak",
    "Largest single-executable HBM footprint (args+outputs+temps+code)")


def profiling_enabled():
    # registered in mxnet_tpu.utils; raw read keeps the hot path
    # import-light (the exec_cache MXNET_EXEC_CACHE convention)
    return os.environ.get("MXNET_PROFILING", "1").lower() not in (
        "0", "false", "off")


def _max_sigs():
    try:
        return max(1, int(os.environ.get("MXNET_PROFILING_MAX_SIGS",
                                         _DEFAULT_MAX_SIGS)))
    except ValueError:
        return _DEFAULT_MAX_SIGS


def _new_record(digest, kind, canonical, label):
    return {
        "digest": digest, "kind": kind,
        "canonical": canonical, "label": label,
        "executables": 0,
        "trace_s": 0.0, "compile_s": 0.0,
        "arg_bytes": 0, "out_bytes": 0, "temp_bytes": 0,
        "code_bytes": 0, "alias_bytes": 0, "hbm_bytes": 0,
        "flops": 0.0, "bytes_accessed": 0.0,
        "platform": None,
    }


def record_executable(digest, kind, compiled, trace_s, compile_s,
                      canonical=None, label=None, from_disk=False):
    """Merge one captured executable into the record table. Analyses
    that a backend does not implement degrade to zeros — the record
    (and its compile-time fields) exists regardless. `from_disk=True`
    marks an executable restored AOT by the exec_cache_disk tier: it
    bills `totals.disk_loads` instead of `totals.compiles` and carries
    compile_s≈0 (the restart win the deviceStats view exposes)."""
    mem = None
    try:
        mem = compiled.memory_analysis()
    except Exception:
        pass
    cost = None
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if isinstance(ca, dict):
            cost = ca
    except Exception:
        pass
    arg_b = int(getattr(mem, "argument_size_in_bytes", 0) or 0)
    out_b = int(getattr(mem, "output_size_in_bytes", 0) or 0)
    tmp_b = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    code_b = int(getattr(mem, "generated_code_size_in_bytes", 0) or 0)
    alias_b = int(getattr(mem, "alias_size_in_bytes", 0) or 0)
    # donated (aliased) bytes live inside the argument allocation —
    # don't double-count them in the footprint
    hbm = arg_b + out_b + tmp_b + code_b
    flops = float((cost or {}).get("flops", 0.0) or 0.0)
    bytes_acc = float((cost or {}).get("bytes accessed", 0.0) or 0.0)
    try:
        platform = jax.default_backend()
    except Exception:
        platform = None

    key = (str(digest), str(kind))
    with _lock:
        rec = _records.get(key)
        if rec is None:
            rec = _records[key] = _new_record(digest, kind, canonical,
                                              label)
        rec["executables"] += 1
        rec["trace_s"] += trace_s
        rec["compile_s"] += compile_s
        for field, val in (("arg_bytes", arg_b), ("out_bytes", out_b),
                           ("temp_bytes", tmp_b), ("code_bytes", code_b),
                           ("alias_bytes", alias_b), ("hbm_bytes", hbm),
                           ("flops", flops),
                           ("bytes_accessed", bytes_acc)):
            if val > rec[field]:
                rec[field] = val
        if canonical and not rec["canonical"]:
            rec["canonical"] = canonical
        rec["platform"] = platform
        if from_disk:
            _totals["disk_loads"] += 1
            rec["disk_loads"] = rec.get("disk_loads", 0) + 1
        else:
            _totals["compiles"] += 1
        n_records = len(_records)
        peak = max(r["hbm_bytes"] for r in _records.values())
    _COMPILE_SECONDS.inc(compile_s, kind=str(kind))
    _EXECUTABLES.set(n_records)
    _HBM_PEAK.set(peak)
    return hbm


def note_fallback(digest=None, kind=None, compile_error=False):
    with _lock:
        _totals["fallbacks"] += 1
        if compile_error:
            _totals["compile_errors"] += 1


def device_stats():
    """Snapshot: {"executables": {"digest:kind": record},
    "totals": {...}, "preflight": last pre-flight report (if any)}.
    Empty dict while nothing was captured (omit_empty view)."""
    with _lock:
        recs = {f"{d}:{k}": dict(r) for (d, k), r in _records.items()}
        totals = dict(_totals)
    from . import preflight as _pf

    pf = _pf.last_preflight()
    if not recs and pf is None:
        return {}
    totals.update({
        "count": len(recs),
        "compile_s": round(sum(r["compile_s"] for r in recs.values()),
                           6),
        "trace_s": round(sum(r["trace_s"] for r in recs.values()), 6),
        "hbm_peak_bytes": max(
            [r["hbm_bytes"] for r in recs.values()], default=0),
    })
    out = {"executables": recs, "totals": totals}
    if pf is not None:
        out["preflight"] = pf
    return out


def records_for(canonical=None, digest=None, kind=None):
    """Record list filtered by canonical digest / family digest /
    kind — the CI gate's join key against execCacheStats."""
    with _lock:
        recs = [dict(r) for r in _records.values()]
    if canonical is not None:
        recs = [r for r in recs if r["canonical"] == canonical]
    if digest is not None:
        recs = [r for r in recs if r["digest"] == digest]
    if kind is not None:
        recs = [r for r in recs if r["kind"] == kind]
    return recs


def reset_device_stats():
    with _lock:
        _records.clear()
        for k in _totals:
            _totals[k] = 0


_register_view("deviceStats", device_stats, prom_prefix="device",
               omit_empty=True)


# --------------------------------------------------------- the wrapper
def _sig_key(args, kwargs):
    """Hashable signature of a call: aval-shaped for array leaves,
    type+value for python scalars (static args bake into the compile).
    None => a tracer is present (nested trace: bypass AOT)."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for x in leaves:
        if isinstance(x, jax.core.Tracer):
            return None
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            sig.append((tuple(x.shape), str(x.dtype),
                        bool(getattr(x, "weak_type", False))))
        elif isinstance(x, (bool, int, float, complex, str, bytes,
                            type(None))):
            sig.append((type(x).__name__, x))
        else:
            raise TypeError(f"unhashable jit argument {type(x)}")
    return (treedef, tuple(sig))


class _FailedSig:
    """Sentinel: AOT capture unusable for this signature; dispatch raw."""

    __slots__ = ()


_FAILED = _FailedSig()


class _RecordingLowered:
    """Wraps `jax.stages.Lowered` so callers running the AOT protocol
    themselves (FusedTrainStep does `fn.lower(*args).compile()`) still
    land a record at compile time."""

    __slots__ = ("_lowered", "_wrapper", "_lower_s")

    def __init__(self, lowered, wrapper, lower_s):
        self._lowered = lowered
        self._wrapper = wrapper
        self._lower_s = lower_s

    def compile(self, *args, **kwargs):
        t0 = time.perf_counter()
        compiled = self._lowered.compile(*args, **kwargs)
        w = self._wrapper
        record_executable(w.digest, w.kind, compiled,
                          trace_s=self._lower_s,
                          compile_s=time.perf_counter() - t0,
                          canonical=w.canonical, label=w.label)
        return compiled

    def __getattr__(self, name):
        return getattr(self._lowered, name)


class InstrumentedJit:
    """AOT-capturing wrapper around one `jax.jit` callable (see module
    docstring). Strictly transparent: same results, one compile, jit
    fallback on anything unusual."""

    __slots__ = ("fn", "digest", "kind", "canonical", "label",
                 "_compiled", "_lock")

    def __init__(self, fn, digest, kind, canonical=None, label=None):
        self.fn = fn
        self.digest = str(digest)
        self.kind = str(kind)
        self.canonical = canonical
        self.label = label
        self._compiled = {}
        self._lock = threading.Lock()

    def __call__(self, *args, **kwargs):
        if not profiling_enabled():
            return self.fn(*args, **kwargs)
        try:
            key = _sig_key(args, kwargs)
        except TypeError:
            return self.fn(*args, **kwargs)
        if key is None:  # nested trace
            return self.fn(*args, **kwargs)
        entry = self._compiled.get(key)
        if entry is None:
            entry = self._capture(key, args, kwargs)
        if entry is _FAILED:
            return self.fn(*args, **kwargs)
        try:
            return entry(*args, **kwargs)
        except (TypeError, ValueError):
            # aval drift the signature key was too coarse to see —
            # the exact-shape executable refuses; jit re-dispatches
            note_fallback(self.digest, self.kind)
            return self.fn(*args, **kwargs)

    def _capture(self, key, args, kwargs):
        """lower+compile+record for one signature. Compilation runs
        OUTSIDE the instance lock (a concurrent duplicate costs one
        wasted compile; a lock held across XLA would serialize every
        signature of this family behind the compiler).

        Disk tier first: when exec_cache_disk is mounted, a compatible
        AOT-serialized executable for this exact (digest, kind,
        signature) deserializes in place of the lower+compile — zero
        trace, zero compile, recorded with from_disk=True. A fresh
        compile is serialized back so the NEXT process restores."""
        if len(self._compiled) >= _max_sigs():
            with self._lock:
                self._compiled.setdefault(key, _FAILED)
            return self._compiled[key]
        disk = _disk_tier()
        sighash = None
        if disk is not None:
            try:
                sighash = disk.sig_hash(key)
                restored = disk.load_executable(self.digest, self.kind,
                                                sighash)
            except Exception:
                restored = None
            if restored is not None:
                record_executable(self.digest, self.kind, restored,
                                  trace_s=0.0, compile_s=0.0,
                                  canonical=self.canonical,
                                  label=self.label, from_disk=True)
                with self._lock:
                    self._compiled.setdefault(key, restored)
                return self._compiled[key]
        try:
            t0 = time.perf_counter()
            lowered = self.fn.lower(*args, **kwargs)
            t1 = time.perf_counter()
            compiled = lowered.compile()
            t2 = time.perf_counter()
        except Exception:
            note_fallback(self.digest, self.kind, compile_error=True)
            with self._lock:
                self._compiled.setdefault(key, _FAILED)
            return self._compiled[key]
        record_executable(self.digest, self.kind, compiled,
                          trace_s=t1 - t0, compile_s=t2 - t1,
                          canonical=self.canonical, label=self.label)
        if disk is not None and sighash is not None:
            try:
                disk.store_executable(self.digest, self.kind, sighash,
                                      compiled)
            except Exception:
                pass  # serialization support is best-effort
        with self._lock:
            self._compiled.setdefault(key, compiled)
        return self._compiled[key]

    def lower(self, *args, **kwargs):
        """AOT protocol passthrough; the Lowered records on compile."""
        t0 = time.perf_counter()
        lowered = self.fn.lower(*args, **kwargs)
        return _RecordingLowered(lowered, self,
                                 time.perf_counter() - t0)

    def __getattr__(self, name):
        return getattr(self.fn, name)


def instrument(fn, digest, kind, canonical=None, label=None):
    """Wrap `fn` (a jax.jit callable) for executable accounting. A
    falsy digest returns `fn` unchanged — unkeyed programs stay raw."""
    if not digest:
        return fn
    return InstrumentedJit(fn, digest, kind, canonical=canonical,
                           label=label)

"""CalibrationStore: measured step/forward seconds, persisted.

The autotuner persists tuning CHOICES; this store persists tuning
EVIDENCE — (canonical digest, platform, kind) → measured wall seconds,
harvested for free at points where the framework is already timing
warm executions: `serving.ServedModel.warmup()` (one timed forward per
bucket), `decoding.DecodeEngine.warmup()` (one timed decode step per
bucket), and the `fit` epoch loop (epoch seconds / batches). ROADMAP
item 2's "measured records fed back into the cost model":
`cost_model.calibrated_cost()` reads this store and prefers a measured
record over its analytic estimate.

Persistence mirrors the tuner exactly: one JSON table at
MXNET_CALIBRATION_CACHE (default ~/.cache/mxnet_tpu/calibration.json),
loads are plain reads of an atomically-replaced file, saves re-merge
this process's full record set over the disk table and `os.replace` —
concurrent writers can each lose one race, never corrupt the file.
Repeat observations of one key fold by EWMA (alpha 0.3): calibration
tracks drift without thrashing on a single noisy measurement."""
from __future__ import annotations

import json
import os
import threading

_EWMA_ALPHA = 0.3


def _default_cache_path():
    from ..utils import getenv

    return os.path.expanduser(str(getenv("MXNET_CALIBRATION_CACHE")))


class CalibrationStore:
    """(digest, platform, kind) -> {"seconds", "samples", ...}.

    `kind` namespaces what was measured: "forward" (serving-style
    inference step), "decode_step", "prefill", "fit_step" — plus
    bucket-qualified variants ("forward[8x128]") when the harvest
    point knows its padding bucket."""

    def __init__(self, cache_path=None):
        self.cache_path = cache_path or _default_cache_path()
        self._lock = threading.Lock()
        self._local = {}  # this process's records (full-set merge save)

    # ------------------------------------------------------ persistence
    def _load(self):
        try:
            with open(self.cache_path) as f:
                table = json.load(f)
            return table if isinstance(table, dict) else {}
        except (OSError, ValueError):
            return {}

    def _save(self, table):
        from ..utils.persist import atomic_write_json

        atomic_write_json(self.cache_path, table)

    @staticmethod
    def _key(digest, platform, kind):
        return f"{digest}:{platform}:{kind}"

    # ---------------------------------------------------------- surface
    def record(self, digest, platform, kind, seconds, meta=None):
        """Fold one measurement in and persist. `seconds` <= 0 or a
        falsy digest is dropped (a timing that failed upstream)."""
        if not digest or not platform or seconds is None:
            return None
        seconds = float(seconds)
        if seconds <= 0:
            return None
        key = self._key(digest, platform, kind)
        with self._lock:
            prev = self._local.get(key)
            if prev is None:
                prev = self._load().get(key)
            if prev and prev.get("samples"):
                folded = (_EWMA_ALPHA * seconds
                          + (1 - _EWMA_ALPHA) * float(prev["seconds"]))
                rec = {
                    "digest": digest, "platform": platform,
                    "kind": kind, "seconds": folded,
                    "samples": int(prev["samples"]) + 1,
                }
            else:
                rec = {"digest": digest, "platform": platform,
                       "kind": kind, "seconds": seconds, "samples": 1}
            if meta:
                rec["meta"] = dict(meta)
            self._local[key] = rec
            pending = dict(self._local)
        # disk merge outside the lock (the tuner's convention): holding
        # a lock across filesystem latency is an MX006 violation and a
        # real stall for every other harvest point
        table = self._load()
        table.update(pending)
        try:
            self._save(table)
        except OSError:
            pass  # read-only cache dir: in-memory store still serves
        return rec

    def lookup(self, digest, platform, kind="forward"):
        """Record for the exact (digest, platform, kind), or None."""
        key = self._key(digest, platform, kind)
        with self._lock:
            rec = self._local.get(key)
        if rec is None:
            rec = self._load().get(key)
        return dict(rec) if rec else None

    def measured_seconds(self, digest, platform, kind="forward"):
        rec = self.lookup(digest, platform, kind)
        return float(rec["seconds"]) if rec else None

    def records(self, digest=None):
        """All records (disk ∪ local, local wins), optionally filtered
        by canonical digest."""
        table = self._load()
        with self._lock:
            table.update(self._local)
        if digest is not None:
            table = {k: v for k, v in table.items()
                     if v.get("digest") == digest}
        return table

    def clear(self):
        """Drop local records and the persisted table (tests)."""
        with self._lock:
            self._local.clear()
        try:
            os.unlink(self.cache_path)
        except OSError:
            pass


_default = None
_default_lock = threading.Lock()


def calibration_store():
    """The process-wide store every automatic harvest point writes to
    (path re-resolves when MXNET_CALIBRATION_CACHE changed — tests
    repoint it per-tmpdir)."""
    global _default
    path = _default_cache_path()
    with _default_lock:
        if _default is None or _default.cache_path != path:
            _default = CalibrationStore(path)
        return _default

"""Global PRNG state.

Analog of the reference's per-device mshadow Random resource seeded by
`mx.random.seed` (src/resource.cc SeedRandom). TPU-native: a single
counter-based jax PRNG key chain; every random op draws a fresh split.
Keys are recorded on the autograd tape so replay is deterministic.
"""
from __future__ import annotations

import random as _pyrandom
import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0

# Host-side RNG handles. Library code must draw through these instead
# of the bare `random` / `np.random` module functions (mxlint MX005):
# it keeps every draw visibly under mx.random.seed control, so two
# hosts (or two runs) stay in lockstep.
_py_rng = _pyrandom.Random(_DEFAULT_SEED)


def py_rng() -> "_pyrandom.Random":
    """The framework-owned stdlib RNG, reseeded by `seed()`."""
    return _py_rng


def np_rng():
    """numpy RandomState under `seed()` control.

    Returns numpy's global RandomState object, so draws interleave
    exactly as if made through ``np.random.*`` — `seed()` (and plain
    ``np.random.seed`` in tests) both steer it."""
    import numpy as _np

    return _np.random.mtrand._rand


def _key():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
    return _state.key


def seed(seed_state: int):
    """Seed the global PRNG (analog of MXRandomSeed).

    Also seeds numpy's global generator: host-side samplers
    (initializers, test utilities) draw from numpy, and the reference's
    MXRandomSeed controls initializer draws the same way."""
    import numpy as _np

    _state.key = jax.random.PRNGKey(int(seed_state))
    _np.random.seed(int(seed_state) & 0xFFFFFFFF)
    _py_rng.seed(int(seed_state))


def next_key():
    k = _key()
    _state.key, out = jax.random.split(k)
    return out


# Sampler front-ends (python/mxnet/random.py) are generated onto the
# ndarray module from the op registry; `uniform`/`normal` re-exported there.

"""ctypes bindings for the native IO core (native/recordio_core.cc).

Compiles the shared library on first use (g++ -O2 -shared; cached next
to the source, rebuilt when the source is newer). pybind11 is not in
the image, so the ABI is plain C consumed via ctypes — the same pattern
as the reference's Python-over-C-API layering (python/mxnet/base.py
dlopens libmxnet).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .base import MXNetError

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
)
_SRC = os.path.join(_NATIVE_DIR, "recordio_core.cc")
_SO = os.path.join(_NATIVE_DIR, "librecordio_core.so")

_lib = None
_lock = threading.Lock()


def _build():
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", _SO,
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True
    )
    if proc.returncode != 0:
        raise MXNetError(
            f"native build failed: {' '.join(cmd)}\n{proc.stderr}"
        )


def get_lib():
    """Load (building if needed) the native library."""
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SRC):
            raise MXNetError(f"native source missing: {_SRC}")
        if (
            not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            _build()
        lib = ctypes.CDLL(_SO)
        lib.rio_reader_open.restype = ctypes.c_void_p
        lib.rio_reader_open.argtypes = [ctypes.c_char_p]
        lib.rio_reader_next.restype = ctypes.c_int64
        lib.rio_reader_next.argtypes = [ctypes.c_void_p]
        lib.rio_reader_fetch.restype = None
        lib.rio_reader_fetch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8)
        ]
        lib.rio_reader_close.restype = None
        lib.rio_reader_close.argtypes = [ctypes.c_void_p]
        lib.rio_build_index.restype = ctypes.c_int64
        lib.rio_build_index.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
        ]
        lib.rio_prefetcher_start.restype = ctypes.c_void_p
        lib.rio_prefetcher_start.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int
        ]
        lib.rio_prefetcher_next.restype = ctypes.c_int64
        lib.rio_prefetcher_next.argtypes = [ctypes.c_void_p]
        lib.rio_prefetcher_fetch.restype = None
        lib.rio_prefetcher_fetch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8)
        ]
        lib.rio_prefetcher_stop.restype = None
        lib.rio_prefetcher_stop.argtypes = [ctypes.c_void_p]
        lib.rio_prefetcher_error.restype = ctypes.c_int64
        lib.rio_prefetcher_error.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64
        ]
        _lib = lib
        return _lib


def available():
    try:
        get_lib()
        return True
    except Exception:
        return False


_IMGDEC_SRC = os.path.join(_NATIVE_DIR, "image_decode.cc")
_IMGDEC_SO = os.path.join(_NATIVE_DIR, "libimage_decode.so")
_imgdec_lib = None


def get_lib_imgdec():
    """Load (building if needed) the native JPEG decode+augment pool
    (native/image_decode.cc; links the system libjpeg)."""
    global _imgdec_lib
    if _imgdec_lib is not None:
        return _imgdec_lib
    with _lock:
        if _imgdec_lib is not None:
            return _imgdec_lib
        if not os.path.exists(_IMGDEC_SRC):
            raise MXNetError(f"native source missing: {_IMGDEC_SRC}")
        if (
            not os.path.exists(_IMGDEC_SO)
            or os.path.getmtime(_IMGDEC_SO)
            < os.path.getmtime(_IMGDEC_SRC)
        ):
            proc = subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                 "-pthread", _IMGDEC_SRC, "-ljpeg", "-o", _IMGDEC_SO],
                capture_output=True, text=True,
            )
            if proc.returncode != 0:
                raise MXNetError(
                    f"native image decoder build failed:\n{proc.stderr}"
                )
        lib = ctypes.CDLL(_IMGDEC_SO)
        lib.imgdec_create.restype = ctypes.c_void_p
        lib.imgdec_create.argtypes = [ctypes.c_int]
        lib.imgdec_destroy.restype = None
        lib.imgdec_destroy.argtypes = [ctypes.c_void_p]
        lib.imgdec_batch.restype = None
        lib.imgdec_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint8),   # blob
            ctypes.POINTER(ctypes.c_int64),   # offsets
            ctypes.POINTER(ctypes.c_int64),   # lens
            ctypes.c_int,                     # n
            ctypes.c_int, ctypes.c_int,       # out_h, out_w
            ctypes.c_int,                     # resize_short
            ctypes.c_int, ctypes.c_int,       # rand_crop, rand_mirror
            ctypes.c_int,                     # chw layout
            ctypes.c_uint64,                  # seed
            ctypes.POINTER(ctypes.c_float),   # mean (or None)
            ctypes.POINTER(ctypes.c_float),   # std (or None)
            ctypes.POINTER(ctypes.c_float),   # out
            ctypes.POINTER(ctypes.c_uint8),   # ok flags
        ]
        lib.imgdec_batch_aug.restype = None
        lib.imgdec_batch_aug.argtypes = (
            lib.imgdec_batch.argtypes[:-2]
            + [ctypes.c_float] * 4            # brightness/contrast/
                                              # saturation/pca_noise
            + lib.imgdec_batch.argtypes[-2:]
        )
        lib.imgdec_batch_u8.restype = None
        # same as the aug entry minus mean/std, uint8 output
        lib.imgdec_batch_u8.argtypes = (
            lib.imgdec_batch.argtypes[:-4]
            + [ctypes.c_float] * 4
            + [ctypes.POINTER(ctypes.c_uint8),
               ctypes.POINTER(ctypes.c_uint8)]
        )
        _imgdec_lib = lib
        return _imgdec_lib


class NativeImageDecoder(object):
    """Fused JPEG decode -> resize-short -> crop -> mirror -> normalize
    -> CHW float32, on a persistent native thread pool (the
    ImageRecordIOParser2 analog, iter_image_recordio_2.cc:28)."""

    def __init__(self, nthreads=4, resize_short=0, rand_crop=False,
                 rand_mirror=False, mean=None, std=None,
                 layout="NCHW", brightness=0.0, contrast=0.0,
                 saturation=0.0, pca_noise=0.0):
        import numpy as np

        self._lib = get_lib_imgdec()
        self._h = self._lib.imgdec_create(int(nthreads))
        self.resize_short = int(resize_short)
        self.rand_crop = bool(rand_crop)
        self.rand_mirror = bool(rand_mirror)
        self.brightness = float(brightness)
        self.contrast = float(contrast)
        self.saturation = float(saturation)
        self.pca_noise = float(pca_noise)
        self.layout = layout.upper()
        def three(v, what):
            # C++ reads exactly [0..2]: broadcast scalars, reject odd
            # lengths (an OOB read would corrupt normalization silently)
            if v is None:
                return None
            a = np.asarray(v, np.float32).ravel()
            if a.size == 1:
                a = np.repeat(a, 3)
            if a.size != 3:
                raise ValueError(
                    f"{what} must be a scalar or length-3, got "
                    f"shape {np.shape(v)}")
            return np.ascontiguousarray(a)

        self._mean = three(mean, "mean")
        self._std = three(std, "std")

    def decode_batch(self, blobs, out, seed=0):
        """Decode `blobs` (list of bytes) into out[(n,3,H,W) float32]
        (or (n,H,W,3) for layout NHWC). Returns a uint8 array of
        per-image success flags."""
        import numpy as np

        n = len(blobs)
        if self.layout == "NHWC":
            h, w, c = out.shape[1], out.shape[2], out.shape[3]
        else:
            c, h, w = out.shape[1], out.shape[2], out.shape[3]
        assert c == 3 and out.dtype in (np.float32, np.uint8)
        if out.dtype == np.uint8 and (
                self._mean is not None or self._std is not None):
            raise ValueError(
                "uint8 output carries raw pixels; normalize on device "
                "(drop mean/std or use a float32 output)")
        blob = np.frombuffer(b"".join(blobs), dtype=np.uint8)
        lens = np.asarray([len(b) for b in blobs], np.int64)
        offs = np.zeros(n, np.int64)
        np.cumsum(lens[:-1], out=offs[1:])
        ok = np.zeros(n, np.uint8)
        fptr = ctypes.POINTER(ctypes.c_float)
        common = [
            self._h,
            blob.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, h, w, self.resize_short,
            1 if self.rand_crop else 0,
            1 if self.rand_mirror else 0,
            0 if self.layout == "NHWC" else 1,
            ctypes.c_uint64(seed & (2**64 - 1)),
            self._mean.ctypes.data_as(fptr)
            if self._mean is not None else None,
            self._std.ctypes.data_as(fptr)
            if self._std is not None else None,
        ]
        u8ptr = ctypes.POINTER(ctypes.c_uint8)
        if out.dtype == np.uint8:
            # common minus the mean/std pointers (u8 never normalizes)
            self._lib.imgdec_batch_u8(
                *common[:-2], self.brightness, self.contrast,
                self.saturation, self.pca_noise,
                out.ctypes.data_as(u8ptr), ok.ctypes.data_as(u8ptr))
            return ok
        tail = [
            out.ctypes.data_as(fptr),
            ok.ctypes.data_as(u8ptr),
        ]
        if self.brightness or self.contrast or self.saturation \
                or self.pca_noise:
            self._lib.imgdec_batch_aug(
                *common, self.brightness, self.contrast,
                self.saturation, self.pca_noise, *tail)
        else:
            self._lib.imgdec_batch(*common, *tail)
        return ok

    def close(self):
        if self._h:
            self._lib.imgdec_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


_PREDICT_SRC = os.path.join(_NATIVE_DIR, "capi_predict.cc")
_PREDICT_SO = os.path.join(_NATIVE_DIR, "libmxtpu_predict.so")


def embed_flags():
    """python3-config flags for embedding CPython, validated."""
    cfg = subprocess.run(
        ["python3-config", "--includes", "--ldflags", "--embed"],
        capture_output=True, text=True,
    )
    if cfg.returncode != 0 or not cfg.stdout.strip():
        raise MXNetError(
            "python3-config --embed failed (Python built without "
            f"embed support?): {cfg.stderr}"
        )
    return cfg.stdout.split()


def _build_embed_lib(src, so, label):
    """Compile an embeddable (CPython-hosting) C API library, cached by
    mtime."""
    if os.path.exists(so) and \
            os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    cmd = (
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", src]
        + embed_flags() + ["-o", so]
    )
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise MXNetError(
            f"{label} build failed: {' '.join(cmd)}\n{proc.stderr}"
        )
    return so


def build_predict_lib():
    """Build the embeddable C predict API (native/capi_predict.cc) —
    the amalgamation/libmxnet_predict analog. Returns the .so path."""
    return _build_embed_lib(_PREDICT_SRC, _PREDICT_SO, "predict lib")


_CORE_SRC = os.path.join(_NATIVE_DIR, "capi_core.cc")
_CORE_SO = os.path.join(_NATIVE_DIR, "libmxtpu_c.so")


def build_core_lib():
    """Build the embeddable core C API (native/capi_core.cc — NDArray/
    imperative/Symbol/Executor tiers of the reference c_api.h). Returns
    the .so path."""
    return _build_embed_lib(_CORE_SRC, _CORE_SO, "core C API")


_ENGINE_SRC = os.path.join(_NATIVE_DIR, "engine_core.cc")
_ENGINE_SO = os.path.join(_NATIVE_DIR, "libengine_core.so")
_engine_lib = None


def get_lib_engine():
    """Load (building if needed) the native dependency engine
    (native/engine_core.cc)."""
    global _engine_lib
    if _engine_lib is not None:
        return _engine_lib
    with _lock:
        if _engine_lib is not None:
            return _engine_lib
        if not os.path.exists(_ENGINE_SRC):
            raise MXNetError(f"native source missing: {_ENGINE_SRC}")
        if (
            not os.path.exists(_ENGINE_SO)
            or os.path.getmtime(_ENGINE_SO)
            < os.path.getmtime(_ENGINE_SRC)
        ):
            proc = subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 "-pthread", _ENGINE_SRC, "-o", _ENGINE_SO],
                capture_output=True, text=True,
            )
            if proc.returncode != 0:
                raise MXNetError(
                    f"native engine build failed:\n{proc.stderr}"
                )
        lib = ctypes.CDLL(_ENGINE_SO)
        lib.eng_create.restype = ctypes.c_void_p
        lib.eng_create.argtypes = [ctypes.c_int]
        lib.eng_new_var.restype = ctypes.c_uint64
        lib.eng_new_var.argtypes = [ctypes.c_void_p]
        lib.eng_push.restype = None
        lib.eng_push.argtypes = [
            ctypes.c_void_p,
            ctypes.CFUNCTYPE(None, ctypes.c_void_p),
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ]
        lib.eng_wait_all.restype = None
        lib.eng_wait_all.argtypes = [ctypes.c_void_p]
        lib.eng_destroy.restype = None
        lib.eng_destroy.argtypes = [ctypes.c_void_p]
        _engine_lib = lib
        return _engine_lib


class NativeRecordReader(object):
    """Sequential framed reader over the native core."""

    def __init__(self, path):
        lib = get_lib()
        self._lib = lib
        self._h = lib.rio_reader_open(path.encode())
        if not self._h:
            raise MXNetError(f"cannot open {path}")

    def read(self):
        """Next record bytes, or None at EOF."""
        n = self._lib.rio_reader_next(self._h)
        if n == -2:
            raise MXNetError("corrupt recordio file")
        if n == -1:
            return None
        if n == 0:
            return b""
        buf = (ctypes.c_uint8 * n)()
        self._lib.rio_reader_fetch(self._h, buf)
        return bytes(buf)

    def close(self):
        if self._h:
            self._lib.rio_reader_close(self._h)
            self._h = None

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativePrefetchReader(object):
    """Background-thread prefetching reader (the iter_prefetcher.h
    analog): the native worker reads ahead into a bounded queue while
    Python consumes."""

    def __init__(self, path, capacity=64, loop=False):
        lib = get_lib()
        self._lib = lib
        self._path = path
        self.capacity = capacity
        self._h = lib.rio_prefetcher_start(
            path.encode(), capacity, 1 if loop else 0
        )
        if not self._h:
            raise MXNetError(f"cannot start prefetcher on {path}")

    def read(self):
        n = self._lib.rio_prefetcher_next(self._h)
        if n == -2:
            # worker hit an error (corrupt framing / unreadable file);
            # surface it instead of a silently truncated epoch
            msg = ctypes.create_string_buffer(512)
            self._lib.rio_prefetcher_error(self._h, msg, 512)
            raise MXNetError(
                f"recordio prefetch failed on {self._path}: "
                f"{msg.value.decode() or 'unknown error'}"
            )
        if n < 0:
            return None
        if n == 0:
            return b""
        buf = (ctypes.c_uint8 * n)()
        self._lib.rio_prefetcher_fetch(self._h, buf)
        return bytes(buf)

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec

    def close(self):
        if self._h:
            self._lib.rio_prefetcher_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def build_index(path, max_records=1 << 24):
    """Offsets of every record (reference MXIndexedRecordIO .idx)."""
    lib = get_lib()
    buf = (ctypes.c_uint64 * max_records)()
    n = lib.rio_build_index(path.encode(), buf, max_records)
    if n < 0:
        raise MXNetError(f"cannot index {path}")
    return list(buf[: min(n, max_records)])

"""ctypes bindings for the native IO core (native/recordio_core.cc).

Compiles the shared library on first use (g++ -O2 -shared; cached next
to the source, rebuilt when the source is newer). pybind11 is not in
the image, so the ABI is plain C consumed via ctypes — the same pattern
as the reference's Python-over-C-API layering (python/mxnet/base.py
dlopens libmxnet).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from .base import MXNetError

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
)
_SRC = os.path.join(_NATIVE_DIR, "recordio_core.cc")
_SO = os.path.join(_NATIVE_DIR, "librecordio_core.so")

_lib = None
_lock = threading.Lock()


def _build():
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
        _SRC, "-o", _SO,
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True
    )
    if proc.returncode != 0:
        raise MXNetError(
            f"native build failed: {' '.join(cmd)}\n{proc.stderr}"
        )


def get_lib():
    """Load (building if needed) the native library."""
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SRC):
            raise MXNetError(f"native source missing: {_SRC}")
        if (
            not os.path.exists(_SO)
            or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        ):
            _build()
        lib = ctypes.CDLL(_SO)
        lib.rio_reader_open.restype = ctypes.c_void_p
        lib.rio_reader_open.argtypes = [ctypes.c_char_p]
        lib.rio_reader_next.restype = ctypes.c_int64
        lib.rio_reader_next.argtypes = [ctypes.c_void_p]
        lib.rio_reader_fetch.restype = None
        lib.rio_reader_fetch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8)
        ]
        lib.rio_reader_close.restype = None
        lib.rio_reader_close.argtypes = [ctypes.c_void_p]
        lib.rio_build_index.restype = ctypes.c_int64
        lib.rio_build_index.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
        ]
        lib.rio_prefetcher_start.restype = ctypes.c_void_p
        lib.rio_prefetcher_start.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int
        ]
        lib.rio_prefetcher_next.restype = ctypes.c_int64
        lib.rio_prefetcher_next.argtypes = [ctypes.c_void_p]
        lib.rio_prefetcher_fetch.restype = None
        lib.rio_prefetcher_fetch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8)
        ]
        lib.rio_prefetcher_stop.restype = None
        lib.rio_prefetcher_stop.argtypes = [ctypes.c_void_p]
        lib.rio_prefetcher_error.restype = ctypes.c_int64
        lib.rio_prefetcher_error.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64
        ]
        _lib = lib
        return _lib


def available():
    try:
        get_lib()
        return True
    except Exception:
        return False


_PREDICT_SRC = os.path.join(_NATIVE_DIR, "capi_predict.cc")
_PREDICT_SO = os.path.join(_NATIVE_DIR, "libmxtpu_predict.so")


def embed_flags():
    """python3-config flags for embedding CPython, validated."""
    cfg = subprocess.run(
        ["python3-config", "--includes", "--ldflags", "--embed"],
        capture_output=True, text=True,
    )
    if cfg.returncode != 0 or not cfg.stdout.strip():
        raise MXNetError(
            "python3-config --embed failed (Python built without "
            f"embed support?): {cfg.stderr}"
        )
    return cfg.stdout.split()


def _build_embed_lib(src, so, label):
    """Compile an embeddable (CPython-hosting) C API library, cached by
    mtime."""
    if os.path.exists(so) and \
            os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    cmd = (
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", src]
        + embed_flags() + ["-o", so]
    )
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise MXNetError(
            f"{label} build failed: {' '.join(cmd)}\n{proc.stderr}"
        )
    return so


def build_predict_lib():
    """Build the embeddable C predict API (native/capi_predict.cc) —
    the amalgamation/libmxnet_predict analog. Returns the .so path."""
    return _build_embed_lib(_PREDICT_SRC, _PREDICT_SO, "predict lib")


_CORE_SRC = os.path.join(_NATIVE_DIR, "capi_core.cc")
_CORE_SO = os.path.join(_NATIVE_DIR, "libmxtpu_c.so")


def build_core_lib():
    """Build the embeddable core C API (native/capi_core.cc — NDArray/
    imperative/Symbol/Executor tiers of the reference c_api.h). Returns
    the .so path."""
    return _build_embed_lib(_CORE_SRC, _CORE_SO, "core C API")


_ENGINE_SRC = os.path.join(_NATIVE_DIR, "engine_core.cc")
_ENGINE_SO = os.path.join(_NATIVE_DIR, "libengine_core.so")
_engine_lib = None


def get_lib_engine():
    """Load (building if needed) the native dependency engine
    (native/engine_core.cc)."""
    global _engine_lib
    if _engine_lib is not None:
        return _engine_lib
    with _lock:
        if _engine_lib is not None:
            return _engine_lib
        if not os.path.exists(_ENGINE_SRC):
            raise MXNetError(f"native source missing: {_ENGINE_SRC}")
        if (
            not os.path.exists(_ENGINE_SO)
            or os.path.getmtime(_ENGINE_SO)
            < os.path.getmtime(_ENGINE_SRC)
        ):
            proc = subprocess.run(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                 "-pthread", _ENGINE_SRC, "-o", _ENGINE_SO],
                capture_output=True, text=True,
            )
            if proc.returncode != 0:
                raise MXNetError(
                    f"native engine build failed:\n{proc.stderr}"
                )
        lib = ctypes.CDLL(_ENGINE_SO)
        lib.eng_create.restype = ctypes.c_void_p
        lib.eng_create.argtypes = [ctypes.c_int]
        lib.eng_new_var.restype = ctypes.c_uint64
        lib.eng_new_var.argtypes = [ctypes.c_void_p]
        lib.eng_push.restype = None
        lib.eng_push.argtypes = [
            ctypes.c_void_p,
            ctypes.CFUNCTYPE(None, ctypes.c_void_p),
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ]
        lib.eng_wait_all.restype = None
        lib.eng_wait_all.argtypes = [ctypes.c_void_p]
        lib.eng_destroy.restype = None
        lib.eng_destroy.argtypes = [ctypes.c_void_p]
        _engine_lib = lib
        return _engine_lib


class NativeRecordReader(object):
    """Sequential framed reader over the native core."""

    def __init__(self, path):
        lib = get_lib()
        self._lib = lib
        self._h = lib.rio_reader_open(path.encode())
        if not self._h:
            raise MXNetError(f"cannot open {path}")

    def read(self):
        """Next record bytes, or None at EOF."""
        n = self._lib.rio_reader_next(self._h)
        if n == -2:
            raise MXNetError("corrupt recordio file")
        if n == -1:
            return None
        if n == 0:
            return b""
        buf = (ctypes.c_uint8 * n)()
        self._lib.rio_reader_fetch(self._h, buf)
        return bytes(buf)

    def close(self):
        if self._h:
            self._lib.rio_reader_close(self._h)
            self._h = None

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativePrefetchReader(object):
    """Background-thread prefetching reader (the iter_prefetcher.h
    analog): the native worker reads ahead into a bounded queue while
    Python consumes."""

    def __init__(self, path, capacity=64, loop=False):
        lib = get_lib()
        self._lib = lib
        self._path = path
        self.capacity = capacity
        self._h = lib.rio_prefetcher_start(
            path.encode(), capacity, 1 if loop else 0
        )
        if not self._h:
            raise MXNetError(f"cannot start prefetcher on {path}")

    def read(self):
        n = self._lib.rio_prefetcher_next(self._h)
        if n == -2:
            # worker hit an error (corrupt framing / unreadable file);
            # surface it instead of a silently truncated epoch
            msg = ctypes.create_string_buffer(512)
            self._lib.rio_prefetcher_error(self._h, msg, 512)
            raise MXNetError(
                f"recordio prefetch failed on {self._path}: "
                f"{msg.value.decode() or 'unknown error'}"
            )
        if n < 0:
            return None
        if n == 0:
            return b""
        buf = (ctypes.c_uint8 * n)()
        self._lib.rio_prefetcher_fetch(self._h, buf)
        return bytes(buf)

    def __iter__(self):
        while True:
            rec = self.read()
            if rec is None:
                return
            yield rec

    def close(self):
        if self._h:
            self._lib.rio_prefetcher_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def build_index(path, max_records=1 << 24):
    """Offsets of every record (reference MXIndexedRecordIO .idx)."""
    lib = get_lib()
    buf = (ctypes.c_uint64 * max_records)()
    n = lib.rio_build_index(path.encode(), buf, max_records)
    if n < 0:
        raise MXNetError(f"cannot index {path}")
    return list(buf[: min(n, max_records)])

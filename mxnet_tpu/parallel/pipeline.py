"""Pipeline parallelism over a mesh 'pipe' axis.

New capability vs the reference (SURVEY.md §2.5: its only model
parallelism was ctx-group graph surgery with _CrossDeviceCopy inserts,
graph_executor.cc:242-318, example/model-parallel-lstm). TPU-native
design: every stage's weights live on its own mesh slice; microbatches
stream through the ring with `lax.ppermute` activations transfers (ICI
neighbor hops) under `shard_map` — the standard GPipe-style schedule
expressed as a collective program, compiled once by XLA.

The schedule: with S stages and M microbatches, run S+M-1 ticks; at
tick t, stage s processes microbatch t-s (bubble at the ends). Each
device holds ONE stage; the activation buffer rotates by one stage per
tick.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
try:
    from jax import shard_map
except ImportError:  # older jax keeps shard_map under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

if hasattr(jax.lax, "pcast"):
    def _pcast_varying(x, axis_name):
        return jax.lax.pcast(x, (axis_name,), to="varying")
else:
    # older jax: shard_map has no varying-axis tracking, every
    # per-device value is implicitly varying — identity is exact
    def _pcast_varying(x, axis_name):
        return x


def _stage_apply(fn, params, x, stage_idx):
    """Apply the per-stage fn with this device's stage params."""
    return fn(params, x, stage_idx)


def pipeline_apply(fn, stage_params, microbatches, mesh,
                   axis_name="pipe"):
    """Run a pipeline of S stages over M microbatches.

    fn(params_for_stage, x, stage_index) -> y   (same shape as x)
    stage_params: pytree whose leaves have leading dim S (stage-major;
      sharded over `axis_name`).
    microbatches: (M, ...) array of microbatch inputs (replicated).
    Returns (M, ...) outputs after the last stage.
    """
    s = mesh.shape[axis_name]
    m = microbatches.shape[0]

    def shard_fn(params, mb):
        # params leaves: (1, ...) local stage slice; mb: (M, ...) full
        idx = jax.lax.axis_index(axis_name)
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        ticks = s + m - 1
        x_shape = mb.shape[1:]
        buf = jnp.zeros(x_shape, mb.dtype)  # activation held here
        buf = _pcast_varying(buf, axis_name)
        outs = jnp.zeros((m,) + x_shape, mb.dtype)
        outs = _pcast_varying(outs, axis_name)

        def tick(t, carry):
            buf, outs = carry
            # stage 0 ingests microbatch t; other stages use the
            # activation that just arrived from the left neighbor
            mb_idx = jnp.clip(t, 0, m - 1)
            x_in = jnp.where(
                idx == 0,
                mb[mb_idx],
                buf,
            )
            active = (t - idx >= 0) & (t - idx < m)
            y = _stage_apply(fn, local, x_in, idx)
            y = jnp.where(active, y, buf)
            # last stage writes its finished microbatch t-(S-1)
            done_idx = jnp.clip(t - (s - 1), 0, m - 1)
            write = (idx == s - 1) & (t >= s - 1)
            outs = jnp.where(
                write,
                outs.at[done_idx].set(y),
                outs,
            )
            # rotate activations one stage to the right
            perm = [(i, (i + 1) % s) for i in range(s)]
            buf_next = jax.lax.ppermute(y, axis_name, perm)
            return buf_next, outs

        buf, outs = jax.lax.fori_loop(0, ticks, tick, (buf, outs))
        # only the last stage holds real outputs; broadcast them
        outs = jax.lax.psum(
            jnp.where(idx == s - 1, outs, jnp.zeros_like(outs)),
            axis_name,
        )
        return outs

    spec_params = jax.tree_util.tree_map(
        lambda _: P(axis_name), stage_params
    )
    fn_sharded = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec_params, P()),
        out_specs=P(),
    )
    return fn_sharded(stage_params, microbatches)


def pipeline_apply_hetero(stage_fns, flat_params, flat_auxs,
                          microbatches, mesh, axis_name="pipe"):
    """GPipe over HETEROGENEOUS stages — arbitrary per-stage programs,
    shape changes at boundaries, aux (BatchNorm) state — still ONE
    compiled SPMD program with per-stage memory scaling.

    The reference could split an arbitrary graph across devices with
    ctx groups (example/model-parallel-lstm/lstm.py:48-99); a
    homogeneous stage stack can't express embedding + blocks + head.
    SPMD needs every device to run the same program, so heterogeneity
    is encoded as data, not code:

      - each stage's parameters are flattened into one padded fp
        vector; the stack (S, Lmax) shards over `axis_name`, so a
        device holds ONLY its stage's weights (memory scales with S);
      - the stage body is `lax.switch(axis_index)` over the S stage
        functions — one program, S branches, each statically shaped;
      - boundary activations ride the ppermute ring as flat padded
        vectors of size max-over-boundaries; each branch unflattens
        its true input shape and re-pads its output.

    stage_fns: list of S callables
        fn_s(flat_param_vec, flat_aux_vec, xs, mb_idx)
          -> (ys, new_flat_aux_vec)
        where xs is a TUPLE of stage s's true-shaped inputs (for s=0 a
        1-tuple taken directly from `microbatches`, so integer token
        inputs are fine) and ys is a tuple of its true-shaped outputs —
        stage s+1's i-th input receives stage s's i-th output
        (residual/carry boundaries ride the same ring payload).
        Shapes are declared by `stage_fns[s].in_shapes` /
        `.in_dtypes` / `.out_shapes` / `.out_dtypes` attributes
        (lists, set by the caller). The LAST stage must declare exactly
        one output (the pipeline's result).
    flat_params: (S, Lmax) stage-major padded parameter stack.
    flat_auxs:   (S, Amax) stage-major padded aux stack (Amax may be 0).
    microbatches: (M, ...) stage-0 inputs, replicated.
    Returns ((M, *out_shape_last) outputs, (S, Amax) updated auxs).
    """
    s = mesh.shape[axis_name]
    m = microbatches.shape[0]
    assert len(stage_fns) == s
    assert len(stage_fns[-1].out_shapes) == 1, \
        "last pipeline stage must have exactly one output"

    import numpy as np

    def _payload(f):
        return sum(int(np.prod(sh)) for sh in f.out_shapes)

    last_shape = tuple(stage_fns[-1].out_shapes[0])
    out_dtype = stage_fns[-1].out_dtypes[0]
    # ring payload: the largest flattened boundary activation SET
    # (all of a stage's outputs concatenated). The LAST stage's output
    # never rides the ring (stage 0 ignores its incoming buf), so it
    # is excluded — for an LM whose head emits vocab-sized logits this
    # keeps the ppermute at d_model width.
    emax = max((_payload(f) for f in stage_fns[:-1]), default=1)

    def shard_fn(params, auxs, mb):
        idx = jax.lax.axis_index(axis_name)
        p_local = params[0]  # (Lmax,) this stage's padded weights
        a_local = auxs[0]    # (Amax,)
        ticks = s + m - 1
        buf = jnp.zeros((emax,), jnp.float32)
        buf = _pcast_varying(buf, axis_name)
        outs = jnp.zeros((m,) + last_shape, out_dtype)
        outs = _pcast_varying(outs, axis_name)
        a_var = a_local  # sharded input: already axis-varying

        def make_branch(si):
            fn = stage_fns[si]

            def branch(buf, a, mb_idx):
                if si == 0:
                    xs = (mb[mb_idx],)
                else:
                    xs, off = [], 0
                    for sh, dt in zip(fn.in_shapes, fn.in_dtypes):
                        e = int(np.prod(sh))
                        xs.append(
                            buf[off:off + e].reshape(sh).astype(dt))
                        off += e
                    xs = tuple(xs)
                ys, a2 = fn(p_local, a, xs, mb_idx)
                flat = jnp.concatenate(
                    [jnp.ravel(y).astype(jnp.float32) for y in ys])
                if flat.shape[0] > emax:  # last stage: ring discards it
                    flat = flat[:emax]
                pad = emax - flat.shape[0]
                if pad:
                    flat = jnp.concatenate(
                        [flat, jnp.zeros((pad,), jnp.float32)])
                return flat, a2, ys[0] if si == s - 1 else None

            return branch

        branches = [make_branch(si) for si in range(s)]

        def run_stage(buf, a, mb_idx):
            # last-stage output must be a uniform shape across
            # branches for lax.switch: non-last branches fabricate a
            # zero one
            def wrap(b):
                def f(args):
                    buf, a, mb_idx = args
                    flat, a2, y = b(buf, a, mb_idx)
                    if y is None:
                        y = _pcast_varying(
                            jnp.zeros(last_shape, out_dtype),
                            axis_name)
                    return flat, a2, y
                return f

            return jax.lax.switch(
                idx, [wrap(b) for b in branches], (buf, a, mb_idx))

        def tick(t, carry):
            buf, outs, a = carry
            mb_idx = jnp.clip(t - idx, 0, m - 1)
            active = (t - idx >= 0) & (t - idx < m)
            y_flat, a2, y_last = run_stage(buf, a, mb_idx)
            y_flat = jnp.where(active, y_flat, buf)
            a = jnp.where(active, a2, a)
            done_idx = jnp.clip(t - (s - 1), 0, m - 1)
            write = (idx == s - 1) & (t >= s - 1)
            outs = jnp.where(
                write, outs.at[done_idx].set(y_last), outs)
            perm = [(i, (i + 1) % s) for i in range(s)]
            buf_next = jax.lax.ppermute(y_flat, axis_name, perm)
            return buf_next, outs, a

        buf, outs, a_var = jax.lax.fori_loop(
            0, ticks, tick, (buf, outs, a_var))
        outs = jax.lax.psum(
            jnp.where(idx == s - 1, outs, jnp.zeros_like(outs)),
            axis_name,
        )
        return outs, a_var[None]

    kwargs = {}
    if not hasattr(jax.lax, "pcast"):
        # without pcast the replication checker cannot see that every
        # lax.switch branch is uniformly device-varying; disable it
        # (the modern checker validates this same program via pcast)
        kwargs["check_rep"] = False
    fn_sharded = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P()),
        out_specs=(P(), P(axis_name)),
        **kwargs,
    )
    return fn_sharded(flat_params, flat_auxs, microbatches)

"""Pipeline parallelism over a mesh 'pipe' axis.

New capability vs the reference (SURVEY.md §2.5: its only model
parallelism was ctx-group graph surgery with _CrossDeviceCopy inserts,
graph_executor.cc:242-318, example/model-parallel-lstm). TPU-native
design: every stage's weights live on its own mesh slice; microbatches
stream through the ring with `lax.ppermute` activations transfers (ICI
neighbor hops) under `shard_map` — the standard GPipe-style schedule
expressed as a collective program, compiled once by XLA.

The schedule: with S stages and M microbatches, run S+M-1 ticks; at
tick t, stage s processes microbatch t-s (bubble at the ends). Each
device holds ONE stage; the activation buffer rotates by one stage per
tick.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P


def _stage_apply(fn, params, x, stage_idx):
    """Apply the per-stage fn with this device's stage params."""
    return fn(params, x, stage_idx)


def pipeline_apply(fn, stage_params, microbatches, mesh,
                   axis_name="pipe"):
    """Run a pipeline of S stages over M microbatches.

    fn(params_for_stage, x, stage_index) -> y   (same shape as x)
    stage_params: pytree whose leaves have leading dim S (stage-major;
      sharded over `axis_name`).
    microbatches: (M, ...) array of microbatch inputs (replicated).
    Returns (M, ...) outputs after the last stage.
    """
    s = mesh.shape[axis_name]
    m = microbatches.shape[0]

    def shard_fn(params, mb):
        # params leaves: (1, ...) local stage slice; mb: (M, ...) full
        idx = jax.lax.axis_index(axis_name)
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        ticks = s + m - 1
        x_shape = mb.shape[1:]
        buf = jnp.zeros(x_shape, mb.dtype)  # activation held here
        buf = jax.lax.pcast(buf, (axis_name,), to="varying")
        outs = jnp.zeros((m,) + x_shape, mb.dtype)
        outs = jax.lax.pcast(outs, (axis_name,), to="varying")

        def tick(t, carry):
            buf, outs = carry
            # stage 0 ingests microbatch t; other stages use the
            # activation that just arrived from the left neighbor
            mb_idx = jnp.clip(t, 0, m - 1)
            x_in = jnp.where(
                idx == 0,
                mb[mb_idx],
                buf,
            )
            active = (t - idx >= 0) & (t - idx < m)
            y = _stage_apply(fn, local, x_in, idx)
            y = jnp.where(active, y, buf)
            # last stage writes its finished microbatch t-(S-1)
            done_idx = jnp.clip(t - (s - 1), 0, m - 1)
            write = (idx == s - 1) & (t >= s - 1)
            outs = jnp.where(
                write,
                outs.at[done_idx].set(y),
                outs,
            )
            # rotate activations one stage to the right
            perm = [(i, (i + 1) % s) for i in range(s)]
            buf_next = jax.lax.ppermute(y, axis_name, perm)
            return buf_next, outs

        buf, outs = jax.lax.fori_loop(0, ticks, tick, (buf, outs))
        # only the last stage holds real outputs; broadcast them
        outs = jax.lax.psum(
            jnp.where(idx == s - 1, outs, jnp.zeros_like(outs)),
            axis_name,
        )
        return outs

    spec_params = jax.tree_util.tree_map(
        lambda _: P(axis_name), stage_params
    )
    fn_sharded = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(spec_params, P()),
        out_specs=P(),
    )
    return fn_sharded(stage_params, microbatches)

"""KVStore('dist_async'): asynchronous parameter-server semantics.

Reference async mode (src/kvstore/kvstore_dist_server.h:136-229,
kvstore.cc:17-45 type table): each worker's push applies the optimizer
IMMEDIATELY on the server — no cross-worker barrier, no gradient
aggregation; pulls return whatever weights the server currently holds.
Fast workers don't wait for stragglers at the cost of gradient
staleness.

TPU-native adaptation: there is no separate server binary. Rank 0
co-hosts the server as a daemon thread, and the transport is the
jax.distributed *coordination service* KV store (the control plane) —
NOT the ICI/DCN data plane, which stays dedicated to the in-jit
collectives of the sync paths. That matches the role split of the
reference (zmq control sockets vs NCCL data channels) and keeps async
worker processes free to proceed at their own pace:

  worker push  -> kv_set  bytes at  ps/g/<key>/<rank>/<seq>
  server loop  -> polls expected seqs, applies updater per arrival
                  (async: per-push, per-worker, no merge), publishes
                  ps/w/<key> with a version counter
  worker pull  -> kv_get  ps/w/<key>   (blocking on first touch)

Liveness: every store heartbeats ps/hb/<rank> (epoch seconds);
`get_num_dead_node` counts stale ranks — the analog of ps-lite's
heartbeat surface (reference include/mxnet/kvstore.h:242).
"""
from __future__ import annotations

import pickle
import threading
import time

import numpy as np

from .. import optimizer as opt
from ..base import MXNetError
from ..kvstore import _ctype_key_value, _str_key
from ..ndarray import NDArray, array as nd_array
from .kvstore_tpu import KVStoreTPU

_HB_INTERVAL = 2.0  # seconds between heartbeats
_POLL = 0.005       # server poll period


def _client():
    from jax._src import distributed

    return distributed.global_state.client


def _try_get_bytes(key, timeout_ms=200):
    """None when the key is absent. Newer jaxlib exposes a true
    non-blocking probe (key_value_try_get_bytes); this jaxlib
    (<=0.4.36) only has the blocking get, so absence costs a short
    DEADLINE_EXCEEDED wait — the hot polling paths avoid per-key
    probes entirely via `_dir_get_bytes`."""
    cl = _client()
    fn = getattr(cl, "key_value_try_get_bytes", None)
    if fn is not None:
        try:
            return fn(key)
        except Exception:
            return None
    try:
        return cl.blocking_key_value_get_bytes(key, timeout_ms)
    except Exception:
        return None


def _try_get(key, timeout_ms=200):
    cl = _client()
    fn = getattr(cl, "key_value_try_get", None)
    if fn is not None:
        try:
            return fn(key)
        except Exception:
            return None
    try:
        return cl.blocking_key_value_get(key, timeout_ms)
    except Exception:
        return None


def _dir_get_bytes(prefix):
    """All (full_key, blob) pairs under `prefix` in ONE coordination-
    service round trip — the server polls gradients with this instead
    of probing every (key, rank, seq) cell individually."""
    try:
        return list(_client().key_value_dir_get_bytes(prefix))
    except Exception:
        return []


def _dir_get(prefix):
    try:
        return list(_client().key_value_dir_get(prefix))
    except Exception:
        return []


def _delete(key):
    try:
        _client().key_value_delete(key)
    except Exception:
        pass


def _set_bytes(key, blob):
    try:
        _client().key_value_set_bytes(key, blob, allow_overwrite=True)
    except TypeError:
        _client().key_value_set_bytes(key, blob)


def _dumps(arr):
    a = np.ascontiguousarray(arr)
    return pickle.dumps((a.dtype.str, a.shape, a.tobytes()), protocol=4)


def _loads(blob):
    dtype, shape, raw = pickle.loads(blob)
    return np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape).copy()


class KVStoreDistAsync(KVStoreTPU):
    """Async parameter server over the coordination-service KV store."""

    def __init__(self, kv_type="dist_async"):
        super().__init__(kv_type)
        import jax

        self._nproc = jax.process_count()
        self._rank = jax.process_index()
        self._seq = {}          # key -> my next push sequence number
        self._server = None
        self._stop = threading.Event()
        self._hb_thread = None
        if self._nproc > 1:
            self._start_heartbeat()

    # --------------------------------------------------------- lifecycle
    def _start_heartbeat(self):
        def beat_once():
            try:
                _client().key_value_set(
                    f"ps/hb/{self._rank}", str(time.time()),
                    allow_overwrite=True)
            except TypeError:
                _client().key_value_set(
                    f"ps/hb/{self._rank}", str(time.time()))
            except Exception:
                pass

        # first beat lands synchronously: init()'s startup barrier then
        # guarantees every rank's heartbeat is visible before any rank
        # can ask get_num_dead_node
        beat_once()

        def beat():
            while not self._stop.is_set():
                self._stop.wait(_HB_INTERVAL)
                beat_once()

        self._hb_thread = threading.Thread(
            target=beat, name="kv_heartbeat", daemon=True)
        self._hb_thread.start()

    def close(self, timeout=10.0):
        """Stop the heartbeat and (rank 0) co-hosted server threads.
        The joins are BOUNDED: a thread wedged inside a coordination-
        service RPC can no longer hang teardown (both are daemonic, so
        a missed join only forfeits the orderly exit, not the
        process)."""
        self._stop.set()
        for t in (self._hb_thread, self._server):
            if t is not None and t.is_alive():
                t.join(timeout)

    # ------------------------------------------------------------ server
    def _ensure_server(self):
        """Rank 0 co-hosts the server thread (reference: separate
        server binaries scheduled by the tracker; one co-hosted server
        is the degenerate single-server topology)."""
        if self._rank != 0 or self._server is not None:
            return
        self._applied = {}  # (key, rank) -> last applied seq

        def serve():
            while not self._stop.is_set():
                # ONE dir scan per cycle picks up every pending push;
                # per-(key, rank) seq ordering is enforced locally so a
                # worker's updates apply in the order it issued them
                # (async across workers, FIFO within one)
                arrived = {}
                for full_key, blob in _dir_get_bytes("ps/g/"):
                    tail = full_key.split("ps/g/", 1)[-1]
                    arrived[tail] = (full_key, blob)
                progressed = False
                for k in list(self._store):
                    for r in range(self._nproc):
                        while True:
                            s = self._applied.get((k, r), 0)
                            hit = arrived.get(f"{k}/{r}/{s}")
                            if hit is None:
                                break
                            full_key, blob = hit
                            grad = nd_array(_loads(blob))
                            if self._updater is not None:
                                self._updater(
                                    _str_key(k), grad, self._store[k])
                            else:
                                grad.copyto(self._store[k])
                            self._publish(k)
                            _delete(full_key)
                            self._applied[(k, r)] = s + 1
                            progressed = True
                if not progressed:
                    time.sleep(_POLL)

        self._server = threading.Thread(
            target=serve, name="kv_async_server", daemon=True)
        self._server.start()

    def _publish(self, k):
        _set_bytes(f"ps/w/{k}", _dumps(self._store[k].asnumpy()))

    # ---------------------------------------------------------- data ops
    def init(self, key, value):
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            self._store[k] = vlist[0].copy()
        if self._nproc == 1:
            return
        self._align_processes(f"async_init_{len(self._store)}")
        if self._rank == 0:
            self._ensure_server()
            for k in keys:
                self._publish(k)
        else:
            # adopt the server's initial weights (one lineage)
            for k in keys:
                blob = _client().blocking_key_value_get_bytes(
                    f"ps/w/{k}", 600_000)
                self._store[k] = nd_array(_loads(blob))

    def push(self, key, value, priority=0):
        """Send the locally-merged gradient; NO barrier, NO cross-worker
        merge — the server applies each worker's gradient on arrival
        (reference async DataHandle, kvstore_dist_server.h:136-160)."""
        if self._nproc == 1:
            return super().push(key, value, priority)
        keys, vals = _ctype_key_value(key, value)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized")
            merged = vlist[0]
            if len(vlist) > 1:
                import jax

                dev = vlist[0].context.jax_device()
                acc = vlist[0]._data
                for v in vlist[1:]:
                    acc = acc + jax.device_put(v._data, dev)
                merged = NDArray(acc, ctx=vlist[0].context)
            s = self._seq.get(k, 0)
            _set_bytes(f"ps/g/{k}/{self._rank}/{s}",
                       _dumps(merged.asnumpy()))
            self._seq[k] = s + 1

    def pull(self, key, out=None, priority=0):
        if self._nproc == 1:
            return super().pull(key, out=out, priority=priority)
        keys, outs = _ctype_key_value(key, out)
        for k, olist in zip(keys, outs):
            if self._rank == 0:
                # the co-hosted server's store IS the authoritative
                # weight; reading the published snapshot here could
                # revert updates the server thread applied since the
                # last publish
                host = self._store[k].asnumpy()
            else:
                blob = _try_get_bytes(f"ps/w/{k}")
                if blob is None:
                    blob = _client().blocking_key_value_get_bytes(
                        f"ps/w/{k}", 600_000)
                host = _loads(blob)
                self._store[k] = nd_array(host)
            for o in olist:
                o[:] = host

    def set_optimizer(self, optimizer):
        """Only the server (rank 0) runs the optimizer — true reference
        async topology, unlike the sync path's run-everywhere."""
        self._set_updater(opt.get_updater(optimizer))

    # ---------------------------------------------------------- liveness
    def get_num_dead_node(self, node_id=0, timeout=60):
        """Stale-heartbeat count (reference kvstore.h:242 ps-lite
        heartbeat surface). A rank is dead when its ps/hb/<rank> entry
        is older than `timeout` seconds (or missing after startup)."""
        if self._nproc == 1:
            return 0
        beats = {}
        for full_key, ts in _dir_get("ps/hb/"):
            try:
                beats[int(full_key.rsplit("/", 1)[-1])] = float(ts)
            except ValueError:
                pass
        now = time.time()
        dead = 0
        for r in range(self._nproc):
            ts = beats.get(r)
            if ts is None or now - ts > timeout:
                dead += 1
        return dead

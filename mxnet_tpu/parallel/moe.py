"""Mixture-of-Experts layer with expert parallelism.

New capability vs the reference (SURVEY.md §2.5: "no EP, no MoE" — the
rebuild must provide the modern equivalent). Design: top-k token
routing with capacity-bounded dense dispatch — everything is static
shapes and batched matmuls so XLA can tile the expert FFNs onto the
MXU; expert parallelism shards the expert dimension over a mesh axis,
with the dispatch/combine einsums lowering to `all_to_all`-equivalent
collectives under GSPMD sharding (no dynamic scatter, no host loops).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def top1_gating(logits, num_experts, capacity):
    """Switch-style top-1 router. logits: (T, E). Returns
    (dispatch (T, E, C) one-hot, combine (T, E, C) weights, aux_loss).
    """
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                  # (T,)
    gate = jnp.take_along_axis(
        probs, expert[:, None], axis=-1
    )[:, 0]                                               # (T,)
    onehot = jax.nn.one_hot(expert, num_experts)          # (T, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0       # (T, E)
    keep = (pos < capacity) & (onehot > 0)
    pos = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos, capacity) * keep[..., None]
    dispatch = pos_onehot                                  # (T, E, C)
    combine = dispatch * gate[:, None, None]
    # load-balancing auxiliary loss (Switch Transformer style)
    density = onehot.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux = jnp.sum(density * density_proxy) * num_experts
    return dispatch, combine, aux


def moe_ffn(x, router_w, w1, w2, capacity_factor=1.25,
            mesh=None, axis_name="expert"):
    """MoE feed-forward. x: (T, D) tokens; router_w: (D, E);
    w1: (E, D, F); w2: (E, F, D). Returns (out (T, D), aux_loss).

    With `mesh` given, expert-major weights and the dispatched token
    blocks are sharded over `axis_name` (expert parallelism): the
    dispatch einsum becomes the all-to-all that routes tokens to the
    chips owning their experts.
    """
    t, d = x.shape
    e = w1.shape[0]
    capacity = max(1, int(capacity_factor * t / e))
    logits = x @ router_w                                  # (T, E)
    dispatch, combine, aux = top1_gating(logits, e, capacity)
    # route: (T, E, C) x (T, D) -> (E, C, D)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)
    if mesh is not None:
        expert_in = jax.lax.with_sharding_constraint(
            expert_in, NamedSharding(mesh, P(axis_name, None, None))
        )
    h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", expert_in, w1))
    expert_out = jnp.einsum("ecf,efd->ecd", h, w2)
    if mesh is not None:
        expert_out = jax.lax.with_sharding_constraint(
            expert_out, NamedSharding(mesh, P(axis_name, None, None))
        )
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out, aux


def init_moe_params(rng, d_model, d_ff, num_experts, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(rng, 3)
    scale = 1.0 / jnp.sqrt(d_model)
    return {
        "router_w": jax.random.normal(
            k1, (d_model, num_experts), dtype) * scale,
        "w1": jax.random.normal(
            k2, (num_experts, d_model, d_ff), dtype) * scale,
        "w2": jax.random.normal(
            k3, (num_experts, d_ff, d_model), dtype
        ) * (1.0 / jnp.sqrt(d_ff)),
    }

"""KVStore('tpu'): the distributed KVStore facade over mesh collectives.

Replaces the reference's entire ps-lite stack (SURVEY.md §2.5;
src/kvstore/kvstore_dist.h, kvstore_dist_server.h). The mapping:

  reference                          tpu-native
  ---------                          ----------
  ZPush(grad) to key-sharded servers sum gradients into the store; on a
                                     multi-device mesh the values are
                                     NamedSharding'd jax Arrays, so the
                                     add lowers to an XLA all-reduce over
                                     ICI when copies live on different
                                     chips (no server hop, no host round
                                     trip)
  server MergeBuf + updater          updater applied once on the merged
                                     value (same semantics as sync-mode
                                     DataHandle, kvstore_dist_server.h:183)
  ZPull                              broadcast of the stored value, a
                                     device-to-device copy XLA schedules
                                     over ICI
  rank/num_workers (Postoffice)      jax.process_index()/process_count()
  Barrier                            blocking collective over an all-ones
                                     psum (multi-host); no-op single host
  get_num_dead_node / is_recovery    jax.distributed liveness — surfaced
                                     as stubs returning healthy until a
                                     coordination service is attached

Single-process it behaves exactly like 'device' (in-process reduce), so
`--kv-store tpu` runs everywhere; under `jax.distributed` each process
pushes its local slice and XLA's collectives do the cross-host sum —
the fully-fused path (gradient psum *inside* the train step) is what
Module uses when given a sharded executor (parallel/dp_step.py).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from .. import optimizer as opt
from ..base import MXNetError
from ..kvstore import KVStore, _ctype_key_value, _str_key
from ..ndarray import NDArray

# The actual init lives in _dist_bootstrap (it must run at package
# import, before the jax backend exists — on CPU the gloo collectives
# attach at client construction). Kept as a re-export for callers.
from .._dist_bootstrap import maybe_init_distributed  # noqa: F401

_BARRIER_PSUM = None
_BARRIER_MESH = None  # (mesh, jitted sum) — the pmap-free barrier


def _barrier_psum():
    """The barrier's pmapped psum, bound once: re-wrapping a fresh
    lambda in jax.pmap on every `_barrier()` call would retrace each
    time (mxlint MX002). FALLBACK path — the default barrier is the
    mesh jit below (MXNET_SHARD_KV_MESH)."""
    global _BARRIER_PSUM
    if _BARRIER_PSUM is None:
        _BARRIER_PSUM = jax.pmap(
            lambda v: jax.lax.psum(v, "i"), axis_name="i")
    return _BARRIER_PSUM


def _barrier_mesh():
    """Mesh-jit barrier program, bound once: a 1-D mesh over ALL
    devices and a jitted sum whose input shards over it and whose
    output replicates — the same forced rendezvous as the pmap psum,
    lowered through the one jit chokepoint (sharding.lower) instead of
    pmap. Returns (mesh, input NamedSharding, fn)."""
    global _BARRIER_MESH
    if _BARRIER_MESH is None:
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ..sharding.lower import jit_sharded

        mesh = Mesh(np.asarray(jax.devices()), ("dev",))
        in_sh = NamedSharding(mesh, P("dev"))
        fn = jit_sharded(jnp.sum, in_shardings=in_sh,
                         out_shardings=NamedSharding(mesh, P()))
        _BARRIER_MESH = (mesh, in_sh, fn)
    return _BARRIER_MESH


class KVStoreTPU(KVStore):
    def __init__(self, kv_type="tpu"):
        super().__init__(kv_type)
        maybe_init_distributed()
        self._barrier_count = 0
        self._plan = None  # ShardingPlan, via attach_plan

    def attach_plan(self, plan):
        """Bind a sharding.ShardingPlan: pushed/pulled values are then
        pinned to the plan's mesh (replicated) — semantically the
        identity, but it keeps kvstore traffic on the mesh data plane
        (an async reshard instead of a host hop) when the training step
        itself is mesh-jitted. Module.init_optimizer calls this when a
        plan is bound."""
        self._plan = plan

    def _pin_replicated(self, nd):
        """merged/stored value -> same value pinned replicated on the
        plan's mesh (no-op data-wise; async dispatch, no host sync)."""
        if self._plan is None or jax.process_count() > 1:
            return nd
        from ..sharding.lower import constrain

        return NDArray(constrain(nd._data, self._plan.mesh),
                       ctx=nd.context)

    # --------------------------------------------------- dist push/pull
    _first_collective_done = False

    @staticmethod
    def _align_processes(tag):
        """Coordination-service barrier (no data-plane collectives):
        lines processes up before the first gloo/ICI collective so
        per-process jit-compile skew can't exceed the collective
        context-init deadline. The analog of ps::Postoffice::Barrier
        at startup (kvstore_dist.h:41)."""
        try:
            from jax._src import distributed

            client = distributed.global_state.client
            if client is not None:
                client.wait_at_barrier(
                    f"mxnet_tpu_kv_{tag}", timeout_in_ms=600_000
                )
        except Exception:
            pass

    # one mesh + ONE jitted reducer (jax.jit caches per input
    # shape/dtype internally), built lazily; device-path failure is
    # remembered so the hot push path warns once, not per key per step
    _proc_mesh = None
    _reduce_jit = None
    _device_sum_broken = False

    @classmethod
    def _process_mesh(cls):
        """1-D mesh with ONE device per process — the collective fabric
        for the cross-process sum (the ps-lite server ring's role)."""
        if cls._proc_mesh is None:
            import numpy as np
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            by_proc = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, d)
            cls._proc_mesh = Mesh(
                np.asarray([by_proc[p]
                            for p in sorted(by_proc)]), ("proc",))
            cls._reduce_jit = jax.jit(
                lambda x: jnp.sum(x, axis=0),
                out_shardings=NamedSharding(cls._proc_mesh, P()))
        return cls._proc_mesh

    @classmethod
    def _mark_device_sum_broken(cls, exc):
        import logging

        cls._device_sum_broken = True
        logging.getLogger(__name__).warning(
            "device-native cross-process sum unavailable (%s); "
            "using the host-staged path from now on", exc)

    def _cross_process_sum(self, merged):
        """Sum the locally-merged value across worker processes — the
        replacement for ZPush-to-servers + MergeBuf accumulation
        (kvstore_dist.h:216-230, kvstore_dist_server.h:183).

        DEVICE-NATIVE: each process's merged value becomes one shard of
        a (nproc, ...) global array and a jitted sum-over-shards runs as
        ONE XLA all-reduce over DCN/ICI — no host round-trip (VERDICT r3
        #3). Falls back to the host-staged all-gather if the device
        path is unavailable. The multi-key pipelined analog is push();
        this is the single-value entry point."""
        if jax.process_count() == 1:
            return merged
        if not KVStoreTPU._first_collective_done:
            self._align_processes("first_allgather")
            KVStoreTPU._first_collective_done = True
        if not KVStoreTPU._device_sum_broken:
            try:
                return self._device_sum(merged)
            except Exception as exc:  # pragma: no cover - env-specific
                self._mark_device_sum_broken(exc)
        return self._host_sum(merged)

    def _device_stage(self, merged):
        """Phase A of the device-native sum: put the locally-merged
        value on this process's mesh device and wrap it as one shard of
        the (nproc, ...) global array. Pure async dispatch — no
        collective runs yet, so a multi-key push can stage every key
        before any reduction is issued (the analog of the reference
        engine queueing all ZPush ops before the network drains them,
        kvstore_dist.h:216-230)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._process_mesh()
        nproc = jax.process_count()
        mine = mesh.devices.flat[jax.process_index()]
        local = jax.device_put(merged._data, mine)
        return jax.make_array_from_single_device_arrays(
            (nproc,) + local.shape,
            NamedSharding(mesh, P("proc")), [local[None]])

    def _device_reduce(self, garr, ctx):
        """Phase B: dispatch the jitted all-reduce on a staged global
        array; the result is read as the local replica (no host hop)."""
        out = KVStoreTPU._reduce_jit(garr)
        return NDArray(out.addressable_data(0), ctx=ctx)

    def _device_sum(self, merged):
        return self._device_reduce(
            self._device_stage(merged), merged.context)

    def _host_sum(self, merged):
        from jax.experimental import multihost_utils

        host = merged.asnumpy()
        g = multihost_utils.process_allgather(host)
        return NDArray(
            jnp.asarray(jnp.sum(jnp.asarray(g), axis=0)),
            ctx=merged.context,
        )

    def init(self, key, value):
        """Store the value, broadcasting rank-0's copy to all worker
        processes first. The reference pushes init to the server so all
        workers start from one weight (kvstore_dist.h Push with init;
        ADVICE r1: without this, rank-dependent seeding — a common user
        pattern — silently diverges replicas forever)."""
        super().init(key, value)
        if jax.process_count() == 1:
            return
        from jax.experimental import multihost_utils

        if not KVStoreTPU._first_collective_done:
            self._align_processes("first_broadcast")
            KVStoreTPU._first_collective_done = True
        keys, _ = _ctype_key_value(key, value)
        for k in keys:
            stored = self._store[k]
            host = multihost_utils.broadcast_one_to_all(stored.asnumpy())
            self._store[k] = NDArray(
                jnp.asarray(host), ctx=stored.context
            )

    def push(self, key, value, priority=0):
        """Local device reduce, then cross-process all-reduce, then the
        updater once on the merged value (sync-mode semantics: every
        worker sees the identical merged gradient, so running the
        updater everywhere equals the reference's run-once-on-server,
        kvstore_dist_server.h:136-229).

        A multi-key push is PIPELINED in two phases (VERDICT r4 #3):
        every key's local merge + device staging is issued first (all
        async), then the cross-process reductions are dispatched in
        priority order — highest `priority` first, ties in issue order.
        With the reference convention priority=-key_index
        (model.py:95-97) this reduces early layers first, and because
        every dispatch is non-blocking the reductions overlap both each
        other and any concurrently-dispatched compute (the jax analog
        of the reference's engine-integrated ZPush overlap,
        kvstore_dist.h:111-123). `priority` may be a scalar or one int
        per key."""
        keys, vals = _ctype_key_value(key, value)
        prios = (list(priority) if isinstance(priority, (list, tuple))
                 else [priority] * len(keys))
        if len(prios) != len(keys):
            raise MXNetError("priority list must match key count")
        nproc = jax.process_count()
        if nproc > 1 and not KVStoreTPU._first_collective_done:
            self._align_processes("first_allgather")
            KVStoreTPU._first_collective_done = True
        # phase A: local merges + device staging for EVERY key
        staged = []  # (key, merged NDArray, garr or None)
        for k, vlist in zip(keys, vals):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized")
            merged = vlist[0]
            if len(vlist) > 1:
                dev = vlist[0].context.jax_device()
                acc = vlist[0]._data
                for v in vlist[1:]:
                    acc = acc + jax.device_put(v._data, dev)
                merged = NDArray(acc, ctx=vlist[0].context)
            garr = None
            if nproc > 1 and not KVStoreTPU._device_sum_broken:
                try:
                    garr = self._device_stage(merged)
                except Exception as exc:  # pragma: no cover
                    self._mark_device_sum_broken(exc)
            staged.append((k, merged, garr))
        # phase B: dispatch reductions + updaters, priority order
        order = sorted(range(len(staged)),
                       key=lambda i: (-prios[i], i))
        for i in order:
            k, merged, garr = staged[i]
            if nproc > 1:
                if garr is not None:
                    try:
                        merged = self._device_reduce(
                            garr, merged.context)
                    except Exception as exc:  # pragma: no cover
                        self._mark_device_sum_broken(exc)
                        merged = self._host_sum(merged)
                else:
                    merged = self._host_sum(merged)
            merged = self._pin_replicated(merged)
            if self._updater is not None:
                self._updater(_str_key(k), merged, self._store[k])
            else:
                merged.copyto(self._store[k])

    def pull(self, key, out=None, priority=0):
        """Broadcast stored values into the out arrays; with a plan
        attached the stored value is first pinned replicated on the
        plan's mesh (the mesh-path no-op — the copy then never leaves
        the mesh data plane)."""
        if self._plan is not None and jax.process_count() == 1:
            keys, _ = _ctype_key_value(key, out)
            for k in keys:
                if k in self._store:
                    self._store[k] = self._pin_replicated(
                        self._store[k])
        return super().pull(key, out=out, priority=priority)

    @property
    def rank(self):
        """(reference kvstore_dist.h:155 ps::MyRank)"""
        return jax.process_index()

    @property
    def num_workers(self):
        """(reference kvstore_dist.h:157 ps::NumWorkers)"""
        return jax.process_count()

    def _barrier(self, force=False):
        """(reference kvstore_dist.h:144 Postoffice::Barrier).

        A tiny all-device reduction forces every process to reach this
        point before any proceeds. Default implementation is the
        mesh jit (`_barrier_mesh`) — in/out_shardings over a 1-D
        all-device mesh, no pmap; MXNET_SHARD_KV_MESH=0 restores the
        legacy pmapped psum. `force=True` runs the collective even
        single-process (the mesh path is then exercisable in tests
        without jax.distributed)."""
        if jax.process_count() == 1 and not force:
            return
        if os.environ.get("MXNET_SHARD_KV_MESH", "1") not in (
                "0", "false", "off"):
            try:
                import numpy as np

                _mesh, in_sh, fn = _barrier_mesh()
                ones = np.ones((jax.local_device_count(),), np.float32)
                if jax.process_count() > 1:
                    x = jax.make_array_from_process_local_data(
                        in_sh, ones)
                else:
                    x = jax.device_put(ones, in_sh)
                jax.block_until_ready(fn(x))
                return
            except Exception:  # pragma: no cover - env-specific
                pass  # legacy pmap barrier below
        x = jnp.ones((jax.local_device_count(),))
        jax.block_until_ready(_barrier_psum()(x))

    def set_optimizer(self, optimizer):
        """All workers run the same updater on the merged gradient —
        equivalent to the reference's server-side optimizer because the
        merged gradient is identical on every worker after the
        all-reduce (kvstore_dist_server.h:183-201)."""
        self._set_updater(opt.get_updater(optimizer))

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Liveness via the coordination service (reference ps-lite
        heartbeat surface, include/mxnet/kvstore.h:242). Counts worker
        processes the coordinator no longer sees as live; single
        process (or no coordinator) reports all healthy."""
        if jax.process_count() == 1:
            return 0
        try:
            from jax._src import distributed

            client = distributed.global_state.client
            if client is None:
                return 0
            live = client.get_live_nodes(
                list(range(jax.process_count())))
            return jax.process_count() - len(live)
        except Exception:
            return 0

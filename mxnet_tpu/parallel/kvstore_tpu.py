"""KVStore('tpu'): the distributed KVStore facade over mesh collectives.

Replaces the reference's entire ps-lite stack (SURVEY.md §2.5;
src/kvstore/kvstore_dist.h, kvstore_dist_server.h). The mapping:

  reference                          tpu-native
  ---------                          ----------
  ZPush(grad) to key-sharded servers sum gradients into the store; on a
                                     multi-device mesh the values are
                                     NamedSharding'd jax Arrays, so the
                                     add lowers to an XLA all-reduce over
                                     ICI when copies live on different
                                     chips (no server hop, no host round
                                     trip)
  server MergeBuf + updater          updater applied once on the merged
                                     value (same semantics as sync-mode
                                     DataHandle, kvstore_dist_server.h:183)
  ZPull                              broadcast of the stored value, a
                                     device-to-device copy XLA schedules
                                     over ICI
  rank/num_workers (Postoffice)      jax.process_index()/process_count()
  Barrier                            blocking collective over an all-ones
                                     psum (multi-host); no-op single host
  get_num_dead_node / is_recovery    jax.distributed liveness — surfaced
                                     as stubs returning healthy until a
                                     coordination service is attached

Single-process it behaves exactly like 'device' (in-process reduce), so
`--kv-store tpu` runs everywhere; under `jax.distributed` each process
pushes its local slice and XLA's collectives do the cross-host sum —
the fully-fused path (gradient psum *inside* the train step) is what
Module uses when given a sharded executor (parallel/dp_step.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .. import optimizer as opt
from ..base import MXNetError
from ..kvstore import KVStore


class KVStoreTPU(KVStore):
    def __init__(self, kv_type="tpu"):
        super().__init__(kv_type)
        self._barrier_count = 0

    @property
    def rank(self):
        """(reference kvstore_dist.h:155 ps::MyRank)"""
        return jax.process_index()

    @property
    def num_workers(self):
        """(reference kvstore_dist.h:157 ps::NumWorkers)"""
        return jax.process_count()

    def _barrier(self):
        """(reference kvstore_dist.h:144 Postoffice::Barrier).

        A tiny psum across all devices forces every process to reach this
        point before any proceeds."""
        if jax.process_count() == 1:
            return
        x = jnp.ones((jax.local_device_count(),))
        jax.block_until_ready(
            jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x)
        )

    def set_optimizer(self, optimizer):
        """All workers run the same updater on the merged gradient —
        equivalent to the reference's server-side optimizer because the
        merged gradient is identical on every worker after the
        all-reduce (kvstore_dist_server.h:183-201)."""
        self._set_updater(opt.get_updater(optimizer))

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Reference surfaces ps-lite heartbeat info
        (kvstore_dist.h:159-167). jax.distributed has no queryable
        liveness yet; report all healthy."""
        return 0

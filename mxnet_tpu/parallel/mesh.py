"""Device-mesh management.

TPU-native replacement for the reference's device bookkeeping (context
lists in Module + kvstore device comm). A global mesh is the ambient
fabric: axes named 'data', 'model', 'seq', 'pipe', 'expert' cover
DP/TP/SP/PP/EP. Multi-host: jax.distributed supplies the full device
set; processes see the same global mesh (analog of ps-lite's node
roster, kvstore_dist.h:35-51, without the server tier).
"""
from __future__ import annotations

import functools

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_current_mesh = None

# Canonical axis names, in nesting order.
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"
# GSPMD rule-layer axes (mxnet_tpu.sharding): ZeRO-style parameter
# sharding and tensor parallelism over ONE mesh with 'data'.
FSDP_AXIS = "fsdp"
TP_AXIS = "tp"


def data_parallel_mesh(n_devices=None):
    """1-D mesh over all (or first n) devices with a 'data' axis — the
    analog of the reference's default multi-device data parallelism
    (DataParallelExecutorGroup over a context list)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (DATA_AXIS,))


def make_mesh(axis_sizes: dict):
    """Build a mesh from {axis_name: size}; sizes must multiply to a
    divisor of the device count. E.g. {'data': 2, 'model': 4}.

    Multi-process, the 'data' axis is laid out process-major regardless
    of its position in `axis_sizes`: jax.devices() orders devices by
    process, so making 'data' the slowest-varying axis aligns process
    boundaries with batch shards — each process feeds a contiguous
    global-batch slice (make_array_from_process_local_data's contract)
    while the model/seq/pipe axes stay intra-process, riding ICI rather
    than DCN (the scaling-book mesh-major recipe)."""
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes))
    devs = jax.devices()[:n]
    if jax.process_count() > 1 and DATA_AXIS in names and len(names) > 1:
        di = names.index(DATA_AXIS)
        order = (di,) + tuple(
            i for i in range(len(names)) if i != di)
        arr = np.asarray(devs).reshape(
            tuple(sizes[i] for i in order))
        arr = np.transpose(arr, np.argsort(order))
    else:
        arr = np.asarray(devs).reshape(sizes)
    return Mesh(arr, names)


def global_put(value, sharding):
    """Place a host value (identical on every process) under `sharding`,
    including shardings that span processes — the multi-host analog of
    jax.device_put (which requires addressable devices). Each process
    supplies only its addressable shards, cut from the full host value."""
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    host = np.asarray(value)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx])


@functools.lru_cache(maxsize=None)
def _replicator(mesh):
    """One cached jitted identity per mesh: reshard-to-replicated (an
    XLA all-gather). A fresh jit per call would recompile every time."""
    repl = NamedSharding(mesh, PartitionSpec())
    return jax.jit(lambda x: x, out_shardings=repl)


def full_host(arr):
    """The FULL global value of a jax Array as np.ndarray, on every
    process. Process-spanning sharded arrays are resharded to replicated
    first (ONE compiled all-gather over ICI/DCN — no per-shard host
    hops), then read from the local copy.

    COLLECTIVE for process-spanning sharded arrays: every process must
    call it (rank-guarded calls deadlock), same contract as any jax
    multihost computation."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    sh = arr.sharding
    if not getattr(sh, "is_fully_replicated", False):
        arr = _replicator(sh.mesh)(arr)
    return np.asarray(arr.addressable_data(0))


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh


def current_mesh():
    return _current_mesh


class use_mesh:
    """Context manager installing `mesh` as the ambient mesh (read by
    mesh-aware ops like RingAttention/MoEFFN at trace time)."""

    def __init__(self, mesh):
        self._mesh = mesh
        self._prev = None

    def __enter__(self):
        global _current_mesh
        self._prev = _current_mesh
        _current_mesh = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        global _current_mesh
        _current_mesh = self._prev
        return False


def parse_partition_spec(spec):
    """Parse a sharding annotation into a PartitionSpec.

    Accepts a PartitionSpec/tuple directly, or the string syntax used in
    Symbol `__sharding__` attrs: comma-separated per-dim entries, each
    an axis name, 'None'/'*' (unsharded), or 'a+b' (multi-axis). E.g.
    "None,model" = column-parallel 2-D weight; "data+seq" = dim 0
    sharded over both axes.
    """
    if spec is None:
        return PartitionSpec()
    if isinstance(spec, PartitionSpec):
        return spec
    if isinstance(spec, (tuple, list)):
        return PartitionSpec(*spec)
    s = str(spec).strip()
    if not s or s == "None":
        return PartitionSpec()
    dims = []
    for part in s.split(","):
        part = part.strip()
        if part in ("None", "", "*"):
            dims.append(None)
        elif "+" in part:
            dims.append(tuple(p.strip() for p in part.split("+")))
        else:
            dims.append(part)
    return PartitionSpec(*dims)


def default_mesh():
    """Current mesh, or a fresh data-parallel mesh over all devices."""
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = data_parallel_mesh()
    return _current_mesh


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh, axis=0, mesh_axis=DATA_AXIS):
    spec = [None] * (axis + 1)
    spec[axis] = mesh_axis
    return NamedSharding(mesh, PartitionSpec(*spec))

"""Device-mesh management.

TPU-native replacement for the reference's device bookkeeping (context
lists in Module + kvstore device comm). A global mesh is the ambient
fabric: axes named 'data', 'model', 'seq', 'pipe', 'expert' cover
DP/TP/SP/PP/EP. Multi-host: jax.distributed supplies the full device
set; processes see the same global mesh (analog of ps-lite's node
roster, kvstore_dist.h:35-51, without the server tier).
"""
from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_current_mesh = None

# Canonical axis names, in nesting order.
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def data_parallel_mesh(n_devices=None):
    """1-D mesh over all (or first n) devices with a 'data' axis — the
    analog of the reference's default multi-device data parallelism
    (DataParallelExecutorGroup over a context list)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (DATA_AXIS,))


def make_mesh(axis_sizes: dict):
    """Build a mesh from {axis_name: size}; sizes must multiply to a
    divisor of the device count. E.g. {'data': 2, 'model': 4}."""
    names = tuple(axis_sizes.keys())
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes))
    devs = np.asarray(jax.devices()[:n]).reshape(sizes)
    return Mesh(devs, names)


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh


def current_mesh():
    return _current_mesh


class use_mesh:
    """Context manager installing `mesh` as the ambient mesh (read by
    mesh-aware ops like RingAttention/MoEFFN at trace time)."""

    def __init__(self, mesh):
        self._mesh = mesh
        self._prev = None

    def __enter__(self):
        global _current_mesh
        self._prev = _current_mesh
        _current_mesh = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        global _current_mesh
        _current_mesh = self._prev
        return False


def parse_partition_spec(spec):
    """Parse a sharding annotation into a PartitionSpec.

    Accepts a PartitionSpec/tuple directly, or the string syntax used in
    Symbol `__sharding__` attrs: comma-separated per-dim entries, each
    an axis name, 'None'/'*' (unsharded), or 'a+b' (multi-axis). E.g.
    "None,model" = column-parallel 2-D weight; "data+seq" = dim 0
    sharded over both axes.
    """
    if spec is None:
        return PartitionSpec()
    if isinstance(spec, PartitionSpec):
        return spec
    if isinstance(spec, (tuple, list)):
        return PartitionSpec(*spec)
    s = str(spec).strip()
    if not s or s == "None":
        return PartitionSpec()
    dims = []
    for part in s.split(","):
        part = part.strip()
        if part in ("None", "", "*"):
            dims.append(None)
        elif "+" in part:
            dims.append(tuple(p.strip() for p in part.split("+")))
        else:
            dims.append(part)
    return PartitionSpec(*dims)


def default_mesh():
    """Current mesh, or a fresh data-parallel mesh over all devices."""
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = data_parallel_mesh()
    return _current_mesh


def replicated(mesh):
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh, axis=0, mesh_axis=DATA_AXIS):
    spec = [None] * (axis + 1)
    spec[axis] = mesh_axis
    return NamedSharding(mesh, PartitionSpec(*spec))

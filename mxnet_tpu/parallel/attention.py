"""Attention kernels: XLA reference + Pallas flash-attention.

New capability vs the 2017 reference (SURVEY.md §5: no attention ops
exist there — its long-sequence answer was bucketing + truncated
unrolling); this is the modern TPU-native replacement the rebuild is
required to provide. The blockwise online-softmax structure follows the
public flash-attention recipe (PAPERS.md); the Pallas kernel keeps a
(block_q, head_dim) accumulator + running max/sum in VMEM and streams
K/V blocks from HBM, so attention memory is O(T·d) instead of O(T²).

Two implementations behind one entry point `attention(...)`:
- impl='xla': plain einsum+softmax, fully fused by XLA. Baseline and
  gradient path.
- impl='flash': Pallas kernel forward (MXU matmuls per block), with a
  custom_vjp whose backward recomputes via the XLA path (forward-memory
  win now; dedicated backward kernel is future work).
Runs in interpret mode on CPU so tests exercise the same kernel code.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def attention_reference(q, k, v, causal=False, scale=None):
    """(B, T, H, D) attention via XLA ops."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


# ------------------------------------------------------------ pallas flash


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal,
                      scale, seq_k, q_block_idx):
    """One (batch*head, q_block) program: stream K/V blocks, online
    softmax."""
    q = q_ref[...]  # (block_q, d)
    block_q, d = q.shape
    num_kb = seq_k // block_k

    def body(kb, carry):
        o, m, l = carry
        k_blk = k_ref[pl.dslice(kb * block_k, block_k), :]
        v_blk = v_ref[pl.dslice(kb * block_k, block_k), :]
        s = jnp.dot(
            q, k_blk.T, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            q_pos = (
                q_block_idx * block_q
                + jax.lax.broadcasted_iota(jnp.int32,
                                           (block_q, block_k), 0)
            )
            k_pos = (
                kb * block_k
                + jax.lax.broadcasted_iota(jnp.int32,
                                           (block_q, block_k), 1)
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[:, None] + jnp.dot(
            p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        return o_new, m_new, l_new

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o, m, l = jax.lax.fori_loop(0, num_kb, body, (o0, m0, l0))
    o_ref[...] = (o / l[:, None]).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k, interpret):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    assert tq % block_q == 0 and tk % block_k == 0, (
        "flash attention: sequence lengths must divide block sizes"
    )
    # layout: fold (batch, head) into the grid's first axis
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, tq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, tk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, tk, d)

    grid = (b * h, tq // block_q)

    def kernel(q_ref, k_ref, v_ref, o_ref):
        _flash_fwd_kernel(
            q_ref, k_ref, v_ref, o_ref, block_k=block_k,
            causal=causal, scale=scale, seq_k=tk,
            q_block_idx=pl.program_id(1),
        )

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qb: (bh, qb, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qb: (bh, 0, 0)),
            pl.BlockSpec((None, tk, d), lambda bh, qb: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (None, block_q, d), lambda bh, qb: (bh, qb, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, tq, d).transpose(0, 2, 1, 3)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_attention(q, k, v, causal, scale, block_q, block_k,
                     interpret):
    return _flash_forward(
        q, k, v, causal, scale, block_q, block_k, interpret
    )


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash_forward(
        q, k, v, causal, scale, block_q, block_k, interpret
    )
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(
            q_, k_, v_, causal=causal, scale=scale
        ),
        q, k, v,
    )
    return vjp(g)


_flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def attention(q, k, v, causal=False, scale=None, impl="xla",
              block_q=128, block_k=128, interpret=None):
    """Multi-head attention on (B, T, H, D) tensors."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if impl == "xla":
        return attention_reference(q, k, v, causal=causal, scale=scale)
    if impl == "flash":
        if interpret is None:
            interpret = jax.default_backend() == "cpu"
        return _flash_attention(
            q, k, v, causal, scale, block_q, block_k, interpret
        )
    raise ValueError(f"unknown attention impl {impl!r}")

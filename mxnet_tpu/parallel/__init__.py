"""Distributed / multi-chip machinery.

The reference's distribution stack (KVStore over ps-lite, §2.5 of
SURVEY.md) is replaced by mesh-sharded computation: a
`jax.sharding.Mesh` over the TPU slice, `NamedSharding` layouts on
parameters/batches, and XLA collectives over ICI/DCN inserted by the
compiler. This package holds the mesh helpers, the KVStore('tpu')
facade, and the data-parallel fused train step.
"""
from .mesh import (
    current_mesh,
    default_mesh,
    set_mesh,
    make_mesh,
    data_parallel_mesh,
)
from .kvstore_tpu import KVStoreTPU
from .attention import attention, attention_reference
from .ring_attention import ring_attention, ulysses_attention
from .pipeline import pipeline_apply
from .moe import moe_ffn, top1_gating, init_moe_params

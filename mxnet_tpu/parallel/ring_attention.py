"""Sequence/context parallelism: ring attention and Ulysses layouts.

New capability vs the 2017 reference (SURVEY.md §5 mandates modern
equivalents of its bucketing/model-parallel-LSTM long-sequence story):
shard the sequence axis over a mesh 'seq' axis and either

- **ring attention**: K/V shards rotate around the ring via
  `lax.ppermute` (XLA lowers to ICI neighbor exchange) while each
  device's Q shard accumulates blockwise online-softmax partials — the
  per-step compute overlaps the next step's transfer, attention memory
  stays O(T_local), and total traffic is one full K/V rotation; or
- **Ulysses**: two `all_to_all`s re-layout (seq-sharded, all heads) ->
  (head-sharded, full seq), run dense local attention, and scatter
  back. Cheaper for many heads; needs heads % seq_devices == 0.

Both are pure-collective designs under `shard_map` — no parameter
server, no explicit send/recv (contrast: reference's ps-lite ZPush/ZPull
transport, src/kvstore/kvstore_dist.h).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map
except ImportError:  # older jax keeps shard_map under experimental
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _ring_attention_shard(q, k, v, *, axis_name, causal, scale):
    """Per-device body under shard_map. q/k/v: (B, T_local, H, D)."""
    p = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape

    qh = q.transpose(0, 2, 1, 3)  # (B, H, Tq, D)

    # pcast: mark the accumulators as device-varying along the ring axis
    # so the fori_loop carry types match the (varying) body outputs.
    # Older jax has no varying-axis tracking (every per-device value is
    # implicitly varying) — identity there.
    def _varying(x):
        if hasattr(jax.lax, "pcast"):
            return jax.lax.pcast(x, (axis_name,), to="varying")
        return x

    o0 = _varying(jnp.zeros((b, h, t_local, d), jnp.float32))
    m0 = _varying(jnp.full((b, h, t_local), NEG_INF, jnp.float32))
    l0 = _varying(jnp.zeros((b, h, t_local), jnp.float32))

    q_pos = my_idx * t_local + jnp.arange(t_local)

    def body(step, carry):
        o, m, l, k_cur, v_cur = carry
        src = (my_idx - step) % p  # which shard we currently hold
        kh = k_cur.transpose(0, 2, 1, 3)
        vh = v_cur.transpose(0, 2, 1, 3)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qh, kh,
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            k_pos = src * t_local + jnp.arange(t_local)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        pexp = jnp.exp(s - m_new[..., None])
        l_new = l * corr + pexp.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", pexp, vh,
            preferred_element_type=jnp.float32,
        )
        # rotate K/V around the ring (ICI neighbor exchange)
        perm = [(i, (i + 1) % p) for i in range(p)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return o_new, m_new, l_new, k_next, v_next

    o, m, l, _, _ = jax.lax.fori_loop(
        0, p, body, (o0, m0, l0, k, v)
    )
    out = (o / l[..., None]).astype(q.dtype)
    return out.transpose(0, 2, 1, 3)  # (B, T_local, H, D)


def ring_attention(q, k, v, mesh=None, axis_name="seq", causal=False,
                   scale=None):
    """Ring attention over sequence-sharded (B, T, H, D) arrays.

    q/k/v may be global arrays (they are sharded over `axis_name` on
    dim 1 by shard_map) or already-placed sharded arrays.
    """
    from . import mesh as _mesh_mod

    if mesh is None:
        mesh = _mesh_mod.default_mesh()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_shard, axis_name=axis_name,
            causal=causal, scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def seq_mesh_for(total_len, axis_name="seq", max_devices=None):
    """A 1-D 'seq' mesh sized for ring attention over `total_len`
    tokens: the largest device count that divides total_len (ring
    attention shards the sequence axis evenly). Degrades to a 1-device
    mesh — callers (e.g. the decode tier's long-prompt prefill,
    MXNET_DECODE_RING_PREFILL) can use it unconditionally."""
    import numpy as np

    devs = jax.devices()
    if max_devices is not None:
        devs = devs[:max_devices]
    n = len(devs)
    while n > 1 and total_len % n:
        n -= 1
    return Mesh(np.asarray(devs[:n]), (axis_name,))


def _ulysses_shard(q, k, v, *, axis_name, causal, scale):
    """Per-device body: all_to_all to head-sharded full-seq layout,
    dense local attention, all_to_all back. q: (B, T_local, H, D)."""
    from .attention import attention_reference

    # (B, T_local, H, D) -> (B, T_full, H_local, D): split heads (axis 2)
    # across the seq axis, gather sequence (axis 1).
    qg = jax.lax.all_to_all(
        q, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    kg = jax.lax.all_to_all(
        k, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    vg = jax.lax.all_to_all(
        v, axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    out = attention_reference(qg, kg, vg, causal=causal, scale=scale)
    # back to (B, T_local, H, D)
    return jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention(q, k, v, mesh=None, axis_name="seq", causal=False,
                      scale=None):
    """Ulysses (head-scatter / seq-gather) attention over
    sequence-sharded (B, T, H, D) arrays. Requires H % axis_size == 0."""
    from . import mesh as _mesh_mod

    if mesh is None:
        mesh = _mesh_mod.default_mesh()
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    axis_size = mesh.shape[axis_name]
    if q.shape[2] % axis_size != 0:
        raise ValueError(
            f"ulysses_attention: num heads {q.shape[2]} must be "
            f"divisible by the '{axis_name}' axis size {axis_size}"
        )
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(
            _ulysses_shard, axis_name=axis_name, causal=causal,
            scale=scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)

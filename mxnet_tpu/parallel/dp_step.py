"""FusedTrainStep: forward + backward + all-reduce + optimizer update in
ONE donated XLA computation.

This is the TPU-native replacement for the reference's training data
plane, where three separate mechanisms cooperate per step:

  - GraphExecutor::Forward/Backward pushes cached engine ops
    (src/executor/graph_executor.cc:780-832),
  - KVStore push/pull wraps ZPush/ZPull in engine async ops so comm
    overlaps compute (src/kvstore/kvstore_dist.h:111-123,
    python/mxnet/model.py:88-97 priority-ordered push/pull),
  - the optimizer runs per-parameter fused kernels
    (src/operator/optimizer_op-inl.h).

Here all three collapse into a single jit: the loss graph's vjp produces
gradients, GSPMD inserts the cross-device all-reduce when the batch is
sharded over a mesh axis (gradients of replicated parameters against a
sharded batch ARE the psum — no host hop, no parameter server), and the
optimizer's traced `apply_dense` updates weights and state in the same
computation. Buffers for parameters, optimizer state, and aux state are
donated, so the update is in-place at the XLA level — the analog of the
reference's PlanMemory/inplace-addto passes.

Mixed precision (the reference trains fp16 via cuDNN,
tests/python/train/test_dtype.py): `compute_dtype=bfloat16` keeps fp32
master weights and casts weights/activations to bf16 for the fwd/bwd
compute; gradient cotangents come back through the cast (fp32), and aux
(e.g. BatchNorm running stats) updates are cast back to their master
dtype. Labels are never cast (class indices above 256 are not bf16-
representable).
"""
from __future__ import annotations

import contextlib
import logging
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import profiler as _profiler
from ..base import MXNetError
from ..ndarray import NDArray


def _to_jnp_tree(tree):
    """Map NDArray leaves of a pytree (None / NDArray / tuple) to jnp."""
    if tree is None:
        return None
    if isinstance(tree, NDArray):
        return tree._data
    if isinstance(tree, (tuple, list)):
        return tuple(_to_jnp_tree(t) for t in tree)
    return jnp.asarray(tree)


class FusedTrainStep:
    """One donated jit over (params, opt_states, auxs).

    Owns the training state while active: parameters, optimizer state and
    aux arrays live as jax Arrays inside this object, and the Module
    flushes them back into executor NDArrays only when a non-fused code
    path (eval forward, get_params, checkpointing) needs them.
    """

    def __init__(self, executor, optimizer, param_names, label_names=(),
                 mesh=None, data_axis="data", compute_dtype=None,
                 param_specs=None, data_specs=None, batch_scale=None,
                 logger=logging, plan=None):
        self._ex = executor
        self._opt = optimizer
        self._logger = logger
        self._mesh = mesh
        self._data_axis = data_axis
        self._plan = plan
        self._param_specs = dict(param_specs or {})
        self._data_specs = dict(data_specs or {})
        self._compute_dtype = (
            jnp.dtype(compute_dtype) if compute_dtype is not None else None
        )

        arg_names = executor._arg_names
        pset = set(param_names)
        self._param_names = [n for n in arg_names if n in pset]
        self._trainable = [
            n for n in self._param_names
            if executor._grad_req.get(n, "null") != "null"
        ]
        self._data_names = [n for n in arg_names if n not in pset]
        self._label_names = set(label_names)
        self._aux_names = list(executor._aux_names)

        # Take over the training state from the executor — as COPIES:
        # step() donates these buffers to XLA, and donating an array the
        # executor/module still references would invalidate it under
        # the caller's feet.
        self.params = {
            n: jnp.copy(executor.arg_dict[n]._data)
            for n in self._param_names
        }
        self.auxs = {
            n: jnp.copy(executor.aux_dict[n]._data)
            for n in self._aux_names
        }
        self.states = {
            n: _to_jnp_tree(
                optimizer.create_state(i, executor.arg_dict[n])
            )
            for i, n in enumerate(self._trainable)
        }
        # MXNET_TPU_OPT_STATE_DTYPE=bfloat16 stores optimizer state
        # (momentum/moments) in bf16: halves the optimizer-update HBM
        # traffic — one of the r3 profile's residual costs — at a small
        # accumulation-precision cost. The update still computes in
        # f32 (bf16 state promotes inside apply_dense) and rounds back
        # on store (_build preserves state dtypes across steps so
        # donation stays type-stable).
        sdt = os.environ.get("MXNET_TPU_OPT_STATE_DTYPE")
        self._state_dtype = jnp.dtype(sdt) if sdt else None
        if self._state_dtype is not None:
            self.states = jax.tree_util.tree_map(
                lambda x: x.astype(self._state_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x,
                self.states)
        self._base_rng = executor._rng
        self._t = 0  # steps taken through this fused step
        self._nproc = jax.process_count()
        # how many per-process batches make one global batch: nproc when
        # the batch shards over a process-spanning data axis, 1 when the
        # mesh is pure model/seq/pipe (every process feeds the identical
        # full batch — standard SPMD replicated-input contract). The
        # Module passes the value from its _multiproc_mesh_plan so ONE
        # decision governs executor shapes, staging, and rescale_grad.
        if batch_scale is not None:
            self._batch_scale = int(batch_scale)
        else:
            self._batch_scale = (
                self._nproc if self._nproc > 1 and mesh is not None
                and data_axis in mesh.axis_names else 1)

        if self._nproc > 1:
            # every process must start from ONE weight lineage (the
            # reference pushes init through the servers for the same
            # reason, kvstore_dist.h Push-on-init); rank 0 wins. Host
            # hop happens once at construction, never per step.
            from jax.experimental import multihost_utils

            self.params = multihost_utils.broadcast_one_to_all(
                jax.tree_util.tree_map(np.asarray, self.params))
            self.auxs = multihost_utils.broadcast_one_to_all(
                jax.tree_util.tree_map(np.asarray, self.auxs))
            self.states = multihost_utils.broadcast_one_to_all(
                jax.tree_util.tree_map(np.asarray, self.states))

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._repl = NamedSharding(mesh, P())
            # default batch sharding: dim 0 over the data axis (absent
            # e.g. on a pure-TP mesh -> replicated batch)
            self._batch_sh = (
                NamedSharding(mesh, P(data_axis))
                if data_axis in mesh.axis_names else self._repl
            )
            self._param_sh = {
                n: NamedSharding(mesh, self._param_specs.get(n, P()))
                for n in self.params
            }
            self._data_sh = {
                n: (NamedSharding(mesh, self._data_specs[n])
                    if n in self._data_specs else None)
                for n in self._data_names
            }
            # fsdp gather-before-use: parameters whose COMPUTE layout
            # differs from storage (the plan's fsdp axis drops inside
            # the step) get pinned via with_sharding_constraint in fwd;
            # its vjp transpose IS the reduce-scatter after grad.
            from ..sharding.lower import gather_shardings

            self._gather_sh = gather_shardings(plan, self._param_specs)
            self.params = {
                n: self._put(v, self._param_sh[n])
                for n, v in self.params.items()
            }
            self.auxs = {
                n: self._put(v, self._repl)
                for n, v in self.auxs.items()
            }
            # optimizer state leaves shaped like the param shard with
            # it; anything else (scalar counters) replicates
            self.states = {
                n: self._place_state(self.states[n], n)
                for n in self.states
            }
        else:
            self._repl = None
            self._batch_sh = None
            self._param_sh = None
            self._data_sh = None
            self._gather_sh = {}

        self._multi_cache = {}     # (k, stacked) -> jitted k-step loop
        self._multi_compiled = {}  # (k, stacked) -> AOT executable
        # numerics sentinel (mxnet_tpu.numerics): when a SentinelSpec
        # is enabled, every step program additionally returns one stats
        # row; rows pile up here DEVICE-side until drain_sentinel()
        self._sentinel = None
        self._sentinel_pending = []   # [(rows (k, C) array, [(t, lr)])]
        self._sentinel_dropped = 0
        self._jitted = self._build()
        self._compiled = None  # AOT executable, built on first run

    def _put(self, value, sharding):
        """Place a host/device value under `sharding`. Multi-process:
        the mesh spans processes, so build the global jax.Array from the
        (identical-everywhere) host value instead of device_put."""
        from .mesh import global_put

        return global_put(value, sharding)

    def _state_sharding(self, state, name):
        """Sharding pytree for one param's optimizer state: leaves with
        the param's shape follow the param's sharding, others replicate."""
        pshape = self.params[name].shape
        psh = self._param_sh[name]
        return jax.tree_util.tree_map(
            lambda leaf: psh if getattr(leaf, "shape", None) == pshape
            else self._repl,
            state,
        )

    def _place_state(self, state, name):
        sh = self._state_sharding(state, name)
        return jax.tree_util.tree_map(self._put, state, sh)

    # ------------------------------------------------------------ build
    def _bucket_plan(self):
        """Static plan for the flat-bucket optimizer update
        (MXNET_TPU_OPT_BUCKET=1), or None when ineligible. Eligible
        when every trainable parameter shares one dtype, one state
        structure, one wd multiplier, and a replicated (or meshless)
        layout — concatenation then changes nothing about the
        elementwise update math."""
        if os.environ.get("MXNET_TPU_OPT_BUCKET", "0") != "1":
            return None
        tr = self._trainable
        if not tr:
            return None
        from jax.sharding import PartitionSpec as P

        if self._mesh is not None and any(
                self._param_specs.get(n, P()) != P() for n in tr):
            self._logger.info(
                "opt bucket disabled: sharded parameters present")
            return None
        dtypes = {self.params[n].dtype for n in tr}
        structs = {jax.tree_util.tree_structure(self.states[n])
                   for n in tr}
        if len(dtypes) > 1 or len(structs) > 1:
            self._logger.info(
                "opt bucket disabled: mixed dtype/state structure "
                "across parameters")
            return None
        segs, off = [], 0
        for n in tr:
            sz = int(np.prod(self.params[n].shape))
            segs.append((n, off, sz))
            off += sz
        return {"segs": segs}

    def _build(self):
        run = self._ex._run_graph
        opt = self._opt
        trainable = list(self._trainable)
        cdt = self._compute_dtype
        labels = self._label_names
        bucket = self._bucket_plan()
        self._bucket_active = bucket is not None
        gsh = self._gather_sh
        mesh = self._mesh
        sentinel = self._sentinel
        nan_inj = self._nan_inject_plan()

        def gather_c(tree):
            """Pin fsdp-stored params to their compute layout inside
            the trace (gather-before-use); the vjp transpose of this
            constraint is the reduce-scatter of the gradients."""
            if not gsh:
                return tree
            from ..sharding.lower import constrain

            return {
                k: (constrain(v, mesh, gsh[k]) if k in gsh else v)
                for k, v in tree.items()
            }

        def cast_c(x):
            """master -> compute dtype (params, auxs, float data).
            UNSIGNED integer data (uint8 raw-pixel batches from the
            iterator's dtype='uint8' path) promotes to the compute
            dtype here, ON DEVICE — the host->device transfer stays
            1/4 size and the cast fuses into the first consumer;
            signed ints (labels, indices) are never touched."""
            if jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(cdt) if cdt is not None else x
            if jnp.issubdtype(x.dtype, jnp.unsignedinteger):
                return x.astype(cdt if cdt is not None else jnp.float32)
            return x

        def step(params, states, auxs, data, lr, t):
            rng = jax.random.fold_in(self._base_rng, t)
            train_p = {k: params[k] for k in trainable}
            frozen_p = {
                k: v for k, v in params.items() if k not in train_p
            }
            data_c = {
                k: (v if k in labels else cast_c(v))
                for k, v in data.items()
            }
            auxs_c = {k: cast_c(v) for k, v in auxs.items()}
            frozen_c = gather_c({k: cast_c(v)
                                 for k, v in frozen_p.items()})

            def fwd(tp):
                tp_c = gather_c({k: cast_c(v) for k, v in tp.items()})
                return run(
                    {**frozen_c, **tp_c, **data_c}, auxs_c, rng, True
                )

            outs, vjp_fn, aux_upd = jax.vjp(fwd, train_p, has_aux=True)
            (grads,) = vjp_fn([jnp.ones_like(o) for o in outs])

            if nan_inj is not None:
                # fault-injection (MXNET_TPU_FAULT_INJECT=nan:step:N):
                # poison one gradient tensor ON DEVICE at step N — a
                # jnp.where on the step counter, baked into the trace,
                # so the injected run compiles the same program shape
                # as a healthy one (no retrace, no host branch)
                iname, istep = nan_inj
                g = grads[iname]
                grads = dict(grads)
                grads[iname] = jnp.where(
                    jnp.equal(t, np.int32(istep)),
                    jnp.full_like(g, jnp.nan), g)

            new_params = dict(params)
            new_states = dict(states)
            keep_dtype = jax.tree_util.tree_map
            if bucket is not None:
                # MXNET_TPU_OPT_BUCKET: ONE apply_dense over every
                # trainable parameter concatenated flat (multi-tensor
                # apply) — identical elementwise math, ~1 fused update
                # kernel instead of one per parameter. lr/wd
                # multipliers are read HERE (trace time, same moment
                # the per-param path reads them) and become
                # per-element vectors when non-uniform — lr and wd
                # enter every registered optimizer elementwise, so a
                # vector broadcasts into the same math.
                segs = bucket["segs"]
                wflat = jnp.concatenate(
                    [params[n].ravel() for n in trainable])
                gflat = jnp.concatenate(
                    [grads[n].astype(params[n].dtype).ravel()
                     for n in trainable])
                sflat = jax.tree_util.tree_map(
                    lambda *leaves: jnp.concatenate(
                        [l.ravel() for l in leaves]),
                    *[states[n] for n in trainable]) \
                    if states[trainable[0]] is not None else None
                lms = [opt._lr_mult_for(n) for n in trainable]
                lr_b = lr
                if any(lm != lms[0] for lm in lms):
                    lr_b = lr * jnp.concatenate([
                        jnp.full((sz,), np.float32(lm))
                        for (_n, _o, sz), lm in zip(segs, lms)])
                elif lms[0] != 1.0:
                    lr_b = lr * np.float32(lms[0])
                wds = [opt._wd_for(n) for n in trainable]
                if opt.wd and any(w != wds[0] for w in wds):
                    wd_mult_vec = jnp.concatenate([
                        jnp.full((sz,), np.float32(w / opt.wd))
                        for (_n, _o, sz), w in zip(segs, wds)])
                else:
                    wd_mult_vec = (wds[0] / opt.wd) if opt.wd else 1.0
                with opt.temp_wd_mult("__bucket__", wd_mult_vec):
                    w2, s2 = opt.apply_dense(
                        "__bucket__", wflat, gflat, sflat, lr_b, t)
                for n, off, sz in bucket["segs"]:
                    shape = params[n].shape
                    new_params[n] = w2[off:off + sz].reshape(shape)
                    if s2 is None:
                        new_states[n] = None
                    else:
                        piece = jax.tree_util.tree_map(
                            lambda leaf, sh=shape, o=off, z=sz:
                            leaf[o:o + z].reshape(sh), s2)
                        new_states[n] = keep_dtype(
                            lambda old, new: new.astype(old.dtype),
                            states[n], piece)
            else:
                for name in trainable:
                    w = params[name]
                    g = grads[name].astype(w.dtype)
                    lr_p = lr * opt._lr_mult_for(name)
                    w2, s2 = opt.apply_dense(
                        name, w, g, states[name], lr_p, t
                    )
                    new_params[name] = w2
                    # preserve the stored state dtype (bf16 opt-state
                    # mode computes in promoted f32, rounds back on
                    # store) so donated buffers stay type-stable
                    new_states[name] = keep_dtype(
                        lambda old, new: new.astype(old.dtype),
                        states[name], s2)
            new_auxs = {
                **auxs,
                **{
                    k: v.astype(auxs[k].dtype)
                    for k, v in aux_upd.items()
                    if k in auxs
                },
            }
            if sentinel is not None:
                # numerics sentinel row: every reduction here happens
                # inside the jit, so under a mesh GSPMD turns them into
                # the cross-shard psums for free and the row comes out
                # replicated — norms are GLOBAL regardless of the plan
                row = sentinel.compute(outs, params, new_params, grads)
                return outs, new_params, new_states, new_auxs, row
            return outs, new_params, new_states, new_auxs

        self._step_fn = step  # raw traceable body (multi-step loop)
        kwargs = {"donate_argnums": (0, 1, 2)}
        if self._mesh is not None:
            state_sh = {
                n: self._state_sharding(self.states[n], n)
                for n in self.states
            }
            aux_sh = {n: self._repl for n in self.auxs}
            data_sh = {
                n: (self._data_sh.get(n) or self._batch_sh)
                for n in self._data_names
            }
            kwargs["in_shardings"] = (
                self._param_sh, state_sh, aux_sh, data_sh, None, None,
            )
            # outputs keep whatever layout XLA picks (batch-sharded in
            # practice); pinning them could fail on rank-0 outputs.
            # Multi-process: replicate outputs (one small all-gather)
            # so every process can read them without a collective fetch
            out_sh = (
                self._repl if self._nproc > 1 else None,
                self._param_sh, state_sh, aux_sh,
            )
            if sentinel is not None:
                out_sh = out_sh + (self._repl,)
            kwargs["out_shardings"] = out_sh
        from ..sharding.lower import jit_sharded

        return jit_sharded(
            step,
            in_shardings=kwargs.get("in_shardings"),
            out_shardings=kwargs.get("out_shardings"),
            donate_argnums=kwargs["donate_argnums"],
            digest=self._profiling_digest(), kind="fused_step")

    def _profiling_digest(self):
        """Executable-accounting key for this step's programs: the
        executor's exec-cache entry digest, plus the sharding-plan
        digest when one governs the layout (the same symbol under two
        plans is two different executables)."""
        digest = getattr(self._ex._compiled, "digest", None)
        if digest and self._plan is not None:
            try:
                digest = f"{digest}+{self._plan.digest()[:8]}"
            except Exception:
                pass
        return digest

    def _nan_inject_plan(self):
        """(param_name, step) for the fault injector's 'nan:step:N'
        spec, or None. Resolved at build time so the poison bakes into
        the trace. Lazy import: fault.py imports the model layer."""
        from ..fault import parse_nan_inject

        spec = parse_nan_inject()
        if spec is None:
            return None
        istep, pname = spec
        if pname is None:
            pname = self._trainable[0] if self._trainable else None
        if pname not in self._trainable:
            self._logger.warning(
                "nan injection target %r is not a trainable parameter "
                "— injection disabled", pname)
            return None
        return (pname, istep)

    # -------------------------------------------------- numerics sentinel
    # device-resident rows between drains are bounded; a run that never
    # drains (numerics enabled, no monitor attached) drops the oldest
    _SENTINEL_CAP = 4096

    def enable_sentinel(self, spec):
        """Bake a numerics SentinelSpec into the step programs: every
        step then returns one extra replicated stats row. Rebuilds the
        jits — cheap before first compile (AOT compilation is lazy),
        a recompile after. Idempotent for the same spec."""
        if self._sentinel is spec:
            return
        self._sentinel = spec
        self._jitted = self._build()
        self._compiled = None
        self._multi_cache.clear()
        self._multi_compiled.clear()

    def _absorb(self, res, meta):
        """Unpack one dispatch's result into the owned training state;
        stash sentinel rows (still ON DEVICE — zero sync) when enabled.
        `meta` is [(t, lr)], one entry per row the result carries."""
        if self._sentinel is None:
            outs, self.params, self.states, self.auxs = res
            return outs
        outs, self.params, self.states, self.auxs, rows = res
        self._sentinel_pending.append((rows, list(meta)))
        total = sum(len(m) for _r, m in self._sentinel_pending)
        while total > self._SENTINEL_CAP and \
                len(self._sentinel_pending) > 1:
            _r, m = self._sentinel_pending.pop(0)
            total -= len(m)
            self._sentinel_dropped += len(m)
        return outs

    @staticmethod
    def _rows_ready(rows):
        try:
            return rows.is_ready()
        except AttributeError:
            return True

    def drain_sentinel(self, wait=True):
        """Move pending sentinel rows to host in ONE fetch (counted in
        hostSyncStats exactly like the device-metric drain, PR 3).
        Returns [(t, lr, row)] with row a 1-D float32 vector in the
        spec's column order; [] (no fetch) when nothing is pending.

        `wait=False` is the steady-state mode (NumericsMonitor's
        interval drains): only rows whose step has already COMPLETED
        on device are fetched, so the drain never stalls the dispatch
        pipeline behind an in-flight step — those rows ride the next
        drain. `wait=True` (epoch ends, manual drains, device Monitor
        toc) blocks for everything pending."""
        pending = self._sentinel_pending
        if not pending:
            return []
        if wait:
            take = len(pending)
        else:
            # dispatch order == completion order: the first unready
            # entry bounds everything after it
            take = 0
            for rows, _m in pending:
                if not self._rows_ready(rows):
                    break
                take += 1
            if take == 0:
                return []
        self._sentinel_pending = pending[take:]
        pending = pending[:take]
        host = jax.device_get([r for r, _m in pending])
        _profiler.count_host_sync("blocking_fetches")
        _profiler.count_host_sync("metric_fetches")
        out = []
        for mat, (_rows, metas) in zip(host, pending):
            mat = np.asarray(mat)
            if mat.ndim == 1:
                mat = mat[None]
            for i, (t, lr) in enumerate(metas):
                out.append((int(t), float(lr), mat[i]))
        return out

    # -------------------------------------------------------------- run
    def _place_data(self, data_vals):
        if self._batch_sh is None:
            return data_vals
        if self._nproc > 1:
            # THE multi-process data plane: each process contributes its
            # local batch shard; the global array is assembled without
            # any host gather, and the gradient all-reduce happens
            # inside the jit over DCN/ICI (vs the reference's
            # engine-wrapped ZPush/ZPull, kvstore_dist.h:111-123)
            return {
                k: jax.make_array_from_process_local_data(
                    self._data_sh.get(k) or self._batch_sh,
                    np.asarray(v))
                for k, v in data_vals.items()
            }
        return {
            k: jax.device_put(v, self._data_sh.get(k) or self._batch_sh)
            for k, v in data_vals.items()
        }

    def _ambient(self):
        """Install this step's mesh as ambient for the trace (mesh-aware
        ops — RingAttention, MoEFFN — read it); no-op without a mesh."""
        from . import mesh as mesh_mod

        return mesh_mod.use_mesh(self._mesh) if self._mesh is not None \
            else contextlib.nullcontext()

    def step(self, data_vals):
        """Run one fused step on {name: jnp array} batch inputs. Returns
        the forward outputs; params/states/auxs are advanced in place."""
        self._t += 1
        opt = self._opt
        opt.num_update += 1
        lr = (
            opt.lr_scheduler(opt.num_update)
            if opt.lr_scheduler is not None else opt.lr
        )
        args = (
            self.params, self.states, self.auxs,
            self._place_data(data_vals),
            np.float32(lr), np.int32(self._t),
        )
        with self._ambient(), _profiler.scope(
                "fused_train_step", "executor"):
            if self._compiled is None:
                try:
                    self._compiled = self._jitted.lower(*args).compile()
                except Exception:  # fall back to dispatch-compiled jit
                    self._compiled = False
            fn = self._compiled if self._compiled else self._jitted
            meta = ((self._t, float(lr)),)
            try:
                outs = self._absorb(fn(*args), meta)
            except (TypeError, ValueError):
                # shape/dtype drift (e.g. a differently-sized final
                # batch): the AOT executable is exact-shape; re-dispatch
                outs = self._absorb(self._jitted(*args), meta)
        return outs

    # ------------------------------------------------- multi-step loop
    def _multi_fn(self, k, stacked):
        """jit of a device-side k-step training loop (lax.scan over the
        fused step body). One host dispatch advances k optimizer steps;
        over a remote-dispatch backend (the axon TPU tunnel) the
        per-dispatch round-trip amortizes k-fold. The reference gets
        the same effect from its async dependency engine queueing many
        ops ahead of the host (SURVEY §2.2); the XLA-native equivalent
        is a compiled step loop."""
        key = (int(k), bool(stacked))
        fn = self._multi_cache.get(key)
        if fn is not None:
            return fn
        step_fn = self._step_fn
        sentinel = self._sentinel

        def multi(params, states, auxs, data, lrs, ts):
            carry = (params, states, auxs)
            rows = None
            if k > 1:
                if stacked:
                    xs = ({n: v[:-1] for n, v in data.items()},
                          lrs[:-1], ts[:-1])

                    def body(c, x):
                        data_i, lr_i, t_i = x
                        p, s, a = c
                        res = step_fn(p, s, a, data_i, lr_i, t_i)
                        return (res[1], res[2], res[3]), \
                            (res[4] if sentinel is not None else None)
                else:
                    xs = (lrs[:-1], ts[:-1])

                    def body(c, x):
                        lr_i, t_i = x
                        p, s, a = c
                        res = step_fn(p, s, a, data, lr_i, t_i)
                        return (res[1], res[2], res[3]), \
                            (res[4] if sentinel is not None else None)
                carry, rows = jax.lax.scan(body, carry, xs)
            params, states, auxs = carry
            last = {n: v[-1] for n, v in data.items()} if stacked \
                else data
            res = step_fn(params, states, auxs, last, lrs[-1], ts[-1])
            if sentinel is None:
                return res
            outs, p2, s2, a2, last_row = res
            # (k, C) row matrix: scan ys for the first k-1 steps plus
            # the peeled final step — same drain shape as k step()s
            all_rows = (jnp.concatenate([rows, last_row[None]], 0)
                        if rows is not None else last_row[None])
            return outs, p2, s2, a2, all_rows

        kwargs = {"donate_argnums": (0, 1, 2)}
        if self._mesh is not None:
            state_sh = {
                n: self._state_sharding(self.states[n], n)
                for n in self.states
            }
            aux_sh = {n: self._repl for n in self.auxs}
            base_sh = {
                n: (self._data_sh.get(n) or self._batch_sh)
                for n in self._data_names
            }
            data_sh = base_sh if not stacked else {
                n: NamedSharding(self._mesh, P(None, *sh.spec))
                for n, sh in base_sh.items()
            }
            kwargs["in_shardings"] = (
                self._param_sh, state_sh, aux_sh, data_sh, None, None,
            )
            out_sh = (
                self._repl if self._nproc > 1 else None,
                self._param_sh, state_sh, aux_sh,
            )
            if sentinel is not None:
                out_sh = out_sh + (self._repl,)
            kwargs["out_shardings"] = out_sh
        from ..sharding.lower import jit_sharded

        fn = jit_sharded(
            multi,
            in_shardings=kwargs.get("in_shardings"),
            out_shardings=kwargs.get("out_shardings"),
            donate_argnums=kwargs["donate_argnums"],
            digest=self._profiling_digest(),
            kind=f"fused_multi[{int(k)}]")
        self._multi_cache[key] = fn
        return fn

    def run_steps(self, data_vals, k, stacked=False):
        """Advance k train steps in ONE dispatch. Semantically identical
        to k ``step()`` calls: per-step lr follows the scheduler, t (and
        therefore the dropout rng chain) advances per inner step, state
        dtypes are preserved by the body itself.

        stacked=False reuses one resident batch for every inner step
        (synthetic benchmarking); stacked=True expects every data value
        with a leading (k,) axis of per-step batches and scans over it.

        Multi-process meshes run the SAME compiled k-loop for stacked
        batches: each process contributes its local (k, local_rows,
        ...) slice and the global array assembles without a host
        gather, exactly like the single-step data plane (_place_data).
        The non-stacked (replayed-batch) form stays sequential there —
        it exists for single-host benching only."""
        if k < 1:
            raise ValueError("run_steps needs k >= 1")
        opt = self._opt
        lrs, ts = [], []
        for _ in range(k):
            self._t += 1
            opt.num_update += 1
            lrs.append(float(
                opt.lr_scheduler(opt.num_update)
                if opt.lr_scheduler is not None else opt.lr))
            ts.append(self._t)
        if self._nproc > 1 and not stacked:
            outs = None
            placed = self._place_data(data_vals)  # loop-invariant
            for i in range(k):
                args = (self.params, self.states, self.auxs, placed,
                        np.float32(lrs[i]), np.int32(ts[i]))
                with self._ambient():
                    outs = self._absorb(
                        self._jitted(*args), ((ts[i], lrs[i]),))
            return outs
        lrs_v = np.asarray(lrs, np.float32)
        ts_v = np.asarray(ts, np.int32)

        def stacked_sharding(n):
            return NamedSharding(
                self._mesh,
                P(None, *(self._data_sh.get(n)
                          or self._batch_sh).spec))

        if stacked and self._nproc > 1:
            # global (k, global_rows, ...) from per-process local
            # slices — the multi-process data plane, leading step
            # axis replicated
            data = {
                n: jax.make_array_from_process_local_data(
                    stacked_sharding(n), np.asarray(v))
                for n, v in data_vals.items()
            }
        elif stacked and self._mesh is not None:
            data = {
                n: jax.device_put(v, stacked_sharding(n))
                for n, v in data_vals.items()
            }
        elif stacked:
            data = data_vals
        else:
            data = self._place_data(data_vals)
        fn = self._multi_fn(k, stacked)
        key = (int(k), bool(stacked))
        with self._ambient(), _profiler.scope(
                "fused_train_steps", "executor"):
            args = (self.params, self.states, self.auxs,
                    data, lrs_v, ts_v)
            ex = self._multi_compiled.get(key)
            if ex is None:
                try:  # AOT, like the single-step path
                    ex = fn.lower(*args).compile()
                except Exception:
                    ex = False
                self._multi_compiled[key] = ex
            call = ex if ex else fn
            meta = tuple(zip(ts, lrs))
            try:
                outs = self._absorb(call(*args), meta)
            except (TypeError, ValueError):
                outs = self._absorb(fn(*args), meta)
        return outs

    def sync(self):
        """Fence: wait until all queued steps have executed.

        Uses a host fetch of one parameter element rather than
        block_until_ready — remote-dispatch backends (the axon TPU
        tunnel) acknowledge enqueue, not completion, so only a value
        round-trip is a true barrier."""
        _profiler.count_host_sync("blocking_waits")
        jax.block_until_ready(self.params)
        if self.params:
            leaf = next(iter(self.params.values()))
            if self._nproc > 1:
                np.asarray(leaf.addressable_data(0))
            else:
                np.asarray(jax.device_get(jnp.ravel(leaf)[0]))

    # --------------------------------------------------------- teardown
    def load_params(self, arg_params, aux_params):
        """Replace the owned parameters/auxs from NDArray dicts (the
        Module calls this when params changed outside the fused step —
        set_params, init_params(force_init), an eager update)."""
        def place(x, sh):
            if sh is not None:
                return self._put(np.asarray(x), sh)
            return jnp.copy(jnp.asarray(x))

        for n in self._param_names:
            sh = self._param_sh[n] if self._param_sh is not None else None
            self.params[n] = place(arg_params[n]._data, sh)
        for n in self._aux_names:
            self.auxs[n] = place(aux_params[n]._data, self._repl)

    def snapshot(self):
        """(params, auxs) as safe-to-expose copies: the live buffers
        will be donated by the next step(), so callers must never hold
        references to them. In mesh mode the copies are materialized on
        a single device so eager executors can consume them.

        Multi-process with model-sharded params this is COLLECTIVE
        (full_host all-gathers): every process must reach it — get_params
        / checkpointing must not be rank-guarded (jax multihost
        contract; the reference's rank-0-only save worked because dist
        kvstore values were always replicated)."""
        if self._mesh is None:
            leaf = jnp.copy
        elif self._nproc > 1:
            # replicated leaves read their local copy; model-sharded
            # params all-gather to replicated first (full_host)
            from .mesh import full_host

            leaf = lambda v: jnp.asarray(full_host(v))
        else:
            dev0 = self._mesh.devices.flat[0]
            leaf = lambda v: jax.device_put(v, dev0)
        cp = lambda t: {k: leaf(v) for k, v in t.items()}
        return cp(self.params), cp(self.auxs)

    # ------------------------------------------------------ diagnostics
    def flops(self):
        """FLOPs of one compiled train step, from XLA cost analysis.

        When only a multi-step loop was compiled (run_steps-only use,
        e.g. BENCH_MULTISTEP), per-step work is estimated from the
        k-loop program. XLA cost analysis counts a while/scan body ONCE
        regardless of trip count, so the k-loop program's reported cost
        is (scan body) + (the one peeled final step) ~= 2x one step for
        any k > 1 — hence the /2 below (exactly 1x for k == 1, where
        there is no scan). The residual error is the non-step scan
        plumbing, which is negligible against a train step."""
        def _cost(ex):
            cost = ex.cost_analysis()
            if isinstance(cost, list):
                cost = cost[0]
            return float(cost.get("flops", 0.0))

        try:
            if self._compiled:
                return _cost(self._compiled)
            for (k, _st), ex in self._multi_compiled.items():
                if ex:
                    return _cost(ex) / (2.0 if k > 1 else 1.0)
        except Exception:
            return 0.0
        return 0.0

    # ------------------------------------------ optimizer state save/load
    STATE_FORMAT = "mxnet_tpu/fused_v1"

    def get_states(self):
        # collective when states are model-sharded multi-process: all
        # processes must call (see snapshot's contract note)
        from .mesh import full_host

        host = jax.tree_util.tree_map(full_host, self.states)
        return pickle.dumps(
            {"format": self.STATE_FORMAT, "t": self._t, "states": host}
        )

    def set_states(self, blob):
        obj = pickle.loads(blob)
        if isinstance(obj, dict) and obj.get("format") == \
                self.STATE_FORMAT:
            t, host = obj["t"], obj["states"]
        elif isinstance(obj, dict):
            # eager Updater checkpoint ({index: state}): translate
            # indices to parameter names through the optimizer's map
            idx2name = self._opt.idx2name
            host = {
                idx2name[i]: v for i, v in obj.items()
                if idx2name.get(i) in self.states
            }
            missing = set(self.states) - set(host)
            if missing:
                raise MXNetError(
                    f"optimizer state file lacks entries for {missing}"
                )
            t = self._opt.num_update
        else:
            raise MXNetError("unrecognized optimizer state format")

        tmpl = self.states
        new = jax.tree_util.tree_map(jnp.asarray, host)
        if self._state_dtype is not None:
            # a resumed f32 checkpoint must re-enter the configured
            # reduced-precision state mode, not silently disable it
            new = jax.tree_util.tree_map(
                lambda x: x.astype(self._state_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, new)
        if self._repl is not None:
            new = {n: self._place_state(s, n) for n, s in new.items()}
        if jax.tree_util.tree_structure(new) != \
                jax.tree_util.tree_structure(tmpl):
            raise MXNetError("optimizer state structure mismatch")
        self._t = t
        self.states = new


def supports_fused(optimizer):
    """True when the optimizer overrides the traced apply_dense form."""
    from ..optimizer import Optimizer

    return type(optimizer).apply_dense is not Optimizer.apply_dense

"""Disk tier under the in-process executable cache — warm restarts.

The in-process cache (`exec_cache`) dedups traces within ONE process;
every restart still pays the full trace+compile bill. This module is
the tier below it: a directory of per-entry records keyed by the same
canonical cache-key digest, holding the optimized canonical graph, the
input signatures, the sharding-plan digest, and AOT-serialized
executables (`jax.experimental.serialize_executable`). A fresh process
that binds the same graph finds the record, deserializes the
executables, and serves with ZERO traces and ZERO compiles.

Two storage layers cooperate:

  * the XLA layer — jax's own persistent compilation cache
    (`jax_compilation_cache_dir`), pointed at `<dir>/xla`. Even when
    our executable blobs are stale (jaxlib upgrade), re-compiles hit
    jax's cache and only the cheap re-trace is paid.
  * our layer — `<dir>/entries/<digest>/record.json` plus
    `exe-<kind>-<sighash>.bin` blobs. record.json carries an
    environment fingerprint (format version, framework + jaxlib
    versions, platform); a mismatch is counted `disk_stale` and falls
    back to a normal re-trace, never an error.

Activation: set MXNET_EXEC_CACHE_DIR (registered in `utils`). Unset
(the default) the tier is inert — zero behavior change. Serving
bundles (`serving.bundle`) mount their embedded `exec_cache/` subtree
as a read-only OVERLAY root: lookups consult the primary dir first,
then overlays; writes go to the primary dir only (or nowhere when only
overlays are mounted).

Robustness contract (tested in tests/test_disk_cache.py):

  * corrupted / torn entries are QUARANTINED (moved aside into
    `<root>/quarantine/`), counted, and treated as a miss — never
    fatal;
  * entries this process wrote are skipped on lookup, so in-process
    trace/compile accounting is bit-identical to the no-disk-tier
    world (tests that pin exact trace counts stay valid);
  * the primary dir is LRU-evicted (whole entries, record mtime as
    recency) to MXNET_EXEC_CACHE_DISK_BYTES; the `xla/` subtree is
    jax's to manage and is not counted.

All counters live under one module lock; ALL file I/O happens outside
it (MX006 — the snapshot pattern, see utils.persist).
"""
from __future__ import annotations

import os
import pickle
import re
import shutil
import threading

from .utils.persist import atomic_write_json, read_json

#: record.json / exe blob format — bump on incompatible layout change
RECORD_VERSION = 1

_lock = threading.Lock()
_stats = {
    "disk_hits": 0,        # record found on disk and compatible
    "disk_misses": 0,      # no record anywhere (tier active)
    "disk_stale": 0,       # record/blob from an incompatible env
    "disk_writes": 0,      # records written by this process
    "disk_evictions": 0,   # whole entries LRU-evicted over the cap
    "disk_quarantined": 0,  # corrupt records/blobs moved aside
    "exe_loads": 0,        # executables deserialized from disk
    "exe_stores": 0,       # executables serialized to disk
}
#: absolute paths written by THIS process — lookups skip them so the
#: in-process cache keeps its exact pre-disk trace/compile accounting
_self_written = set()
#: read-only bundle roots consulted after the primary dir
_overlays = []
_jax_cache_configured_for = None


# --------------------------------------------------------------- paths
def cache_dir():
    """Primary (writable) cache root from MXNET_EXEC_CACHE_DIR, or
    None when the tier is unset."""
    raw = os.environ.get("MXNET_EXEC_CACHE_DIR", "")
    return os.path.expanduser(raw) if raw else None


def tier_active():
    """True when any root (primary or overlay) is mounted."""
    return bool(cache_dir()) or bool(_overlays)


def _roots():
    """Search order: primary first (fresh writes win), then overlays."""
    primary = cache_dir()
    roots = [primary] if primary else []
    roots.extend(_overlays)
    return roots


def entry_dir(root, digest):
    return os.path.join(root, "entries", str(digest))


def add_overlay(path):
    """Mount a read-only exec-cache root (a bundle's `exec_cache/`
    subtree). Idempotent; overlays are searched after the primary."""
    path = os.path.abspath(path)
    with _lock:
        if path not in _overlays:
            _overlays.append(path)


def remove_overlay(path):
    path = os.path.abspath(path)
    with _lock:
        if path in _overlays:
            _overlays.remove(path)


def clear_overlays():
    with _lock:
        _overlays.clear()


# ----------------------------------------------------- jax's own cache
def configure_jax_cache():
    """Point jax's persistent compilation cache at `<dir>/xla` (once
    per dir). The dir must exist BEFORE the config update — jax
    resolves it eagerly. Best-effort: an old jax without the knobs
    just skips the XLA layer."""
    global _jax_cache_configured_for
    root = cache_dir()
    if not root or _jax_cache_configured_for == root:
        return
    xla_dir = os.path.join(root, "xla")
    try:
        os.makedirs(xla_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", xla_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0)
        _jax_cache_configured_for = root
    except Exception:
        pass


# --------------------------------------------------------- fingerprint
def env_fingerprint():
    """What must match for a disk entry to be trusted. Serialized
    executables are jaxlib+platform artifacts; the framework version
    rides along for diagnostics (not checked — our record layout is
    covered by `format`)."""
    import jax

    try:
        import jaxlib

        jaxlib_version = jaxlib.__version__
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_version = jax.__version__
    from . import __version__ as framework_version

    return {
        "format": RECORD_VERSION,
        "framework": framework_version,
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "platform": jax.default_backend(),
    }


def _compatible(env):
    if not isinstance(env, dict):
        return False
    want = env_fingerprint()
    return (env.get("format") == want["format"]
            and env.get("jaxlib") == want["jaxlib"]
            and env.get("platform") == want["platform"])


# ---------------------------------------------------------- quarantine
def _quarantine(root, path):
    """Move a corrupt file (or whole entry dir) aside — never delete
    evidence, never raise. Quarantined entries read as misses."""
    qdir = os.path.join(root, "quarantine")
    try:
        os.makedirs(qdir, exist_ok=True)
        os.replace(path, os.path.join(qdir, os.path.basename(path)
                                      + f".{os.getpid()}"))
    except OSError:
        try:
            os.unlink(path)
        except OSError:
            pass
    with _lock:
        _stats["disk_quarantined"] += 1


# -------------------------------------------------------------- records
def lookup_record(digest):
    """The record dict for `digest` from the first root that has a
    compatible one, else None. Counts disk_hits / disk_misses /
    disk_stale; corrupt records are quarantined and skipped."""
    if not tier_active():
        return None
    stale_seen = False
    for root in _roots():
        path = os.path.join(entry_dir(root, digest), "record.json")
        if path in _self_written or not os.path.exists(path):
            continue
        rec = read_json(path)
        if rec is None:
            _quarantine(root, path)
            continue
        if not _compatible(rec.get("env")):
            stale_seen = True
            continue
        try:  # LRU recency for the eviction walk
            os.utime(path)
        except OSError:
            pass
        with _lock:
            _stats["disk_hits"] += 1
        return rec
    with _lock:
        if stale_seen:
            _stats["disk_stale"] += 1
        else:
            _stats["disk_misses"] += 1
    return None


def write_record(digest, canonical=None, meta_fn=None, root=None):
    """Persist the record for a freshly-built entry into the primary
    root (overlays are read-only). Best-effort: a full disk or
    read-only root costs only the next process a re-trace.

    `root` overrides the destination (serving.bundle writes a bundle's
    self-contained `exec_cache/` subtree); explicit-root writes are
    NOT marked self-written — a bundle is a separate namespace the
    writing process may legitimately mount and read back."""
    explicit = root is not None
    root = root or cache_dir()
    if not root:
        return None
    rec = {"digest": str(digest), "env": env_fingerprint()}
    if canonical:
        rec["canonical"] = canonical
    if meta_fn is not None:
        try:
            meta = meta_fn()
            if meta:
                rec.update(meta)
        except Exception:
            pass  # meta is advisory; the record still marks the entry
    path = os.path.join(entry_dir(root, digest), "record.json")
    try:
        atomic_write_json(path, rec)
    except OSError:
        return None
    with _lock:
        if not explicit:
            _self_written.add(path)
        _stats["disk_writes"] += 1
    if not explicit:
        _maybe_evict()
    return path


# ---------------------------------------------------------- executables
def _safe_kind(kind):
    return re.sub(r"[^A-Za-z0-9_.@-]", "_", str(kind))


def sig_hash(sig_key):
    """Deterministic cross-process hash of profiling's signature key
    (treedef, tuple-of-aval-sigs). str(PyTreeDef) is deterministic and
    dicts flatten in sorted key order, so two processes tracing the
    same call shapes agree."""
    import hashlib

    treedef, sig = sig_key
    return hashlib.sha1(
        repr((str(treedef), sig)).encode()).hexdigest()[:16]


def exe_path(root, digest, kind, sighash):
    return os.path.join(entry_dir(root, digest),
                        f"exe-{_safe_kind(kind)}-{sighash}.bin")


def store_executable(digest, kind, sighash, compiled, root=None):
    """AOT-serialize `compiled` into the primary root (or an explicit
    `root` — the serving.bundle path, not self-marked, not evicted).
    Returns the path, or None (tier unset / serialization
    unsupported / disk full) — all soft failures."""
    explicit = root is not None
    root = root or cache_dir()
    if not root:
        return None
    try:
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = _se.serialize(compiled)
        blob = pickle.dumps({
            "env": env_fingerprint(),
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        })
    except Exception:
        return None
    path = exe_path(root, digest, kind, sighash)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        return None
    with _lock:
        if not explicit:
            _self_written.add(path)
        _stats["exe_stores"] += 1
    if not explicit:
        _maybe_evict()
    return path


def load_executable(digest, kind, sighash):
    """Deserialize an AOT executable from the first root that has a
    compatible blob. None on miss/stale/corrupt (caller re-traces)."""
    if not tier_active():
        return None
    for root in _roots():
        path = exe_path(root, digest, kind, sighash)
        if path in _self_written or not os.path.exists(path):
            continue
        try:
            with open(path, "rb") as f:
                blob = pickle.loads(f.read())
            if not isinstance(blob, dict):
                raise ValueError("not an exe blob")
        except Exception:
            _quarantine(root, path)
            continue
        if not _compatible(blob.get("env")):
            with _lock:
                _stats["disk_stale"] += 1
            continue
        try:
            from jax.experimental import serialize_executable as _se

            compiled = _se.deserialize_and_load(
                blob["payload"], blob["in_tree"], blob["out_tree"])
        except Exception:
            # a payload this jaxlib can't rehydrate IS staleness,
            # whatever the fingerprint claimed
            with _lock:
                _stats["disk_stale"] += 1
            continue
        try:
            os.utime(os.path.join(entry_dir(root, digest),
                                  "record.json"))
        except OSError:
            pass
        with _lock:
            _stats["exe_loads"] += 1
        return compiled
    return None


# ------------------------------------------------------------- eviction
def disk_cap_bytes():
    from .utils import getenv

    try:
        return int(getenv("MXNET_EXEC_CACHE_DISK_BYTES"))
    except Exception:
        return 0


def _entry_sizes(root):
    """[(mtime, bytes, path)] per entry dir under `root`."""
    base = os.path.join(root, "entries")
    out = []
    try:
        names = os.listdir(base)
    except OSError:
        return out
    for name in names:
        d = os.path.join(base, name)
        if not os.path.isdir(d):
            continue
        size = 0
        try:
            for fn in os.listdir(d):
                try:
                    size += os.path.getsize(os.path.join(d, fn))
                except OSError:
                    pass
            mtime = os.path.getmtime(os.path.join(d, "record.json"))
        except OSError:
            mtime = 0.0
        out.append((mtime, size, d))
    return out


def _maybe_evict():
    """Drop least-recently-used WHOLE entries until the primary root's
    entries/ subtree fits MXNET_EXEC_CACHE_DISK_BYTES (0 = uncapped).
    jax's xla/ subtree is its own cache and is not counted."""
    cap = disk_cap_bytes()
    root = cache_dir()
    if not root or cap <= 0:
        return
    entries = _entry_sizes(root)
    total = sum(size for _, size, _ in entries)
    if total <= cap:
        return
    evicted = 0
    for _, size, d in sorted(entries):
        if total <= cap:
            break
        shutil.rmtree(d, ignore_errors=True)
        total -= size
        evicted += 1
    if evicted:
        with _lock:
            _stats["disk_evictions"] += evicted


# ------------------------------------------------------------- counters
def counters():
    with _lock:
        return dict(_stats)


def reset_counters():
    """Zero the counters. `_self_written` is deliberately NOT cleared:
    it is process-lifetime identity (which entries THIS process
    produced), and clearing it mid-process would let tests that reset
    stats start disk-hitting their own writes — changing the pinned
    in-process trace counts the skip exists to protect."""
    with _lock:
        for k in _stats:
            _stats[k] = 0


def disk_stats():
    """telemetry view: all-numeric so the Prometheus flattening emits
    every field. Empty dict when the tier never activated (omit_empty
    hides it from views())."""
    snap = counters()
    active = tier_active()
    if not active and not any(snap.values()):
        return {}
    snap["enabled"] = bool(active)
    snap["overlays"] = len(_overlays)
    snap["cap_bytes"] = disk_cap_bytes()
    return snap


def _register_view():
    try:
        from .telemetry import register_view

        register_view("diskCacheStats", disk_stats,
                      prom_prefix="disk_cache", omit_empty=True)
    except Exception:  # pragma: no cover - telemetry is optional
        pass


_register_view()

"""Python-defined operators (reference python/mxnet/operator.py).

Three generations, matching the reference surface:
- `CustomOp`/`CustomOpProp` + `register` (reference operator.py:396-855)
  — the supported API; ops run via jax.pure_callback (see
  ops/custom.py) and appear as `mx.sym.Custom(..., op_type=name)`.
- `NDArrayOp` (reference operator.py:226) and `NumpyOp` (reference
  operator.py:126) — legacy single-class styles; implemented here as
  adapters that auto-register an equivalent CustomOpProp and whose
  get_symbol() emits the Custom node, preserving the old calling
  convention.
"""
from __future__ import annotations

import itertools

import numpy as np

from .base import MXNetError
from .ops import custom as _custom


class CustomOp(object):
    """Base class for operators implemented in Python (reference
    operator.py:396)."""

    def __init__(self):
        pass

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Assign src to dst according to req (reference operator.py:432)."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError(f"unknown req {req!r}")


class CustomOpProp(object):
    """Properties/metadata for a CustomOp (reference operator.py:522)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (
            in_type,
            [in_type[0]] * len(self.list_outputs()),
            [in_type[0]] * len(self.list_auxiliary_states()),
        )

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def list_auxiliary_states(self):
        return []

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Register a CustomOpProp subclass under op_type `reg_name`
    (reference operator.py register/MXCustomOpRegister)."""

    def do_register(prop_cls):
        _custom.register_prop(reg_name, prop_cls)
        return prop_cls

    return do_register


def get_all_registered():
    return dict(_custom._PROP_REGISTRY)


# --------------------------------------------------------------- legacy APIs

_legacy_counter = itertools.count()


class _LegacyAdapterProp(CustomOpProp):
    """CustomOpProp facade over a PythonOp instance."""

    def __init__(self, pyop=None, **_kwargs):
        super().__init__(need_top_grad=pyop.need_top_grad())
        self._op = pyop

    def list_arguments(self):
        return self._op.list_arguments()

    def list_outputs(self):
        return self._op.list_outputs()

    def infer_shape(self, in_shape):
        ins, outs = self._op.infer_shape(in_shape)
        return ins, outs, []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _LegacyAdapterOp(self._op)


class _LegacyAdapterOp(CustomOp):
    def __init__(self, pyop):
        super().__init__()
        self._op = pyop

    def forward(self, is_train, req, in_data, out_data, aux):
        self._op.forward(in_data=in_data, out_data=out_data)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self._op.backward(
            out_grad=out_grad, in_data=in_data, out_data=out_data,
            in_grad=in_grad,
        )


class PythonOp(object):
    """Base for the legacy NumpyOp/NDArrayOp styles (reference
    operator.py:63)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad
        self._reg_name = None

    def forward(self, in_data, out_data):
        raise NotImplementedError

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_

    def get_symbol(self, *args, **kwargs):
        """Create the symbol for this op applied to `args` (reference
        PythonOp.get_symbol)."""
        from . import symbol

        if self._reg_name is None:
            self._reg_name = (
                f"_legacy_{type(self).__name__}_{next(_legacy_counter)}"
            )
            op = self
            _custom.register_prop(
                self._reg_name,
                lambda **kw: _LegacyAdapterProp(pyop=op),
            )
        kwargs["op_type"] = self._reg_name
        return symbol.Custom(*args, **kwargs)

    __call__ = get_symbol


class NumpyOp(PythonOp):
    """Legacy numpy-array custom op (reference operator.py:126): forward
    and backward receive numpy arrays."""

    def get_symbol(self, *args, **kwargs):
        from . import symbol

        if self._reg_name is None:
            self._reg_name = (
                f"_legacy_{type(self).__name__}_{next(_legacy_counter)}"
            )
            op = self
            _custom.register_prop(
                self._reg_name,
                lambda **kw: _NumpyAdapterProp(pyop=op),
            )
        kwargs["op_type"] = self._reg_name
        return symbol.Custom(*args, **kwargs)

    __call__ = get_symbol


class _NumpyAdapterProp(_LegacyAdapterProp):
    def create_operator(self, ctx, in_shapes, in_dtypes):
        return _NumpyAdapterOp(self._op)


class _NumpyAdapterOp(CustomOp):
    def __init__(self, pyop):
        super().__init__()
        self._op = pyop

    def forward(self, is_train, req, in_data, out_data, aux):
        np_in = [x.asnumpy() for x in in_data]
        np_out = [np.zeros(x.shape, x.dtype) for x in out_data]
        self._op.forward(in_data=np_in, out_data=np_out)
        for dst, src in zip(out_data, np_out):
            dst[:] = src

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        np_og = [x.asnumpy() for x in out_grad]
        np_in = [x.asnumpy() for x in in_data]
        np_out = [x.asnumpy() for x in out_data]
        np_ig = [np.zeros(x.shape, x.dtype) for x in in_grad]
        self._op.backward(
            out_grad=np_og, in_data=np_in, out_data=np_out,
            in_grad=np_ig,
        )
        for dst, src in zip(in_grad, np_ig):
            dst[:] = src


class NDArrayOp(PythonOp):
    """Legacy NDArray custom op (reference operator.py:226): forward and
    backward receive NDArrays (device-backed)."""

    pass

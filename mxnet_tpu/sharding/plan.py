"""ShardingPlan: one mesh + one rule table, bound to a concrete model.

The plan is the single object a user hands to `Module(...,
sharding=plan)` / `Module.bind(..., sharding=plan)` /
`FeedForward(..., sharding=plan)`. It owns:

  - the mesh ({axis: size} built lazily via parallel.mesh.make_mesh,
    or a prebuilt jax Mesh),
  - the rule layer (spec.SpecLayout + user overrides by glob),
  - resolution of every parameter/input name to a fitted
    PartitionSpec / NamedSharding (advisory rules downgrade axes that
    are absent or do not divide; explicit overrides are enforced by
    analysis.graph_verify.verify_sharding BEFORE any trace),
  - fsdp semantics: storage specs keep the fsdp axis (parameters and
    optimizer state live sharded, reduce-scatter after grad falls out
    of the jit's sharded out_shardings); `compute_spec` drops it, and
    the fused step pins parameters to it inside the trace —
    gather-before-use as an explicit with_sharding_constraint
    (MXNET_SHARD_CONSTRAIN_COMPUTE),
  - a stable `digest()` that joins the exec-cache key so resharded
    rebinds of one symbol never collide on a compiled program.

The batch shards over every data-like axis in the mesh ('data' and
'fsdp' together — fsdp devices consume distinct batch rows, which is
what makes it ZeRO data parallelism rather than tensor parallelism).
"""
from __future__ import annotations

import hashlib
import math
import os

from jax.sharding import NamedSharding, PartitionSpec

from .spec import (DEFAULT_LAYOUT, parameter_spec_from_name,
                   rules_digest, spec_to_str)


def _fsdp_min_size():
    # registered as MXNET_SHARD_FSDP_MIN_SIZE in mxnet_tpu.utils; read
    # raw to keep plan resolution import-light
    try:
        return int(os.environ.get("MXNET_SHARD_FSDP_MIN_SIZE", "0"))
    except ValueError:
        return 0


class ShardingPlan:
    """Mesh + rules, resolvable against a Symbol's parameter trees.

    `mesh` is {axis: size} (built lazily on first `.mesh` access so a
    plan can be constructed before jax devices exist) or a prebuilt
    `jax.sharding.Mesh`. `overrides` maps parameter-name globs to
    PartitionSpecs (or the string syntax of
    parallel.mesh.parse_partition_spec); exact names outrank globs.
    """

    def __init__(self, mesh, layout=None, overrides=None,
                 constrain_compute=None):
        if hasattr(mesh, "axis_names"):        # a prebuilt Mesh
            self._mesh = mesh
            self._axis_sizes = dict(
                zip(mesh.axis_names, mesh.devices.shape))
        else:
            self._mesh = None
            self._axis_sizes = {str(k): int(v)
                                for k, v in dict(mesh).items()}
            if any(v < 1 for v in self._axis_sizes.values()):
                raise ValueError(
                    f"mesh axis sizes must be >= 1: {self._axis_sizes}")
        self.layout = layout or DEFAULT_LAYOUT
        self.overrides = dict(overrides or {})
        if constrain_compute is None:
            constrain_compute = os.environ.get(
                "MXNET_SHARD_CONSTRAIN_COMPUTE", "1") not in (
                "0", "false", "off")
        self.constrain_compute = bool(constrain_compute)
        self._resolved = {}        # name -> fitted PartitionSpec
        self._explicit = set()     # names resolved from an override

    # ------------------------------------------------------------ mesh
    @property
    def axis_sizes(self):
        """{axis: size} — available without building the device mesh
        (verify_sharding runs off this, pre-trace)."""
        return dict(self._axis_sizes)

    @property
    def mesh(self):
        if self._mesh is None:
            from ..parallel.mesh import make_mesh

            self._mesh = make_mesh(self._axis_sizes)
        return self._mesh

    def adopt_mesh(self, mesh):
        """Bind to an externally-built Mesh (Module does this so the
        plan and the fused step share ONE mesh object). Axis names and
        sizes must match the plan's."""
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if sizes != self._axis_sizes:
            raise ValueError(
                f"mesh {sizes} does not match the plan's axes "
                f"{self._axis_sizes}")
        self._mesh = mesh

    def device_count(self):
        return math.prod(self._axis_sizes.values()) \
            if self._axis_sizes else 1

    # ---------------------------------------------------- batch inputs
    def batch_axes(self):
        """Mesh axes the batch dim shards over: 'data' and 'fsdp'
        together when both exist (fsdp ranks consume distinct rows)."""
        return tuple(a for a in (self.layout.data_axis,
                                 self.layout.fsdp_axis)
                     if a in self._axis_sizes)

    def input_spec(self, name, ndim=1):
        """Fitted spec for a data/label input: an override wins,
        otherwise dim 0 over the batch axes."""
        if self.overrides:
            spec, explicit = parameter_spec_from_name(
                name, self.layout, self.overrides, ndim=None)
            if explicit:
                return spec
        axes = self.batch_axes()
        if not axes or ndim < 1:
            return PartitionSpec()
        dim0 = axes[0] if len(axes) == 1 else axes
        return PartitionSpec(dim0, *([None] * (ndim - 1)))

    # ------------------------------------------------------ parameters
    def spec_for(self, name, ndim=None):
        """(raw spec, explicit) straight from the rule layer — NOT
        fitted to a shape; resolve() is the fitting step."""
        return parameter_spec_from_name(
            name, self.layout, self.overrides, ndim=ndim)

    def _fit(self, spec, shape, explicit, name):
        """Fit one raw spec to a concrete shape. Advisory (rule/
        fallback) axes drop when absent from the mesh, non-dividing, or
        below the fsdp min-size knob; explicit specs pass through
        untouched (verify_sharding owns rejecting bad ones, with the
        parameter named)."""
        dims = list(tuple(spec))[:len(shape)]
        dims += [None] * (len(shape) - len(dims))
        if explicit:
            return PartitionSpec(*dims)
        min_sz = _fsdp_min_size()
        small = (min_sz > 0
                 and math.prod(shape or (1,)) < min_sz)
        out = []
        for d, size in zip(dims, shape):
            axes = d if isinstance(d, tuple) else (d,)
            kept = []
            for ax in axes:
                if ax is None:
                    continue
                n = self._axis_sizes.get(ax)
                if n is None or n < 2:
                    continue
                if size % n != 0:
                    continue
                if small and ax == self.layout.fsdp_axis:
                    continue
                kept.append(ax)
                size //= n
            out.append(tuple(kept) if len(kept) > 1
                       else (kept[0] if kept else None))
        while out and out[-1] is None:
            out.pop()
        return PartitionSpec(*out)

    def resolve(self, shapes):
        """Fit the rule table against {param_name: shape}; returns
        {name: PartitionSpec} (replicated entries included) and caches
        it. Explicit-override names are recorded in `explicit_names`."""
        for name, shape in shapes.items():
            shape = tuple(shape)
            raw, explicit = self.spec_for(name, ndim=len(shape))
            if explicit:
                self._explicit.add(name)
            self._resolved[name] = self._fit(raw, shape, explicit, name)
        return {n: self._resolved[n] for n in shapes}

    @property
    def explicit_names(self):
        return set(self._explicit)

    def named_shardings(self, shapes):
        """{name: NamedSharding} over the built mesh (resolves first)."""
        specs = self.resolve(shapes)
        mesh = self.mesh
        return {n: NamedSharding(mesh, s) for n, s in specs.items()}

    # --------------------------------------------------- fsdp compute
    def compute_spec(self, spec):
        """Storage spec -> compute spec: the fsdp axis is removed
        (gather-before-use); every other axis stays (tp compute IS
        sharded)."""
        fsdp = self.layout.fsdp_axis
        dims = []
        for d in tuple(spec):
            axes = [a for a in (d if isinstance(d, tuple) else (d,))
                    if a is not None and a != fsdp]
            dims.append(tuple(axes) if len(axes) > 1
                        else (axes[0] if axes else None))
        while dims and dims[-1] is None:
            dims.pop()
        return PartitionSpec(*dims)

    def uses_fsdp(self):
        return self._axis_sizes.get(self.layout.fsdp_axis, 1) > 1

    # ----------------------------------------------------- cache key
    def digest(self):
        """Stable hash of everything that changes the compiled program:
        mesh axis names+sizes, the rule configuration, and the compute-
        constraint mode. Joins `Executor._cache_key` so two binds of one
        symbol under different plans never share a CompiledGraph."""
        h = hashlib.sha1()
        h.update(repr(sorted(self._axis_sizes.items())).encode())
        h.update(rules_digest(self.layout, self.overrides).encode())
        h.update(b"constrain" if self.constrain_compute else b"free")
        return h.hexdigest()

    def describe(self, shapes=None):
        """Human-readable rule dump (docs/sharding.md walkthrough)."""
        lines = [f"mesh: {self._axis_sizes}"]
        for name, spec in sorted((shapes and self.resolve(shapes)
                                  or self._resolved).items()):
            tag = " (override)" if name in self._explicit else ""
            lines.append(f"  {name}: {spec_to_str(spec)}{tag}")
        return "\n".join(lines)

    def __repr__(self):
        return (f"ShardingPlan(mesh={self._axis_sizes}, "
                f"overrides={len(self.overrides)}, "
                f"digest={self.digest()[:12]})")

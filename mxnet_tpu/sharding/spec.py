"""Parameter-name → PartitionSpec rule layer (ROADMAP item 3).

The reference framework places parameters by listing devices
(context lists + `__ctx_group__` attrs); the GSPMD story replaces both
with ONE mesh of named axes and a table of rules mapping each parameter
NAME to a `PartitionSpec` over those axes (the SNIPPETS.md [2] shape:
a frozen `SpecLayout` of role methods plus `parameter_spec_from_name`).

Three axes cover the composed data/model/fsdp story:

  data   pure data parallelism — batch dim 0 shards over it
  fsdp   ZeRO-style parameter sharding: storage (and optimizer state)
         shard over it, compute gathers before use and reduce-scatters
         gradients after (plan.py wires the semantics)
  tp     tensor parallelism — embeddings / projection output dims
         split over it (NOTE: mxnet FullyConnected weights are
         (out, in), so "column parallel" puts `tp` on dim 0)

Resolution order for one parameter name (first match wins):

  1. user overrides, exact (glob-free) patterns first
  2. user overrides with wildcards, in insertion order
  3. DEFAULT_RULES (role globs -> SpecLayout methods), in order
  4. fallback: dim 0 over `fsdp` ("replicated-or-fsdp otherwise" —
     plan.py drops the axis again for params it cannot divide)

Default-rule and fallback specs are ADVISORY: `ShardingPlan.resolve`
silently downgrades any axis that is absent from the mesh or does not
divide the dim. Override specs are USER INTENT: a non-dividing override
is rejected by `analysis.graph_verify.verify_sharding` before any
trace (see docs/sharding.md).
"""
from __future__ import annotations

import fnmatch
import hashlib
from dataclasses import dataclass

from jax.sharding import PartitionSpec

# Canonical axis names (parallel/mesh.py re-exports them alongside the
# legacy data/model/seq/pipe/expert set).
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TP_AXIS = "tp"


@dataclass(frozen=True)
class SpecLayout:
    """Frozen table of role -> PartitionSpec rules over named axes.

    Instantiate with different axis names to retarget the same rules
    (e.g. tp_axis='model' to reuse a legacy 'model' mesh axis)."""

    data_axis: str = DATA_AXIS
    fsdp_axis: str = FSDP_AXIS
    tp_axis: str = TP_AXIS

    # ---------------------------------------------------- weight roles
    def embeddings(self):
        """(vocab, d_model) tables: vocab over fsdp+tp together."""
        return PartitionSpec((self.fsdp_axis, self.tp_axis), None)

    def qkv_projection(self):
        """Attention in-projections, (out, in): out over tp, in over
        fsdp — column-parallel compute, fsdp storage."""
        return PartitionSpec(self.tp_axis, self.fsdp_axis)

    def output_projection(self):
        """Output heads / attention out-projections: split on tp."""
        return PartitionSpec(self.tp_axis, self.fsdp_axis)

    def ffn_up(self):
        """FFN up-projection, (d_ff, d_model): column-parallel."""
        return PartitionSpec(self.tp_axis, self.fsdp_axis)

    def ffn_down(self):
        """FFN down-projection: fsdp storage only (row-parallel tp
        would split the contraction and change reduction order)."""
        return PartitionSpec(self.fsdp_axis, None)

    def layer_norm(self):
        """1-D scale/shift vectors: fsdp storage."""
        return PartitionSpec(self.fsdp_axis)

    def bias(self):
        """1-D biases: fsdp storage (tiny ones downgrade via
        MXNET_SHARD_FSDP_MIN_SIZE)."""
        return PartitionSpec(self.fsdp_axis)

    def replicated(self):
        return PartitionSpec()

    def activations(self):
        """(batch, seq, d_model) activations: batch over data, model
        dim over tp (used by with_sharding_constraint hints, not by
        the parameter table)."""
        return PartitionSpec(self.data_axis, None, self.tp_axis)

    def fallback(self, ndim=None):
        """Everything else: replicated-or-fsdp (dim 0 over fsdp when
        the tensor has dims; scalars replicate)."""
        if not ndim:
            return PartitionSpec()
        return PartitionSpec(self.fsdp_axis,
                             *([None] * (ndim - 1)))


DEFAULT_LAYOUT = SpecLayout()

# (glob over the parameter name, SpecLayout role method). Checked in
# order, first match wins — more specific globs go first.
DEFAULT_RULES = (
    ("*embed*_weight", "embeddings"),
    ("*_qkv_weight", "qkv_projection"),
    ("*_query_weight", "qkv_projection"),
    ("*_key_weight", "qkv_projection"),
    ("*_value_weight", "qkv_projection"),
    ("*_attn_out_weight", "output_projection"),
    ("*_head_weight", "output_projection"),
    ("*_w1_weight", "ffn_up"),
    ("*_up_weight", "ffn_up"),
    ("*_w2_weight", "ffn_down"),
    ("*_down_weight", "ffn_down"),
    ("*_gamma", "layer_norm"),
    ("*_beta", "layer_norm"),
    ("*_bias", "bias"),
)


def spec_to_str(spec):
    """Serialize a PartitionSpec into the Symbol `__sharding__` string
    syntax (parallel/mesh.py parse_partition_spec round-trips it):
    per-dim entries comma-separated, multi-axis dims joined with '+',
    unsharded dims as 'None'."""
    if spec is None:
        return "None"
    parts = []
    for dim in tuple(spec):
        if dim is None:
            parts.append("None")
        elif isinstance(dim, (tuple, list)):
            parts.append("+".join(str(a) for a in dim))
        else:
            parts.append(str(dim))
    return ",".join(parts) if parts else "None"


def _as_spec(value):
    from ..parallel.mesh import parse_partition_spec

    return parse_partition_spec(value)


def parameter_spec_from_name(param_name, layout=None, overrides=None,
                             ndim=None):
    """Resolve one parameter name to its PartitionSpec through the rule
    table. Returns (spec, explicit): `explicit` is True iff a user
    override matched — explicit specs are enforced (verify_sharding
    rejects non-dividing ones), rule/fallback specs downgrade silently
    in `ShardingPlan.resolve`."""
    layout = layout or DEFAULT_LAYOUT
    if overrides:
        # exact patterns outrank wildcard patterns regardless of
        # insertion order; within each class, insertion order wins
        exact = [(p, s) for p, s in overrides.items()
                 if not any(ch in p for ch in "*?[")]
        globby = [(p, s) for p, s in overrides.items()
                  if any(ch in p for ch in "*?[")]
        for pat, s in exact:
            if pat == param_name:
                return _as_spec(s), True
        for pat, s in globby:
            if fnmatch.fnmatchcase(param_name, pat):
                return _as_spec(s), True
    for pat, role in DEFAULT_RULES:
        if fnmatch.fnmatchcase(param_name, pat):
            return getattr(layout, role)(), False
    return layout.fallback(ndim), False


def rules_digest(layout=None, overrides=None):
    """Stable content hash of one rule configuration (layout axes +
    default table + overrides). Deterministic across processes and
    interpreter runs — it enters the exec-cache key via
    `ShardingPlan.digest`, so it must NOT hash object identities."""
    layout = layout or DEFAULT_LAYOUT
    h = hashlib.sha1()
    h.update(repr((layout.data_axis, layout.fsdp_axis,
                   layout.tp_axis)).encode())
    h.update(repr(DEFAULT_RULES).encode())
    for pat in sorted(overrides or {}):
        h.update(pat.encode())
        h.update(spec_to_str(_as_spec((overrides or {})[pat])).encode())
    return h.hexdigest()

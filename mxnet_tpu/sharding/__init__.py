"""mxnet_tpu.sharding — GSPMD-style named-axis partitioning.

One mesh ("data", "fsdp", "tp"), a rule table resolving parameter
names to PartitionSpecs (spec.py), a ShardingPlan binding mesh + rules
to a concrete Symbol's arg/aux/grad/optimizer-state trees (plan.py),
and jit lowering with in/out_shardings + donate_argnums replacing pmap
(lower.py). Entry points: ``Module(..., sharding=plan)`` /
``Module.bind(..., sharding=plan)`` / ``FeedForward(...,
sharding=plan)``. See docs/sharding.md.
"""
from .spec import (DATA_AXIS, DEFAULT_LAYOUT, DEFAULT_RULES, FSDP_AXIS,
                   TP_AXIS, SpecLayout, parameter_spec_from_name,
                   rules_digest, spec_to_str)
from .plan import ShardingPlan
from .lower import (constrain, device_param_bytes, gather_shardings,
                    jit_sharded, lower_stats, reset_stats)

__all__ = [
    "DATA_AXIS", "FSDP_AXIS", "TP_AXIS",
    "SpecLayout", "DEFAULT_LAYOUT", "DEFAULT_RULES",
    "parameter_spec_from_name", "rules_digest", "spec_to_str",
    "ShardingPlan",
    "jit_sharded", "constrain", "gather_shardings",
    "device_param_bytes", "lower_stats", "reset_stats",
]

"""jit lowering with in/out_shardings + donate_argnums — the pmap
replacement (SNIPPETS.md [1]/[3] pjit idiom).

Every sharded program in the stack lowers through `jit_sharded`: the
fused train step (parallel/dp_step.py), the kvstore('tpu') mesh
barrier, and ad-hoc callers. One chokepoint means ONE place that
guarantees the pmap-free invariants: donation is always threaded
through, meshless calls degrade to plain jit, and every build is
counted (`lower_stats` — the shard tier's retrace gate reads it the
way the exec-cache gates read `execCacheStats`).

All helpers here are hot-path safe (mxlint HOT_PATH_MANIFEST): no
device fetch, no blocking wait — `constrain` dispatches asynchronously
and `device_param_bytes` reads sharding METADATA only.
"""
from __future__ import annotations

import threading

import jax
from jax.sharding import NamedSharding, PartitionSpec

_lock = threading.Lock()
_stats = {"jit_builds": 0, "constraints": 0}


def lower_stats():
    """Snapshot of lowering counters (builds must be zero in steady
    state — each retrace would show up here)."""
    with _lock:
        return dict(_stats)


def reset_stats():
    with _lock:
        for k in _stats:
            _stats[k] = 0


def jit_sharded(fn, in_shardings=None, out_shardings=None,
                donate_argnums=(), static_argnums=None, digest=None,
                kind="sharded"):
    """jax.jit with the sharded-training calling convention. None
    shardings are omitted (meshless fallback = plain jit), donation is
    passed through, and the build is counted.

    `digest` (optional) routes the program through the profiling
    layer's executable accounting under `digest:kind` — the fused
    train step passes its plan digest so sharded executables land in
    `deviceStats` next to the exec-cache ones. Callers that drive the
    AOT protocol themselves (`.lower(...).compile()`) are recorded at
    their compile call; plain callers on first dispatch."""
    kwargs = {}
    if donate_argnums:
        kwargs["donate_argnums"] = tuple(donate_argnums)
    if static_argnums is not None:
        kwargs["static_argnums"] = static_argnums
    if in_shardings is not None:
        kwargs["in_shardings"] = in_shardings
    if out_shardings is not None:
        kwargs["out_shardings"] = out_shardings
    with _lock:
        _stats["jit_builds"] += 1
    jitted = jax.jit(fn, **kwargs)
    if digest:
        try:
            from ..profiling import instrument

            jitted = instrument(jitted, digest=digest, kind=kind,
                                label=getattr(fn, "__name__", None))
        except Exception:
            pass
    return jitted


def constrain(x, mesh, spec=None):
    """Pin `x` to NamedSharding(mesh, spec). Inside a trace this is
    `with_sharding_constraint` (a GSPMD hint compiled into the
    program); on a concrete array it is an async device_put reshard.
    mesh=None is the no-op fallback — callers keep one code path."""
    if mesh is None:
        return x
    sh = spec if isinstance(spec, NamedSharding) else NamedSharding(
        mesh, spec if spec is not None else PartitionSpec())
    with _lock:
        _stats["constraints"] += 1
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sh)
    return jax.device_put(x, sh)


def gather_shardings(plan, param_specs):
    """{name: NamedSharding} of the COMPUTE layout (fsdp axis dropped)
    for every param whose storage spec differs from it — the
    gather-before-use set the fused step pins inside its trace. Empty
    when the plan has no fsdp axis or constraining is disabled."""
    if plan is None or not plan.constrain_compute \
            or not plan.uses_fsdp():
        return {}
    mesh = plan.mesh
    out = {}
    for name, spec in param_specs.items():
        cspec = plan.compute_spec(spec)
        if tuple(cspec) != tuple(spec):
            out[name] = NamedSharding(mesh, cspec)
    return out


def device_param_bytes(params):
    """Per-device bytes of a {name: jax.Array} tree, from sharding
    metadata (shard_shape) — no device traffic. The fsdp acceptance
    gate compares this against the replicated footprint."""
    total = 0
    for v in params.values():
        shape = tuple(v.shape)
        sh = getattr(v, "sharding", None)
        if sh is not None:
            try:
                shape = tuple(sh.shard_shape(shape))
            except Exception:
                pass
        n = 1
        for d in shape:
            n *= int(d)
        total += n * v.dtype.itemsize
    return int(total)

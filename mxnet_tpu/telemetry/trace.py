"""Always-on, overhead-bounded structured tracing over a span ring.

The profiler (mxnet_tpu.profiler) answers "what did the process do
while I was profiling" — it buffers unboundedly and only between
explicit run/stop calls. This layer answers the production question
"what is the process doing RIGHT NOW / what was it doing when it
died": every request and every training step records a handful of
spans into a fixed-size ring buffer, always on, so `/statusz` and the
flight recorder can reconstruct the recent past of a live server
without anyone having arranged a profiling session first.

Overhead contract: one span record is two `time.perf_counter()` reads,
one tuple construction, and one deque append under a lock — no
allocation proportional to history (the ring evicts), no I/O, no
device interaction. `ci/check_telemetry.sh` gates the end-to-end cost
at <= 3% of step time; `MXNET_TELEMETRY_SPANS=0` disables recording
entirely (the A/B arm of that gate).

Correlation: `new_trace_id()` mints a process-unique id; serving
threads it `submit -> enqueue -> batch_flush -> execute -> reply`
(the request's Future carries it as `.trace_id`), and `fit` stamps
per-step ids on its data-wait/dispatch/metric-drain spans. Batch-level
spans cover many requests at once: they carry the member ids in a
`trace_ids` attr, and `spans_for_trace` matches both forms.
"""
from __future__ import annotations

import collections
import itertools
import os
import threading
import time

now = time.perf_counter

_DEFAULT_CAPACITY = 2048


def _env_capacity():
    # registered as MXNET_TELEMETRY_SPANS in mxnet_tpu.utils; read raw
    # here so the ring exists before (and without) the full package
    try:
        return max(0, int(os.environ.get("MXNET_TELEMETRY_SPANS",
                                         _DEFAULT_CAPACITY)))
    except ValueError:
        return _DEFAULT_CAPACITY


_lock = threading.Lock()
_capacity = _env_capacity()
_ring = collections.deque(maxlen=_capacity or 1)
_recorded = 0
_id_counter = itertools.count(1)


class Span:
    """One recorded region: (name, trace_id, begin, end, attrs).
    Times are `time.perf_counter()` seconds (same clock family as the
    profiler's host events)."""

    __slots__ = ("name", "trace_id", "t0", "t1", "attrs")

    def __init__(self, name, trace_id, t0, t1, attrs):
        self.name = name
        self.trace_id = trace_id
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs

    @property
    def duration_us(self):
        return (self.t1 - self.t0) * 1e6

    def covers(self, trace_id):
        if self.trace_id == trace_id:
            return True
        attrs = self.attrs
        return bool(attrs) and trace_id in attrs.get("trace_ids", ())

    def to_dict(self):
        out = {
            "name": self.name,
            "trace_id": self.trace_id,
            "t0_us": round(self.t0 * 1e6, 1),
            "dur_us": round(self.duration_us, 1),
        }
        if self.attrs:
            out["attrs"] = {
                k: (list(v) if isinstance(v, tuple) else v)
                for k, v in self.attrs.items()
            }
        return out


def new_trace_id(prefix="req"):
    """Process-unique correlation id (no RNG, no wall clock: a pid-
    scoped monotonic counter, deterministic under mx.random.seed)."""
    return f"{prefix}-{os.getpid():x}-{next(_id_counter):x}"


def record_span(name, trace_id, t0, t1, attrs=None):
    """Append one finished span to the ring (the single hot-path
    recording chokepoint — listed in mxlint's HOT_PATH_MANIFEST)."""
    global _recorded
    if _capacity <= 0:
        return
    span_obj = Span(name, trace_id, t0, t1, attrs)
    with _lock:
        _ring.append(span_obj)
        _recorded += 1


class span:
    """Context manager recording one region:

        with telemetry.span("serving.execute", trace_id=tid, batch=8):
            ...

    The record decision is latched nowhere — the ring is always on —
    but a zero capacity (MXNET_TELEMETRY_SPANS=0) makes __exit__ a
    no-op."""

    __slots__ = ("name", "trace_id", "attrs", "_t0")

    def __init__(self, name, trace_id=None, **attrs):
        self.name = name
        self.trace_id = trace_id
        self.attrs = attrs or None

    def __enter__(self):
        self._t0 = now()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            attrs = dict(self.attrs or ())
            attrs["error"] = exc_type.__name__
            self.attrs = attrs
        record_span(self.name, self.trace_id, self._t0, now(),
                    self.attrs)
        return False


def recent_spans(n=None):
    """Newest-last list of the ring's spans (all of them by default)."""
    with _lock:
        spans = list(_ring)
    if _capacity <= 0:
        return []
    return spans if n is None else spans[-int(n):]


def spans_for_trace(trace_id):
    """Every retained span carrying this correlation id — directly or
    through a batch-level `trace_ids` attr."""
    return [s for s in recent_spans() if s.covers(trace_id)]


def trace_stats():
    """Ring counters for /statusz and the flight recorder."""
    with _lock:
        retained = len(_ring) if _capacity > 0 else 0
        recorded = _recorded
    return {
        "capacity": _capacity,
        "retained": retained,
        "recorded": recorded,
        "evicted": max(0, recorded - retained),
    }


def span_summary():
    """{name: {count, total_us}} aggregated over the retained ring —
    the compact queryable series bench.py embeds in its JSON."""
    out = {}
    for s in recent_spans():
        agg = out.setdefault(s.name, {"count": 0, "total_us": 0.0})
        agg["count"] += 1
        agg["total_us"] += s.duration_us
    for agg in out.values():
        agg["total_us"] = round(agg["total_us"], 1)
    return out


def set_capacity(n):
    """Resize (and clear) the ring — tests and the overhead A/B gate.
    0 disables recording."""
    global _capacity, _ring, _recorded
    n = max(0, int(n))
    with _lock:
        _capacity = n
        _ring = collections.deque(maxlen=n or 1)
        _recorded = 0


def clear():
    """Drop retained spans, keep capacity."""
    global _recorded
    with _lock:
        _ring.clear()
        _recorded = 0

"""Central metrics registry: counters, gauges, histograms — plus views.

Before this module, every subsystem kept its own counter silo
(`execCacheStats`, `servingStats`, `hostSyncStats`,
`inputPipelineStats`, `graphPassStats`) and the only reader was the
stop-time `dump_profile()`. The registry unifies them behind one
process-wide surface without moving any counter: each silo registers
its existing snapshot function as a *view* (`register_view`), so the
silo keeps owning its lock and its hot-path increments, while every
consumer — `/statusz`, `/metrics`, the flight recorder, the profiler
dump — reads through one place. `dump_profile` output stays
byte-compatible because the view snapshots ARE the legacy snapshot
functions.

Native instruments (Counter / Gauge / Histogram) carry label sets for
the few series the silos do not already cover (e.g. the serving
request-latency histogram). The hot-path cost of an `observe()` is a
dict lookup + bisect + three adds under one small lock — bounded and
allocation-free in steady state; `ci/check_telemetry.sh` enforces the
end-to-end overhead bound.

Stdlib-only: the exporter thread (telemetry.http) renders Prometheus
text and the statusz JSON from here without importing jax.
"""
from __future__ import annotations

import bisect
import threading

_DEFAULT_BUCKETS_MS = (
    0.5, 1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0, 500.0,
    1000.0, 2000.0, 5000.0,
)


def _label_key(labels):
    return tuple(sorted(labels.items())) if labels else ()


def _sanitize(name):
    """Prometheus metric-name characters only ([a-zA-Z0-9_])."""
    out = []
    for ch in str(name):
        out.append(ch if (ch.isalnum() and ch.isascii()) or ch == "_"
                   else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _fmt_labels(pairs):
    if not pairs:
        return ""
    body = ",".join(f'{_sanitize(k)}="{_escape(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _escape(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


class _Instrument:
    """Base: one named metric, one value cell per label set."""

    kind = "untyped"

    def __init__(self, name, help_):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        self._cells = {}

    def snapshot(self):
        """{label-key-tuple: value} — plain numbers for counter/gauge,
        dicts for histograms."""
        with self._lock:
            return {k: self._read_cell(v) for k, v in
                    self._cells.items()}

    def _read_cell(self, cell):
        return cell


class Counter(_Instrument):
    """Monotonic count (requests served, spans dropped, ...)."""

    kind = "counter"

    def inc(self, n=1, **labels):
        key = _label_key(labels)
        with self._lock:
            self._cells[key] = self._cells.get(key, 0) + n

    def value(self, **labels):
        with self._lock:
            return self._cells.get(_label_key(labels), 0)

    def render(self, lines):
        lines.append(f"# TYPE {self.name} counter")
        with self._lock:
            items = sorted(self._cells.items())
        for key, val in items or [((), 0)]:
            lines.append(f"{self.name}{_fmt_labels(key)} {val}")


class Gauge(_Instrument):
    """Point-in-time value; `set_fn` installs a callback read at
    snapshot time (queue depths, ring occupancy)."""

    kind = "gauge"

    def __init__(self, name, help_):
        super().__init__(name, help_)
        self._fn = None

    def set(self, value, **labels):
        with self._lock:
            self._cells[_label_key(labels)] = value

    def set_fn(self, fn):
        self._fn = fn

    def value(self, **labels):
        if self._fn is not None:
            return self._fn()
        with self._lock:
            return self._cells.get(_label_key(labels), 0)

    def render(self, lines):
        lines.append(f"# TYPE {self.name} gauge")
        if self._fn is not None:
            try:
                lines.append(f"{self.name} {self._fn()}")
            except Exception:
                lines.append(f"{self.name} 0")
            return
        with self._lock:
            items = sorted(self._cells.items())
        for key, val in items or [((), 0)]:
            lines.append(f"{self.name}{_fmt_labels(key)} {val}")

    def snapshot(self):
        if self._fn is not None:
            try:
                return {(): self._fn()}
            except Exception:
                return {(): 0}
        return super().snapshot()


class Histogram(_Instrument):
    """Fixed-bound bucketed distribution (latencies). An observe() is
    a bisect into the bound list + sum/count adds — the hot-path cost
    never grows with observation count."""

    kind = "histogram"

    def __init__(self, name, help_, buckets=None):
        super().__init__(name, help_)
        self.bounds = tuple(sorted(buckets or _DEFAULT_BUCKETS_MS))

    def _new_cell(self):
        return {"counts": [0] * (len(self.bounds) + 1),
                "sum": 0.0, "count": 0}

    def observe(self, value, **labels):
        key = _label_key(labels)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            cell = self._cells.get(key)
            if cell is None:
                cell = self._cells[key] = self._new_cell()
            cell["counts"][idx] += 1
            cell["sum"] += value
            cell["count"] += 1

    def _read_cell(self, cell):
        return {"counts": list(cell["counts"]), "sum": cell["sum"],
                "count": cell["count"]}

    def render(self, lines):
        lines.append(f"# TYPE {self.name} histogram")
        with self._lock:
            items = sorted((k, self._read_cell(v))
                           for k, v in self._cells.items())
        for key, cell in items:
            cum = 0
            for bound, n in zip(self.bounds, cell["counts"]):
                cum += n
                pairs = key + (("le", repr(float(bound))),)
                lines.append(
                    f"{self.name}_bucket{_fmt_labels(pairs)} {cum}")
            pairs = key + (("le", "+Inf"),)
            lines.append(
                f"{self.name}_bucket{_fmt_labels(pairs)} "
                f"{cell['count']}")
            lines.append(
                f"{self.name}_sum{_fmt_labels(key)} {cell['sum']}")
            lines.append(
                f"{self.name}_count{_fmt_labels(key)} {cell['count']}")


class _View:
    """One subsystem's registered live snapshot function."""

    __slots__ = ("key", "fn", "prom_prefix", "omit_empty", "label_name")

    def __init__(self, key, fn, prom_prefix, omit_empty, label_name):
        self.key = key
        self.fn = fn
        self.prom_prefix = prom_prefix
        self.omit_empty = omit_empty
        self.label_name = label_name


class MetricsRegistry:
    """Process-wide metric + view table. One default instance
    (`mxnet_tpu.telemetry.REGISTRY`) serves the whole framework."""

    # the profiler's historical dump order — kept stable so the trace
    # JSON's key sequence never churns across releases
    LEGACY_ORDER = (
        "execCacheStats", "servingStats", "hostSyncStats",
        "inputPipelineStats", "graphPassStats",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}
        self._views = {}

    # ------------------------------------------------- native metrics
    def _get_or_create(self, cls, name, help_, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help_, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name, help_=""):
        return self._get_or_create(Counter, name, help_)

    def gauge(self, name, help_=""):
        return self._get_or_create(Gauge, name, help_)

    def histogram(self, name, help_="", buckets=None):
        return self._get_or_create(Histogram, name, help_,
                                   buckets=buckets)

    def metrics_snapshot(self):
        """{name: {rendered-label-string: value}} of native metrics."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            out[m.name] = {
                _fmt_labels(key) or "{}": val
                for key, val in sorted(m.snapshot().items())
            }
        return out

    # ---------------------------------------------------------- views
    def register_view(self, key, fn, prom_prefix=None, omit_empty=False,
                      label_name=None):
        """Register a subsystem snapshot function as a live view.

        `key` is the legacy dump_profile key (e.g. "execCacheStats");
        `prom_prefix` names the flattened Prometheus series
        (mxnet_tpu_<prefix>_<field>); `label_name` declares that the
        snapshot's top level is a {instance: {field: value}} map whose
        keys become that label (the servingStats shape); `omit_empty`
        drops a falsy snapshot from dumps (servingStats with no models
        loaded). Re-registration replaces (module reloads in tests)."""
        with self._lock:
            self._views[key] = _View(
                key, fn, prom_prefix or _sanitize(key), omit_empty,
                label_name)

    def has_view(self, key):
        with self._lock:
            return key in self._views

    def view_snapshot(self, key):
        with self._lock:
            view = self._views.get(key)
        if view is None:
            raise KeyError(f"no telemetry view registered for {key!r}")
        return view.fn()

    def view_items(self, legacy_first=True):
        """[(key, snapshot)] for every registered view, honoring
        omit_empty; legacy keys first in their historical order. A view
        whose snapshot function raises is skipped (a silo must never
        take observability down)."""
        with self._lock:
            views = dict(self._views)
        order = [k for k in self.LEGACY_ORDER if k in views]
        order += [k for k in views if k not in self.LEGACY_ORDER]
        out = []
        for key in order:
            view = views[key]
            try:
                snap = view.fn()
            except Exception:
                continue
            if view.omit_empty and not snap:
                continue
            out.append((key, snap))
        return out

    # ------------------------------------------------------ rendering
    def prometheus_text(self):
        """The whole registry in Prometheus text exposition format:
        native instruments with their true types, view snapshots
        flattened to gauges (numeric leaves only)."""
        lines = []
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
            views = dict(self._views)
        for m in metrics:
            m.render(lines)
        for key in sorted(views):
            view = views[key]
            try:
                snap = view.fn()
            except Exception:
                continue
            self._render_view(lines, view, snap)
        return "\n".join(lines) + "\n"

    @staticmethod
    def _render_view(lines, view, snap):
        base = "mxnet_tpu_" + _sanitize(view.prom_prefix)
        if not isinstance(snap, dict):
            return

        def emit(name, pairs, value):
            if isinstance(value, bool):
                value = int(value)
            if isinstance(value, (int, float)):
                lines.append(f"{name}{_fmt_labels(pairs)} {value}")

        if view.label_name:
            # {instance: {field: value}} — instances become a label.
            # View samples carry no TYPE line (untyped is valid
            # exposition format; one TYPE would misname the family).
            for inst, fields in sorted(snap.items()):
                if not isinstance(fields, dict):
                    continue
                for field, value in sorted(fields.items()):
                    if value is None:
                        continue
                    emit(f"{base}_{_sanitize(field)}",
                         ((view.label_name, inst),), value)
            return
        for field, value in sorted(snap.items()):
            if value is None:
                continue
            if isinstance(value, dict):
                # one nested level ({pass: micros}) -> a "key" label
                for sub, subval in sorted(value.items()):
                    emit(f"{base}_{_sanitize(field)}",
                         (("key", sub),), subval)
            else:
                emit(f"{base}_{_sanitize(field)}", (), value)


#: the process-wide default registry every silo registers into
REGISTRY = MetricsRegistry()

counter = REGISTRY.counter
gauge = REGISTRY.gauge
histogram = REGISTRY.histogram
register_view = REGISTRY.register_view
has_view = REGISTRY.has_view
view_snapshot = REGISTRY.view_snapshot
view_items = REGISTRY.view_items
prometheus_text = REGISTRY.prometheus_text

"""Live telemetry endpoints: /metrics, /statusz, /healthz.

A stop-time `dump_profile()` cannot answer "what is this stuck fit /
loaded server doing right now". This exporter is the live window:
opt-in via MXNET_TELEMETRY_PORT=<port> (0 picks an ephemeral port), a
single daemon thread runs a stdlib ThreadingHTTPServer serving

  /metrics   Prometheus text exposition — native instruments with
             their true types plus every registered subsystem view
             flattened to gauges (scrape target for the autoscaling
             signals ROADMAP items 1/5 need: queue depth, p99, qps)
  /statusz   one JSON snapshot: every registered view under its
             legacy dump_profile key, native metrics, span-ring
             counters, process info
  /healthz   200 "ok" liveness probe

Attachment points: `serving.ModelServer.__init__` and
`BaseModule.fit` both call `maybe_start_exporter()`, so setting the
env var is the only step for either workload. Stdlib-only — the
handler never imports jax and never touches device state, so a scrape
cannot stall the dispatch pipeline.
"""
from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import registry as _registry
from . import trace as _trace

_t_start = time.perf_counter()


def statusz():
    """The one-call process snapshot: every registered subsystem view
    (legacy dump_profile keys at top level), native metrics, and span
    counters."""
    out = {
        "pid": os.getpid(),
        "uptime_s": round(time.perf_counter() - _t_start, 3),
    }
    for key, snap in _registry.view_items():
        out[key] = snap
    out["telemetry"] = {
        "spans": _trace.trace_stats(),
        "metrics": _registry.REGISTRY.metrics_snapshot(),
    }
    return out


class TelemetryHandler(BaseHTTPRequestHandler):
    """GET-only handler over the registry — no device access, no
    mutation (listed in mxlint's HOT_PATH_MANIFEST: a scrape must
    never sync the host with the device)."""

    server_version = "mxnet-tpu-telemetry/1.0"

    def do_GET(self):
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            self._reply(200, "text/plain; charset=utf-8", "ok\n")
        elif path == "/metrics":
            self._reply(200, "text/plain; version=0.0.4; charset=utf-8",
                        _registry.prometheus_text())
        elif path == "/statusz":
            self._reply(200, "application/json",
                        json.dumps(statusz(), default=str))
        else:
            self._reply(404, "text/plain; charset=utf-8",
                        "not found (try /metrics /statusz /healthz)\n")

    def _reply(self, code, ctype, body):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, format, *args):  # noqa: A002 - stdlib name
        pass  # a scrape per second must not spam stderr


class Exporter:
    """One HTTP server + daemon thread. `port` reflects the actual
    bound port (useful with port 0)."""

    def __init__(self, port, host="0.0.0.0"):
        self._server = ThreadingHTTPServer((host, int(port)),
                                           TelemetryHandler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name=f"telemetry-exporter-{self.port}", daemon=True)
        self._thread.start()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


_exporter_lock = threading.Lock()
_exporter = None


def start_exporter(port=None, host="127.0.0.1"):
    """Start (or return) the process's exporter. Explicit-port calls
    with a different port raise — one process, one telemetry port."""
    global _exporter
    with _exporter_lock:
        if _exporter is not None:
            if port is not None and int(port) not in (0, _exporter.port):
                raise RuntimeError(
                    f"telemetry exporter already on port "
                    f"{_exporter.port}, refusing to also bind {port}")
            return _exporter
        if port is None:
            raw = os.environ.get("MXNET_TELEMETRY_PORT", "")
            if not raw.strip():
                return None
            port = int(raw)
        _exporter = Exporter(port, host=host)
        return _exporter


def maybe_start_exporter():
    """Idempotent opt-in hook: starts the exporter iff
    MXNET_TELEMETRY_PORT is set. Called from serving.ModelServer and
    BaseModule.fit; returns the exporter or None. Never raises — a
    bad port must not take down training."""
    try:
        return start_exporter(port=None)
    except Exception:
        return None


def exporter_port():
    """The running exporter's bound port, or None — the way to learn
    the ephemeral port MXNET_TELEMETRY_PORT=0 chose."""
    with _exporter_lock:
        return _exporter.port if _exporter is not None else None


def stop_exporter():
    """Shut the process exporter down (tests / clean unload)."""
    global _exporter
    with _exporter_lock:
        exp, _exporter = _exporter, None
    if exp is not None:
        exp.stop()

"""mxnet_tpu.telemetry — unified observability for a live process.

Four pieces (docs/observability.md):

  registry   central metrics registry (Counter/Gauge/Histogram with
             labels) + *views*: the five existing stat silos
             (execCacheStats, servingStats, hostSyncStats,
             inputPipelineStats, graphPassStats) register their
             snapshot functions here, so every consumer reads the
             SAME live counters the profiler dump embeds.
  trace      always-on structured tracing: `span()` over a fixed-size
             ring buffer with correlation ids threaded through
             serving (submit -> enqueue -> batch_flush -> execute ->
             reply; the request Future carries `.trace_id`) and
             through fit (per-step data-wait / dispatch /
             metric-drain spans).
  http       opt-in stdlib exporter thread (MXNET_TELEMETRY_PORT):
             /metrics (Prometheus text), /statusz (one JSON snapshot
             of everything), /healthz.
  flight     crash flight recorder (MXNET_TELEMETRY_FLIGHT_DIR):
             last-N spans + full registry snapshot dumped atomically
             on unhandled exceptions and FaultInjector trips.

Stdlib-only by design: nothing here imports jax, so a scrape, a span
record, or a crash dump can never add a host<->device sync (mxlint's
MX001 polices the hot paths statically).
"""
from __future__ import annotations

from . import registry
from . import trace
from . import http
from . import flight
from .registry import (
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    counter,
    gauge,
    has_view,
    histogram,
    prometheus_text,
    register_view,
    view_items,
    view_snapshot,
)
from .trace import (
    Span,
    new_trace_id,
    recent_spans,
    record_span,
    span,
    span_summary,
    spans_for_trace,
    trace_stats,
)
from .http import (
    Exporter,
    exporter_port,
    maybe_start_exporter,
    start_exporter,
    statusz,
    stop_exporter,
)
from .flight import dump_flight_record, flight_record, maybe_dump

# crash hooks chain the previous handlers and no-op until
# MXNET_TELEMETRY_FLIGHT_DIR is set — free to install eagerly
flight.install()


def bench_snapshot():
    """Compact queryable telemetry series for bench.py JSON output."""
    return {
        "spans": trace_stats(),
        "span_summary": span_summary(),
    }

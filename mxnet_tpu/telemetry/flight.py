"""Crash flight recorder: "what was the process doing when it died".

A production fleet's hardest bugs end a process: an unhandled
exception deep in a worker thread, a fault-injection trip in a soak
test, an OOM-adjacent crash. By the time anyone attaches a debugger
the evidence is gone. The flight recorder freezes it at the moment of
death: the span ring's recent history (every request / step the
process was working on) plus a full registry snapshot (all five
subsystem counter silos, native metrics) into one JSON file, written
atomically (tmp + os.replace — the tuner-cache pattern) so a crash
mid-dump never leaves a torn file.

Enablement: MXNET_TELEMETRY_FLIGHT_DIR=<dir>. When set,
  - `install()` (done at mxnet_tpu.telemetry import) chains
    sys.excepthook + threading.excepthook so ANY unhandled exception
    dumps before the interpreter unwinds;
  - `fault.FaultInjector` dumps right before raising its simulated
    failure, so resilience soaks leave a readable record per trip.
When unset every entry point is a cheap no-op.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import traceback

from . import http as _http
from . import trace as _trace

_seq = itertools.count(1)
_dump_lock = threading.Lock()


def flight_dir():
    # registered as MXNET_TELEMETRY_FLIGHT_DIR in mxnet_tpu.utils
    return os.environ.get("MXNET_TELEMETRY_FLIGHT_DIR", "").strip()


def enabled():
    return bool(flight_dir())


def flight_record(reason, exc=None, extra=None):
    """The record itself (pure build, no I/O): reason, wall time,
    exception traceback when given, last-N spans, full statusz.
    `extra` is a caller-supplied JSON-able dict attached verbatim under
    "extra" (e.g. numerics anomaly context — mxnet_tpu.numerics)."""
    rec = {
        "reason": reason,
        "pid": os.getpid(),
        "time_unix": time.time(),
        "argv": list(sys.argv),
        "spans": [s.to_dict() for s in _trace.recent_spans()],
        "stats": _http.statusz(),
    }
    if extra is not None:
        rec["extra"] = extra
    if exc is not None:
        rec["exception"] = {
            "type": type(exc).__name__,
            "message": str(exc),
            "traceback": "".join(traceback.format_exception(
                type(exc), exc, exc.__traceback__)),
        }
    return rec


def dump_flight_record(reason, exc=None, path=None, extra=None):
    """Write the record atomically; returns the path. Explicit `path`
    overrides the env dir (programmatic dumps)."""
    if path is None:
        d = flight_dir()
        if not d:
            raise RuntimeError(
                "flight recorder disabled: set MXNET_TELEMETRY_FLIGHT_"
                "DIR or pass path=")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(
            d, f"flight-{os.getpid()}-{next(_seq)}.json")
    with _dump_lock:
        # the lock serializes snapshot capture + rendering (two
        # crashing threads each get a coherent record); the slow part
        # — the disk write — happens OUTSIDE it, so one thread's dump
        # never stalls behind another's fsync-speed I/O
        rec = flight_record(reason, exc=exc, extra=extra)
        payload = json.dumps(rec, default=str)
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)  # atomic: never a torn record
    return path


def maybe_dump(reason, exc=None, extra=None):
    """Best-effort dump iff enabled; never raises (called from
    excepthooks and the fault injector's raise path)."""
    if not enabled():
        return None
    try:
        return dump_flight_record(reason, exc=exc, extra=extra)
    except Exception:
        return None


# ------------------------------------------------------------- hooks
_installed = False
_prev_excepthook = None
_prev_threading_hook = None


def _sys_hook(exc_type, exc, tb):
    if exc_type not in (KeyboardInterrupt, SystemExit):
        if exc is not None and exc.__traceback__ is None:
            exc = exc.with_traceback(tb)
        maybe_dump("unhandled_exception", exc=exc)
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def _thread_hook(args):
    if args.exc_type not in (KeyboardInterrupt, SystemExit):
        maybe_dump(
            f"unhandled_exception_in_thread:"
            f"{getattr(args.thread, 'name', '?')}",
            exc=args.exc_value)
    if _prev_threading_hook is not None:
        _prev_threading_hook(args)


def install():
    """Chain the crash hooks once (idempotent). The hooks are no-ops
    while MXNET_TELEMETRY_FLIGHT_DIR is unset, so installing at import
    costs nothing."""
    global _installed, _prev_excepthook, _prev_threading_hook
    if _installed:
        return
    _installed = True
    _prev_excepthook = sys.excepthook
    sys.excepthook = _sys_hook
    _prev_threading_hook = threading.excepthook
    threading.excepthook = _thread_hook

"""Run event log: an append-only JSONL record of one training run.

The durable half of run health: every drained sentinel row becomes one
line ({"event": "step", step, loss, lr, grad_norm, ...}), anomalies
and epoch boundaries get their own lines, and a restarted run appends
a {"event": "resume"} marker instead of truncating — so the file reads
as the full history across preemptions, the signal ROADMAP item 5's
elastic control plane needs to tell divergence from preemption.

Durability model (the mxnet_tpu.data tiny-state pattern): each line is
one `write()` + `flush()`, and `open()` repairs a torn trailing line
(a kill mid-write) by truncating to the last complete line before
appending. Readers (`read_events`) tolerate a torn tail too, so the
log is usable even while a crashed writer's file is being inspected.
"""
from __future__ import annotations

import json
import os
import time


def read_events(path):
    """Parse every complete JSONL event; a torn trailing line (crash
    mid-write) is skipped, never fatal."""
    events = []
    if not os.path.exists(path):
        return events
    with open(path, "rb") as f:
        data = f.read()
    for line in data.split(b"\n"):
        if not line.strip():
            continue
        try:
            events.append(json.loads(line))
        except ValueError:
            continue  # torn tail
    return events


class RunEventLog:
    def __init__(self, path):
        self.path = path
        self._f = None

    # ------------------------------------------------------- lifecycle
    def open(self, context=None):
        """Open for append, repairing a torn trailing line first. When
        the file already holds events, a `resume` marker (with the last
        recorded step) is appended — the run continues the same record.
        `context` merges extra fields into the start/resume marker."""
        if self._f is not None:
            return self
        resumed_from = None
        if os.path.exists(self.path):
            self._repair_tail()
            prior = read_events(self.path)
            if prior:
                steps = [e.get("step") for e in prior
                         if e.get("event") == "step"]
                resumed_from = max(steps) if steps else 0
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "a")
        marker = {
            "event": "resume" if resumed_from is not None else "start",
            "pid": os.getpid(),
            "time_unix": time.time(),
        }
        if resumed_from is not None:
            marker["last_step"] = resumed_from
        if context:
            marker.update(context)
        self.append(marker)
        return self

    def _repair_tail(self):
        """Truncate a torn (kill-mid-write) trailing line so the append
        stream stays line-aligned."""
        with open(self.path, "rb") as f:
            data = f.read()
        if not data or data.endswith(b"\n"):
            return
        cut = data.rfind(b"\n") + 1
        with open(self.path, "r+b") as f:
            f.truncate(cut)

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None

    # --------------------------------------------------------- writing
    def append(self, event):
        """One event -> one line -> one flush. A crash between lines
        loses at most the in-flight event; `open()`/`read_events`
        absorb a crash mid-line."""
        if self._f is None:
            self.open()
        self._f.write(json.dumps(event, default=str) + "\n")
        self._f.flush()

    def step(self, step, row, lr=None):
        """Record one drained sentinel row."""
        ev = {
            "event": "step", "step": int(step),
            "loss": row.get("loss"), "grad_norm": row.get("grad_norm"),
            "param_norm": row.get("param_norm"),
            "update_ratio": row.get("update_ratio"),
            "out_nonfinite": row.get("out_nonfinite"),
            "grad_nonfinite": row.get("grad_nonfinite"),
            "param_nonfinite": row.get("param_nonfinite"),
        }
        if lr is not None:
            ev["lr"] = float(lr)
        self.append(ev)

    def anomaly(self, anom, first_bad_op=None):
        ev = {"event": "anomaly", **anom.to_dict()}
        if first_bad_op is not None:
            ev["first_bad_op"] = first_bad_op
        self.append(ev)

    def epoch(self, epoch, metrics=None):
        ev = {"event": "epoch", "epoch": int(epoch)}
        if metrics:
            pairs = metrics.items() if hasattr(metrics, "items") \
                else metrics
            ev["metrics"] = {k: float(v) for k, v in pairs}
        self.append(ev)

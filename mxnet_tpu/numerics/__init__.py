"""mxnet_tpu.numerics — device-resident training-run health.

Answers the question that pages people on a training fleet: "the loss
went NaN at step 40k — which op, which step, what did the norms look
like before it". Three layers:

  sentinel     per-step stats row computed INSIDE the fused train step
               (sentinel.py; FusedTrainStep.enable_sentinel), drained
               in ONE device_get per log interval — zero new
               steady-state host syncs
  rules        EWMA spike / nonfinite / dead / exploding-group
               detection over drained rows (rules.py), with first-bad-
               op attribution through the executor's eager monitored
               pass on a nonfinite trip (attribution.py), dumped into
               a crash flight record (telemetry/flight.py)
  run log      append-only JSONL record of the run (runlog.py) plus
               the `numericsStats` telemetry view / Prometheus gauges
               (stats.py)

`NumericsMonitor` is the facade `fit` drives: enabled explicitly
(``mod.fit(..., numerics=NumericsMonitor(...))``) or ambiently via
``MXNET_NUMERICS=1`` (knobs: ``MXNET_NUMERICS_INTERVAL``, ``_HISTORY``,
``_RUNLOG``, ``_SPIKE``, ``_ATTRIBUTION`` — docs/observability.md
"Run health").
"""
from __future__ import annotations

import logging
from collections import deque

from .. import utils as _utils
from ..telemetry import flight as _flight
from . import attribution as _attribution
from . import rules as _rules
from . import runlog as _runlog
from . import sentinel as _sentinel
from . import stats as _stats
from .rules import AnomalyDetector, NumericsAnomaly
from .runlog import RunEventLog, read_events
from .sentinel import SentinelSpec

__all__ = [
    "NumericsMonitor", "NumericsAnomaly", "AnomalyDetector",
    "SentinelSpec", "RunEventLog", "read_events", "from_fit_arg",
]


class NumericsMonitor:
    """Run-health driver for one training run.

    Attach to a Module after its optimizer is initialized (fit does
    this); per batch, `note_batch` keeps the step inputs for
    attribution (a reference — zero copies, zero syncs) and
    `after_batch` drains the device-side sentinel rows every
    `interval` steps. `interval <= 0` drains only at epoch ends.
    """

    def __init__(self, interval=None, history=None, run_log=None,
                 spike=None, attribution=None, detector=None,
                 logger=None):
        self.interval = (int(interval) if interval is not None
                         else _utils.getenv("MXNET_NUMERICS_INTERVAL"))
        hist = (int(history) if history is not None
                else _utils.getenv("MXNET_NUMERICS_HISTORY"))
        if run_log is None:
            run_log = _utils.getenv("MXNET_NUMERICS_RUNLOG") or None
        self.attribution = (
            bool(attribution) if attribution is not None
            else _utils.getenv("MXNET_NUMERICS_ATTRIBUTION"))
        if detector is None:
            spike = (float(spike) if spike is not None
                     else float(_utils.getenv("MXNET_NUMERICS_SPIKE")))
            detector = _rules.AnomalyDetector(spike=spike)
        self.detector = detector
        self.logger = logger or logging.getLogger("mxnet_tpu.numerics")
        self.history = deque(maxlen=max(1, hist))
        self.anomalies = []
        self.run_log = _runlog.RunEventLog(run_log) if run_log else None
        self._module = None
        self._last_batch = None
        self._active = False

    # ------------------------------------------------------- lifecycle
    def attach(self, module):
        """Enable the sentinel on the module's fused step and open the
        run log. Inert (with a warning) when the module has no fused
        train path — the sentinel lives inside that jit."""
        ensure = getattr(module, "_ensure_sentinel", None)
        spec = ensure() if ensure is not None else None
        if spec is None:
            self.logger.warning(
                "numerics: module has no fused train step (eager "
                "binding?) — sentinel disabled for this run")
            self._active = False
            return self
        self._module = module
        self._active = True
        if self.run_log is not None:
            self.run_log.open()
        return self

    @property
    def active(self):
        return self._active

    # -------------------------------------------------------- hot path
    def note_batch(self, batch):
        """Keep THIS batch as the attribution replay input. Reference
        only — no copy, no device touch (fit's per-step path)."""
        self._last_batch = batch

    def after_batch(self, module, epoch=0, nbatch=0):
        """Interval check on the fit hot path: drains (one non-blocking
        fetch) only when the fused step counter crosses the interval."""
        if not self._active:
            return
        fs = getattr(module, "_fused_step", None)
        if fs is None or fs._sentinel is None:
            return
        if self.interval > 0 and fs._t and fs._t % self.interval == 0:
            # non-blocking: completed rows only, never a pipeline stall
            self.drain(module, wait=False)

    # ----------------------------------------------------------- drain
    def drain(self, module=None, epoch=None, metrics=None, wait=True):
        """Fetch pending sentinel rows (ONE device_get), run the rules,
        log, and — on a nonfinite trip — attribute and flight-dump.
        `wait=False` fetches only rows already complete on device (the
        hot-path interval drain); the default blocks for everything."""
        module = module or self._module
        if not self._active or module is None:
            return []
        fs = getattr(module, "_fused_step", None)
        if fs is None or fs._sentinel is None:
            return []
        spec = fs._sentinel
        drained = fs.drain_sentinel(wait=wait)
        new_anomalies = []
        for t, lr, raw in drained:
            row = spec.decode_row(raw)
            self.history.append({"step": int(t), "lr": float(lr), **row})
            _stats.note_row(t, row, lr=lr)
            if self.run_log is not None:
                self.run_log.step(t, row, lr=lr)
            new_anomalies.extend(self.detector.observe(t, row))
        for anom in new_anomalies:
            self._handle_anomaly(module, anom)
        if epoch is not None and self.run_log is not None:
            self.run_log.epoch(epoch, metrics)
        return new_anomalies

    def _handle_anomaly(self, module, anom):
        culprit = None
        if anom.kind == "nonfinite" and self.attribution:
            culprit = _attribution.attribute(module, self._last_batch)
        self.anomalies.append(anom)
        self.logger.warning(
            "numerics anomaly: %s%s", anom.message,
            f" — first bad op: {culprit}" if culprit else "")
        _stats.note_anomaly(anom, first_bad_op=culprit)
        if self.run_log is not None:
            self.run_log.anomaly(anom, first_bad_op=culprit)
        # the crash-flight payload: the anomaly, the culprit, and the
        # last-K sentinel rows leading up to it — everything the 3am
        # page needs, durable before anything else can fall over
        _flight.maybe_dump(
            f"numerics:{anom.kind}",
            extra={"numerics": {
                "anomaly": anom.to_dict(),
                "first_bad_op": culprit,
                "recent_rows": [
                    {k: v for k, v in r.items() if k != "groups"}
                    for r in list(self.history)],
            }})

    def close(self):
        if self.run_log is not None:
            self.run_log.close()


def from_fit_arg(arg, logger=None):
    """Resolve fit's `numerics=` argument: a NumericsMonitor passes
    through, True builds one, None consults MXNET_NUMERICS, False
    disables."""
    if isinstance(arg, NumericsMonitor):
        return arg
    if arg is True:
        return NumericsMonitor(logger=logger)
    if arg is None and _utils.getenv("MXNET_NUMERICS"):
        return NumericsMonitor(logger=logger)
    return None

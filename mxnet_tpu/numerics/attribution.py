"""First-bad-op attribution: name the op where the NaN was born.

When a sentinel row trips the nonfinite rule, the poisoned values are
already in the parameters/activations — the question "which op" is
answerable by replaying one batch through the executor's EAGER
monitored pass (`Executor._forward_monitored`, the reference's
MXExecutorSetMonitorCallback surface): every node output flows through
a callback in topological order, so the FIRST non-finite output names
the op. The replay is a cold path (one batch, per-op host checks, runs
only after an anomaly), so its per-op syncs are deliberate and cheap
relative to the page it answers.
"""
from __future__ import annotations


class _FoundBadOp(Exception):
    """Early exit from the monitored pass once the culprit is known."""

    def __init__(self, name):
        super().__init__(name)
        self.name = name


def first_bad_op(executor, is_train=True):
    """Replay the executor's CURRENT bound inputs through the eager
    monitored pass; return the name of the first node output holding a
    NaN/Inf (e.g. ``fc1_output``), or None when the replay is clean.

    The caller must have loaded the offending batch into the
    executor's arg arrays (and flushed fused params back) first."""
    import jax
    import jax.numpy as jnp

    def check(name, nd_arr):
        v = nd_arr._data
        if not jnp.issubdtype(v.dtype, jnp.floating):
            return
        if bool(jax.device_get(jnp.any(~jnp.isfinite(v)))):
            raise _FoundBadOp(name)

    arg_vals, aux_vals = executor._gather_inputs()
    prev = executor._monitor_callback
    executor._monitor_callback = check
    try:
        executor._forward_monitored(
            is_train, executor._rng, arg_vals, aux_vals)
    except _FoundBadOp as hit:
        return hit.name
    finally:
        executor._monitor_callback = prev
    return None


def attribute(module, batch=None):
    """Module-level entry: flush fused params back to the executors,
    load `batch` (the saved step inputs; optional when the executor
    already holds them), and bisect. Returns the culprit op-output name
    or None. Never raises — attribution is advisory."""
    try:
        flush = getattr(module, "_flush_fused", None)
        if flush is not None:
            module._fused_dirty = True  # force: params live in the step
            flush()
        exe = module._exec_group.execs[0]
        if batch is not None:
            names = [n for n, _s in module._exec_group.data_shapes]
            for name, arr in zip(names, batch.data):
                exe.arg_dict[name][:] = arr
            if batch.label:
                lnames = [n for n, _s in
                          (module._exec_group.label_shapes or [])]
                for name, arr in zip(lnames, batch.label):
                    if name in exe.arg_dict:
                        exe.arg_dict[name][:] = arr
        return first_bad_op(exe, is_train=True)
    except Exception:
        return None

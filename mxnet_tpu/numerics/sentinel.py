"""Sentinel engine: per-step run-health statistics computed INSIDE the
fused train step.

The reference's only numeric introspection is the monitor's eager
per-tensor callback (python/mxnet/monitor.py) — one host sync per
tensor per batch, unusable in the pipelined fit. The sentinel inverts
that: a fixed vector of scalars (loss, NaN/Inf counts, per-param-group
gradient/parameter/update norms) is computed at trace time inside the
step jit, so the stats ride the existing dispatch for free. Rows
accumulate device-side (FusedTrainStep keeps the jax arrays, never
reading them) and drain in ONE `jax.device_get` per log interval —
the PR 3 device-metric discipline (metric.py _drain_pending) applied
to run health.

Sharding: every column is a full reduction (sum / max over a whole
parameter or gradient), so under a `ShardingPlan` GSPMD lowers them to
psum/pmax across the fsdp/tp axes inside the trace and the row comes
out replicated — sharded and unsharded runs produce the same row
(tests/test_numerics.py sharded-parity case).

Column layout (all float32):

  [0] loss           mean of the first head output (the framework's
                     loss proxy — SoftmaxOutput/LinearRegressionOutput
                     heads emit per-row losses through out 0's vjp)
  [1] out_nonfinite  NaN/Inf count across every head output
  then, per param group g (derived by stripping weight/bias/gamma/...
  suffixes, so `fc1_weight` and `fc1_bias` share group `fc1`):
  grad_norm_sq, grad_max_abs, grad_nonfinite,
  param_norm_sq, param_nonfinite, update_norm_sq

Global grad norm, update/param ratio etc. are derived HOST-side at
drain time from the per-group sums (`decode_row`) — the device row
stays minimal.
"""
from __future__ import annotations

import functools
import math

# Suffixes that group a parameter under its layer (the reference's
# naming convention: <layer>_<kind>); longest-match first.
GROUP_SUFFIXES = (
    "_moving_mean", "_moving_var", "_weight", "_bias", "_gamma",
    "_beta",
)

HEAD_COLS = ("loss", "out_nonfinite")
GROUP_COLS = (
    "grad_norm_sq", "grad_max_abs", "grad_nonfinite",
    "param_norm_sq", "param_nonfinite", "update_norm_sq",
)


def group_of(name):
    """Param group of one parameter name (suffix stripped)."""
    for suf in GROUP_SUFFIXES:
        if name.endswith(suf) and len(name) > len(suf):
            return name[: -len(suf)]
    return name


class SentinelSpec:
    """Fixed column layout + the traceable row function for one model.

    `trainable` fixes the group set and the iteration order (trace-time
    python, so the order is baked into the jit); params outside
    `trainable` carry no gradient and are excluded — frozen weights
    cannot diverge.
    """

    def __init__(self, trainable):
        self.trainable = tuple(trainable)
        groups = {}
        for n in self.trainable:
            groups.setdefault(group_of(n), []).append(n)
        self.groups = {g: tuple(ns) for g, ns in groups.items()}
        self.columns = tuple(HEAD_COLS) + tuple(
            f"{g}/{c}" for g in self.groups for c in GROUP_COLS)

    @property
    def width(self):
        return len(self.columns)

    # ------------------------------------------------------ trace time
    def compute(self, outs, params, new_params, grads):
        """The sentinel row, as trace-time jnp — called from inside the
        fused step body with that step's forward outputs, pre-update
        params, post-update params, and gradients. Pure reductions:
        under GSPMD every sum/max lowers to in-trace collectives and
        the row replicates."""
        import jax.numpy as jnp

        f32 = jnp.float32

        def nonfinite(x):
            if not jnp.issubdtype(x.dtype, jnp.floating):
                return jnp.zeros((), f32)
            return jnp.sum(~jnp.isfinite(x)).astype(f32)

        cols = [
            jnp.mean(outs[0]).astype(f32),
            functools.reduce(jnp.add, [nonfinite(o) for o in outs]),
        ]
        for names in self.groups.values():
            gs = [grads[n].astype(f32) for n in names]
            ps = [params[n].astype(f32) for n in names]
            us = [(new_params[n].astype(f32) - params[n].astype(f32))
                  for n in names]
            add = functools.reduce(jnp.add, [
                jnp.sum(jnp.square(g)) for g in gs])
            gmax = functools.reduce(jnp.maximum, [
                jnp.max(jnp.abs(g)) for g in gs])
            cols += [
                add,
                gmax.astype(f32),
                functools.reduce(jnp.add, [nonfinite(g) for g in gs]),
                functools.reduce(jnp.add, [
                    jnp.sum(jnp.square(p)) for p in ps]),
                functools.reduce(jnp.add, [nonfinite(p) for p in ps]),
                functools.reduce(jnp.add, [
                    jnp.sum(jnp.square(u)) for u in us]),
            ]
        return jnp.stack(cols).astype(f32)

    # ------------------------------------------------------ drain time
    def decode_row(self, row):
        """Host row (1-D, width `self.width`) -> structured dict with
        the derived globals the anomaly rules consume."""
        vals = [float(v) for v in row]
        d = {"loss": vals[0], "out_nonfinite": vals[1], "groups": {}}
        gsq = psq = usq = 0.0
        gnf = pnf = 0.0
        for i, g in enumerate(self.groups):
            base = len(HEAD_COLS) + i * len(GROUP_COLS)
            seg = dict(zip(GROUP_COLS, vals[base:base + len(GROUP_COLS)]))
            d["groups"][g] = {
                "grad_norm": math.sqrt(max(seg["grad_norm_sq"], 0.0))
                if math.isfinite(seg["grad_norm_sq"]) else
                seg["grad_norm_sq"],
                "grad_max_abs": seg["grad_max_abs"],
                "grad_nonfinite": seg["grad_nonfinite"],
                "param_norm": math.sqrt(max(seg["param_norm_sq"], 0.0))
                if math.isfinite(seg["param_norm_sq"]) else
                seg["param_norm_sq"],
                "param_nonfinite": seg["param_nonfinite"],
                "update_norm": math.sqrt(max(seg["update_norm_sq"], 0.0))
                if math.isfinite(seg["update_norm_sq"]) else
                seg["update_norm_sq"],
            }
            gsq += seg["grad_norm_sq"]
            psq += seg["param_norm_sq"]
            usq += seg["update_norm_sq"]
            gnf += seg["grad_nonfinite"]
            pnf += seg["param_nonfinite"]
        d["grad_norm"] = (math.sqrt(max(gsq, 0.0))
                          if math.isfinite(gsq) else gsq)
        d["param_norm"] = (math.sqrt(max(psq, 0.0))
                           if math.isfinite(psq) else psq)
        d["update_norm"] = (math.sqrt(max(usq, 0.0))
                            if math.isfinite(usq) else usq)
        d["update_ratio"] = (
            d["update_norm"] / d["param_norm"]
            if d["param_norm"] and math.isfinite(d["param_norm"])
            else 0.0)
        d["grad_nonfinite"] = gnf
        d["param_nonfinite"] = pnf
        return d

"""Anomaly rules over drained sentinel rows.

Pure host-side logic (numpy-free, device-free): a drained row dict
(`SentinelSpec.decode_row`) goes in, zero or more structured
`NumericsAnomaly` records come out. The detector is deliberately
stateful-but-tiny — one EWMA float, one consecutive-zero counter per
param group — so it serializes trivially alongside the run event log.

Rules:

  nonfinite      any NaN/Inf in outputs, gradients, or parameters.
                 The page-at-3am rule: trips attribution + flight dump.
  grad_spike     global grad norm > `spike` x its EWMA (after a short
                 warmup so init noise doesn't trip it).
  dead_group     a param group's grad norm is exactly 0.0 for
                 `dead_after` consecutive drained rows — a detached
                 subgraph or a saturated activation. Fires once per
                 group until the group revives.
  exploding_group a group's update/param ratio above `explode` — the
                 update is rewriting the weights wholesale, the usual
                 prelude to divergence.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field


@dataclass
class NumericsAnomaly:
    kind: str            # nonfinite | grad_spike | dead_group | exploding_group
    step: int            # optimizer step of the offending row
    message: str
    value: float = 0.0   # the measured quantity that tripped
    threshold: float = 0.0
    group: str = ""      # param group, for the group-scoped rules
    detail: dict = field(default_factory=dict)

    def to_dict(self):
        return {
            "kind": self.kind, "step": self.step,
            "message": self.message, "value": self.value,
            "threshold": self.threshold, "group": self.group,
            "detail": dict(self.detail),
        }


class AnomalyDetector:
    """Applies the rule set row-by-row; `observe` returns the anomalies
    of one row."""

    def __init__(self, spike=8.0, ewma_alpha=0.1, warmup=5,
                 dead_after=3, explode=1.0):
        self.spike = float(spike)
        self.ewma_alpha = float(ewma_alpha)
        self.warmup = int(warmup)
        self.dead_after = int(dead_after)
        self.explode = float(explode)
        self._ewma = None
        self._seen = 0
        self._dead = {}       # group -> consecutive zero-grad rows
        self._dead_fired = set()

    def observe(self, step, row):
        """row: SentinelSpec.decode_row output. Returns
        [NumericsAnomaly]."""
        anomalies = []
        step = int(step)

        nf = (row.get("out_nonfinite", 0.0)
              + row.get("grad_nonfinite", 0.0)
              + row.get("param_nonfinite", 0.0))
        gn = row.get("grad_norm", 0.0)
        loss_bad = not math.isfinite(row.get("loss", 0.0))
        if nf > 0 or loss_bad or not math.isfinite(gn):
            where = [k for k in ("out", "grad", "param")
                     if row.get(f"{k}_nonfinite", 0.0) > 0]
            if loss_bad:
                where.append("loss")
            anomalies.append(NumericsAnomaly(
                kind="nonfinite", step=step, value=float(nf),
                message=(f"non-finite values at step {step} "
                         f"({'/'.join(where) or 'grad_norm'}): "
                         f"{nf:.0f} elements"),
                detail={"where": where}))

        if math.isfinite(gn):
            if (self._ewma is not None and self._seen >= self.warmup
                    and gn > self.spike * self._ewma):
                anomalies.append(NumericsAnomaly(
                    kind="grad_spike", step=step, value=gn,
                    threshold=self.spike * self._ewma,
                    message=(f"grad norm {gn:.4g} at step {step} is "
                             f"{gn / max(self._ewma, 1e-30):.1f}x the "
                             f"EWMA {self._ewma:.4g}")))
            else:
                # a spike must not poison its own baseline
                self._ewma = (gn if self._ewma is None else
                              (1 - self.ewma_alpha) * self._ewma
                              + self.ewma_alpha * gn)
                self._seen += 1

        for g, seg in row.get("groups", {}).items():
            ggn = seg.get("grad_norm", 0.0)
            if ggn == 0.0:
                self._dead[g] = self._dead.get(g, 0) + 1
                if (self._dead[g] >= self.dead_after
                        and g not in self._dead_fired):
                    self._dead_fired.add(g)
                    anomalies.append(NumericsAnomaly(
                        kind="dead_group", step=step, group=g,
                        threshold=float(self.dead_after),
                        message=(f"param group '{g}' has zero gradient "
                                 f"for {self._dead[g]} consecutive "
                                 f"sentinel rows")))
            else:
                self._dead[g] = 0
                self._dead_fired.discard(g)
            pn = seg.get("param_norm", 0.0)
            un = seg.get("update_norm", 0.0)
            if pn > 0 and math.isfinite(un) and math.isfinite(pn):
                ratio = un / pn
                if ratio > self.explode:
                    anomalies.append(NumericsAnomaly(
                        kind="exploding_group", step=step, group=g,
                        value=ratio, threshold=self.explode,
                        message=(f"param group '{g}' update/param "
                                 f"ratio {ratio:.3g} at step {step} "
                                 f"(> {self.explode:g}): the update is "
                                 f"rewriting the weights")))
        return anomalies

"""`numericsStats` telemetry view + native Prometheus instruments.

Live run health for /metrics and /statusz (PR 7 registry machinery):
the NumericsMonitor pushes each drained row's headline numbers here,
so an exporter scrape answers "what do the norms look like right now"
without touching the device — the snapshot is pure host state
refreshed at drain intervals (the exporter-hot-path rule: a view
function must never block on device values).

Registered omit_empty: processes that never enable numerics keep their
/statusz byte-identical (the serving/decoding snapshot-pinning
convention).
"""
from __future__ import annotations

import threading

from ..telemetry import register_view as _register_view
from ..telemetry import registry as _treg

_lock = threading.Lock()
_state: dict = {}

_GRAD_NORM = _treg.gauge(
    "mxnet_tpu_numerics_grad_norm",
    "Global gradient norm of the most recently drained sentinel row")
_LOSS = _treg.gauge(
    "mxnet_tpu_numerics_loss",
    "Head-output mean (loss proxy) of the most recent sentinel row")
_UPDATE_RATIO = _treg.gauge(
    "mxnet_tpu_numerics_update_ratio",
    "Global update-norm / param-norm ratio of the most recent row")
_ANOMALIES = _treg.counter(
    "mxnet_tpu_numerics_anomalies_total",
    "Numerics anomalies by kind (nonfinite, grad_spike, dead_group, "
    "exploding_group)")


def numerics_stats():
    """Snapshot for the `numericsStats` view ({} while inactive)."""
    with _lock:
        return {k: (dict(v) if isinstance(v, dict) else v)
                for k, v in _state.items()}


def reset_numerics_stats():
    with _lock:
        _state.clear()


_register_view("numericsStats", numerics_stats, prom_prefix="numerics",
               omit_empty=True)


def note_row(step, row, lr=None):
    """Record one drained sentinel row's headline numbers."""
    with _lock:
        _state["last_step"] = int(step)
        _state["loss"] = row.get("loss", 0.0)
        _state["grad_norm"] = row.get("grad_norm", 0.0)
        _state["param_norm"] = row.get("param_norm", 0.0)
        _state["update_ratio"] = row.get("update_ratio", 0.0)
        _state["out_nonfinite"] = row.get("out_nonfinite", 0.0)
        _state["grad_nonfinite"] = row.get("grad_nonfinite", 0.0)
        _state["param_nonfinite"] = row.get("param_nonfinite", 0.0)
        if lr is not None:
            _state["lr"] = float(lr)
        _state["rows_drained"] = _state.get("rows_drained", 0) + 1
        _state.setdefault("anomalies_total", 0)
        _state.setdefault("anomalies", {})
    _GRAD_NORM.set(row.get("grad_norm", 0.0))
    _LOSS.set(row.get("loss", 0.0))
    _UPDATE_RATIO.set(row.get("update_ratio", 0.0))


def note_anomaly(anom, first_bad_op=None):
    with _lock:
        _state["anomalies_total"] = _state.get("anomalies_total", 0) + 1
        kinds = _state.setdefault("anomalies", {})
        kinds[anom.kind] = kinds.get(anom.kind, 0) + 1
        last = anom.to_dict()
        if first_bad_op is not None:
            last["first_bad_op"] = first_bad_op
        _state["last_anomaly"] = last
    _ANOMALIES.inc(1, kind=anom.kind)

"""Executor: binds a Symbol + NDArrays into a compiled computation.

Analog of the reference GraphExecutor (src/executor/graph_executor.cc:333
Init / :912 Bind) and python/mxnet/executor.py. The entire NNVM pass
pipeline collapses into XLA:

  Gradient pass            -> jax.vjp over the traced graph
  PlaceDevice              -> sharding annotations (parallel/, later)
  InferShape/InferType     -> done at bind via ops/shape_infer.py
  PlanMemory / inplace     -> XLA buffer assignment + donation
  AttachOpExecs, bulk-exec -> ONE jit computation for the whole graph
                              (the logical endpoint of bulk-exec: the
                              "segment" is the entire graph)

Training uses a single fused forward+backward computation: `forward
(is_train=True)` runs it with default head gradients (ones — loss ops'
custom_vjp ignores/replaces them, matching reference semantics), caches
gradients, and `backward()` just applies them to the grad arrays under
grad_req write/add. An explicit `backward(out_grads)` re-runs the fused
computation with the provided head gradients.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import exec_cache as _exec_cache
from . import profiler as _profiler
from . import random as _random
from .base import MXNetError
from .exec_cache import cache_stats  # noqa: F401  (public API)
from .ndarray import NDArray
from .symbol import _topo


class Executor:
    """A Symbol bound to devices and arrays, runnable forward/backward.

    The whole graph traces into ONE jit computation with `jax.vjp` as
    the Gradient pass (reference GraphExecutor,
    src/executor/graph_executor.cc); surface: forward/backward/
    outputs/arg_dict/reshape/monitor."""

    def __init__(self, symbol, ctx, args, args_grad, grad_req, aux_states,
                 group2ctx=None, shared_exec=None, sharding=None):
        self._symbol = symbol
        self._ctx = ctx
        self._group2ctx = group2ctx or {}
        # `sharding` is a ShardingPlan (or None): its digest joins the
        # exec-cache key below, so rebinding one symbol under a
        # different plan never lands on a compiled program whose
        # in/out shardings were baked for another mesh/rule set
        self._sharding_plan = sharding
        self.arg_dict = dict(args)
        self.grad_dict = dict(args_grad or {})
        self.aux_dict = dict(aux_states or {})
        self._grad_req = dict(grad_req)
        self._arg_names = symbol.list_arguments()
        self._aux_names = symbol.list_auxiliary_states()
        missing = [n for n in self._arg_names if n not in self.arg_dict]
        if missing:
            raise MXNetError(f"bind: missing arguments {missing}")
        self.arg_arrays = [self.arg_dict[n] for n in self._arg_names]
        self.grad_arrays = [
            self.grad_dict.get(n) for n in self._arg_names
        ]
        self.aux_arrays = [self.aux_dict[n] for n in self._aux_names]
        self._grad_names = [
            n
            for n in self._arg_names
            if self._grad_req.get(n, "null") != "null" and n in self.grad_dict
        ]
        # Allocate output NDArrays at bind time (the reference GraphExecutor
        # allocates head entries in InitDataEntryMemory, so exec.outputs is
        # valid before the first Forward — SequentialModule relies on this).
        _, out_shapes, _ = symbol.infer_shape(
            **{n: tuple(a.shape) for n, a in self.arg_dict.items()}
        )
        if out_shapes is None:
            raise MXNetError(
                f"bind: cannot infer output shapes for {symbol.list_outputs()}"
            )
        try:
            _, out_types, _ = symbol.infer_type()
        except Exception:
            out_types = None
        if not out_types:
            out_types = [np.float32] * len(out_shapes)
        self.outputs = [
            NDArray(jnp.zeros(s, t), ctx=ctx)
            for s, t in zip(out_shapes, out_types)
        ]
        self._monitor_callback = None
        self._cached_grads = None
        self._last_inputs = None
        # draw from the framework PRNG chain so mx.random.seed() controls
        # symbolic Dropout/rrelu reproducibly
        self._rng = _random.next_key()

        self._build(shared_exec)

    # ----------------------------------------------------------- build
    def _build(self, shared_exec=None):
        """Resolve this bind to a CompiledGraph: an exec_cache lookup
        keyed by the canonical graph signature + shapes/dtypes/grad
        config. A shared_exec with a matching signature short-circuits
        the table (the reference's shared-executor bind); otherwise a
        hit shares the previously traced program and a miss traces a
        new one."""
        import os as _os

        from . import passes as _passes
        from .analysis import graph_verify as _gv

        if _gv.verify_enabled():
            _gv.verify_graph(
                self._symbol,
                grad_names=self._grad_names,
                **{n: tuple(a.shape)
                   for n, a in {**self.arg_dict,
                                **self.aux_dict}.items()})

        # graph-pass pipeline (MXNET_GRAPH_PASSES, memoized): the
        # executor TRACES the optimized graph but keeps the original
        # symbol as its public surface (arg names, output names,
        # infer_shape) — passes never rename variables, so binding
        # stays by-name against the same buffers. The cache key is
        # built from the OPTIMIZED canonical graph: isomorphic
        # differently-built symbols collapse onto one entry.
        self._opt_symbol = _passes.optimize_for_bind(self._symbol)
        raw_key = self._symbol.structure_key()
        graph_key = (raw_key if self._opt_symbol is self._symbol
                     else self._opt_symbol.structure_key())

        # codegen lowering of the __fusion_group__ stamps: per-group
        # generated-kernel-or-fallback decisions for THIS bind's
        # shapes/platform (passes.pallas_codegen). The plan's
        # cache_component joins the key below, so a program traced
        # with a group fused can never be replayed for a bind where
        # that group fell back (and vice versa).
        self._codegen_plan = _passes.plan_for(
            self._opt_symbol,
            input_shapes={n: tuple(a.shape)
                          for n, a in {**self.arg_dict,
                                       **self.aux_dict}.items()})

        mirror = _os.environ.get(
            "MXNET_BACKWARD_DO_MIRROR", "0") not in ("0", "", "false")
        self._cache_key = (
            graph_key,
            tuple(sorted(
                (g, repr(c)) for g, c in self._group2ctx.items())),
            tuple((n, tuple(self.arg_dict[n].shape),
                   str(self.arg_dict[n].dtype))
                  for n in self._arg_names),
            tuple((n, tuple(self.aux_dict[n].shape),
                   str(self.aux_dict[n].dtype))
                  for n in self._aux_names),
            tuple((n, self._grad_req.get(n, "null"))
                  for n in self._arg_names),
            tuple(self._grad_names),
            (self._sharding_plan.digest()
             if self._sharding_plan is not None else None),
            self._codegen_plan.cache_component,
            mirror,
        )
        # HBM pre-flight BEFORE any program is looked up or traced:
        # strict mode turns an over-cap bind into an exception with
        # zero traces executed (mxnet_tpu.profiling.preflight)
        from . import profiling as _profiling

        if _profiling.profiling_enabled():
            try:
                _profiling.preflight_bind(
                    self._opt_symbol,
                    {n: (tuple(a.shape), a.dtype)
                     for n, a in self.arg_dict.items()},
                    self._grad_req,
                    auxs={n: (tuple(a.shape), a.dtype)
                          for n, a in self.aux_dict.items()},
                    plan=self._sharding_plan)
            except _profiling.HBMPreflightError:
                raise
            except Exception:
                pass  # estimation failure must never block a bind

        if (shared_exec is not None
                and getattr(shared_exec, "_cache_key", None)
                == self._cache_key
                and getattr(shared_exec, "_compiled", None) is not None):
            self._compiled = shared_exec._compiled
            _exec_cache.count_shared_hit()
            return
        self._compiled = _exec_cache.lookup_or_build(
            self._cache_key, self._trace_graph,
            raw_sig=hash(raw_key),
            canonical_fn=lambda: _passes.canonical_digest(
                self._opt_symbol),
            disk_meta_fn=self._disk_record_meta)

    def _disk_record_meta(self):
        """What the disk tier (exec_cache_disk) persists alongside the
        entry digest: the OPTIMIZED canonical graph JSON plus the full
        bind signature — enough to inspect/rebuild the program offline
        (tools/mx_bundle.py inspect) without re-running the passes."""
        return {
            # _opt_symbol already went through the bind-time pipeline
            # (or the user turned it off) — plain serialization, so
            # the record write never re-runs passes or bills
            # pipeline_runs for key/metadata work
            "graph_json": self._opt_symbol.tojson(),
            "inputs": [[n, list(self.arg_dict[n].shape),
                        str(self.arg_dict[n].dtype)]
                       for n in self._arg_names],
            "auxs": [[n, list(self.aux_dict[n].shape),
                      str(self.aux_dict[n].dtype)]
                     for n in self._aux_names],
            "grad_req": {n: self._grad_req.get(n, "null")
                         for n in self._arg_names},
            "sharding": (self._sharding_plan.digest()
                         if self._sharding_plan is not None else None),
        }

    def _trace_graph(self):
        """Build the pure run_graph program + node plan for this bind's
        signature (cache-miss path). No jax tracing happens here — each
        per-mode jit is constructed lazily by CompiledGraph and traces
        on its first call."""
        sym = getattr(self, "_opt_symbol", None) or self._symbol
        nodes = _topo(sym._outputs)
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        heads = [(id(n), i) for n, i in sym._outputs]
        # ctx-group model parallelism (reference PlaceDevice pass +
        # __ctx_group__ attrs, graph_executor.cc:242-318): map each
        # node's group to a concrete device; run_graph inserts
        # device_put at group boundaries — the _CrossDeviceCopy analog,
        # expressed as sharding annotations inside the single jit
        # computation instead of graph surgery.
        group_dev = {
            g: c.jax_device() for g, c in self._group2ctx.items()
        }
        plan = []
        for n in nodes:
            if n.is_variable:
                continue
            params = n.op.normalize_params(n.attrs)
            grp = n._extra_attrs.get("__ctx_group__")
            plan.append(
                (
                    n.op,
                    params,
                    n.op.resolved_num_outputs(params),
                    [(id(src), i) for src, i in n.inputs],
                    id(n),
                    node_ids[id(n)],
                    n.name,
                    group_dev.get(grp),
                )
            )
        var_names = {
            id(n): n.name for n in nodes if n.is_variable
        }
        aux_set = set(self._aux_names)

        # fused-group routing (passes.pallas_codegen.plan_for): the
        # plan's node indices are positions in the same _topo order as
        # `nodes`, translated here to object ids. The per-op plan stays
        # COMPLETE — it is the lax fallback (and what the monitored /
        # per-op debug path iterates); only run_graph skips the interior
        # of a fused group and calls the generated kernel at its output.
        cg = getattr(self, "_codegen_plan", None)
        fused_skip = frozenset(
            id(nodes[i]) for i in cg.skip) if cg else frozenset()
        fused_call = {
            id(nodes[i]): (fn, tuple((id(nodes[s]), oi)
                                     for s, oi in ext))
            for i, (fn, ext) in (cg.fused.items() if cg else ())
        }

        def run_graph(arg_vals, aux_vals, rng, is_train):
            _exec_cache.note_graph_replay()
            env = {}
            for nid, name in var_names.items():
                env[(nid, 0)] = (
                    aux_vals[name] if name in aux_set else arg_vals[name]
                )
            aux_updates = {}
            for (opdef, params, n_out, in_keys, nid, node_idx, nname,
                 dev) in plan:
                if nid in fused_skip:
                    continue
                if nid in fused_call:
                    ffn, ext_keys = fused_call[nid]
                    ext_vals = [env[k] for k in ext_keys]
                    if dev is not None:
                        ext_vals = [jax.device_put(v, dev)
                                    for v in ext_vals]
                    with jax.named_scope(nname):
                        env[(nid, 0)] = ffn(*ext_vals)
                    continue
                in_vals = [env[k] for k in in_keys]
                if dev is not None:
                    in_vals = [
                        jax.device_put(v, dev) for v in in_vals
                    ]
                kwargs = dict(params)
                if opdef.needs_rng:
                    kwargs["rng"] = jax.random.fold_in(rng, node_idx)
                if opdef.needs_mode:
                    kwargs["is_train"] = is_train
                # named_scope stamps the node name into HLO
                # op_metadata, which the XLA device trace copies into
                # its event args — profiling.timeline attributes
                # device time back to graph nodes through it. Pure
                # trace-time cost; compiled code is unchanged.
                with jax.named_scope(nname):
                    res = opdef.fn(*in_vals, **kwargs)
                if not isinstance(res, tuple):
                    res = (res,)
                for i in range(n_out):
                    env[(nid, i)] = res[i]
                n_aux = len(opdef.aux_names)
                if n_aux and is_train and len(res) > n_out:
                    # trailing inputs are the aux vars; map updates back
                    for (src, _), upd in zip(
                        in_keys[-n_aux:], res[n_out:]
                    ):
                        aux_updates[var_names[src]] = upd
            outs = [env[k] for k in heads]
            return outs, aux_updates

        # memory mirror: rematerialize forward activations in backward
        # instead of keeping them — jax.checkpoint is the analog of the
        # reference's MXNET_BACKWARD_DO_MIRROR / memonger (trades ~10%
        # speed for much smaller activation memory,
        # example/image-classification/README.md:352-359). Full
        # in-place donation of params+state lives on the fused train
        # step (parallel/dp_step.py), which owns its buffers.
        return _exec_cache.CompiledGraph(
            run_graph, plan, var_names, aux_set,
            grad_names=self._grad_names, mirror=self._cache_key[-1],
        )

    # Compiled-program views (shared via exec_cache; the underscore
    # names are the pre-cache attribute surface other layers use —
    # pipeline_module, dp_step, tests).
    @property
    def _run_graph(self):
        return self._compiled.run_graph

    @property
    def _plan(self):
        return self._compiled.plan

    @property
    def _var_names(self):
        return self._compiled.var_names

    @property
    def _aux_set(self):
        return self._compiled.aux_set

    @property
    def _jit_train_step(self):
        return self._compiled.jit_train_step()

    # --------------------------------------------------------- running
    def _gather_inputs(self):
        arg_vals = {n: self.arg_dict[n]._data for n in self._arg_names}
        aux_vals = {n: self.aux_dict[n]._data for n in self._aux_names}
        return arg_vals, aux_vals

    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"unknown forward argument {k!r}")
            self.arg_dict[k][:] = v
        arg_vals, aux_vals = self._gather_inputs()
        self._rng, rng = jax.random.split(self._rng)
        if self._monitor_callback is not None:
            # monitored (debug) path: eager per-node execution so the
            # callback sees every intermediate (reference
            # MXExecutorSetMonitorCallback + ExecuteMonCallback,
            # graph_executor.cc:758). Not jit'd by design. Uses the SAME
            # key as the jit pass below so monitored statistics of
            # stochastic ops (Dropout) reflect the executed draw.
            self._forward_monitored(is_train, rng, arg_vals, aux_vals)
        self._cached_grads = None
        with _profiler.scope(
            f"executor_forward[{'train' if is_train else 'eval'}]",
            "executor",
        ):
            if is_train and self._grad_names:
                head_grads = self._default_head_grads(
                    arg_vals, aux_vals, rng
                )
                outs, grads, aux_upd = self._compiled.jit_train_step()(
                    arg_vals, aux_vals, rng, head_grads
                )
                self._cached_grads = grads
            else:
                outs, aux_upd = self._compiled.jit_fwd(is_train)(
                    arg_vals, aux_vals, rng
                )
        self._last_inputs = (arg_vals, aux_vals, rng)
        if is_train:
            for name, val in aux_upd.items():
                self.aux_dict[name]._set_data(val)
        self.outputs = [NDArray(o, ctx=self._ctx) for o in outs]
        return self.outputs

    def _forward_monitored(self, is_train, rng, arg_vals, aux_vals):
        """Eager per-node execution invoking the monitor callback with
        every node output (debug path; see forward()). `rng` is the
        same key the jit forward will use."""
        env = {}
        for nid, name in self._var_names.items():
            env[(nid, 0)] = (
                aux_vals[name] if name in self._aux_set
                else arg_vals[name]
            )
        for (opdef, params, n_out, in_keys, nid, node_idx, nname,
             dev) in self._plan:
            in_vals = [env[k] for k in in_keys]
            if dev is not None:
                in_vals = [jax.device_put(v, dev) for v in in_vals]
            kwargs = dict(params)
            if opdef.needs_rng:
                kwargs["rng"] = jax.random.fold_in(rng, node_idx)
            if opdef.needs_mode:
                kwargs["is_train"] = bool(is_train)
            res = opdef.fn(*in_vals, **kwargs)
            if not isinstance(res, tuple):
                res = (res,)
            for i in range(n_out):
                env[(nid, i)] = res[i]
                out_name = (
                    f"{nname}_output" if n_out == 1
                    else f"{nname}_output{i}"
                )
                self._monitor_callback(
                    out_name, NDArray(res[i], ctx=self._ctx)
                )

    def _default_head_grads(self, arg_vals, aux_vals, rng):
        # ones-buffers are cached on the shared CompiledGraph and only
        # reallocated when the previous step actually donated them away
        return self._compiled.default_head_grads(arg_vals, aux_vals, rng)

    def backward(self, out_grads=None):
        if not self._grad_names:
            return
        if out_grads is not None:
            if self._last_inputs is None:
                raise MXNetError("backward called before forward")
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            # the train-step jit donates its head-grad buffers only on
            # backends where donation is real — copy just there, so the
            # caller's NDArrays stay valid without paying a copy on
            # donation-free backends
            if _exec_cache.donation_effective():
                head_grads = [jnp.copy(g._data) for g in out_grads]
            else:
                head_grads = [g._data for g in out_grads]
            arg_vals, aux_vals, rng = self._last_inputs
            _, grads, _ = self._compiled.jit_train_step()(
                arg_vals, aux_vals, rng, head_grads
            )
        else:
            if self._cached_grads is None:
                raise MXNetError(
                    "backward called without forward(is_train=True)"
                )
            grads = self._cached_grads
        for name, g in grads.items():
            req = self._grad_req.get(name, "null")
            tgt = self.grad_dict.get(name)
            if tgt is None or req == "null":
                continue
            if req == "write":
                tgt._set_data(g)
            elif req == "add":
                tgt._set_data(tgt._data + g)
            else:
                raise MXNetError(f"unknown grad_req {req!r}")

    # --------------------------------------------------------- utilities
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in (arg_params or {}).items():
            if name in self.arg_dict:
                self.arg_dict[name][:] = arr
            elif not allow_extra_params:
                raise MXNetError(f"unknown argument {name!r}")
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                self.aux_dict[name][:] = arr
            elif not allow_extra_params:
                raise MXNetError(f"unknown aux state {name!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **new_shapes):
        """Return a new executor bound with new input shapes, sharing
        parameter NDArrays where shapes are unchanged
        (reference MXExecutorReshape)."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**new_shapes)
        from . import ndarray as nd

        new_args = {}
        for name, shape in zip(self._arg_names, arg_shapes):
            cur = self.arg_dict[name]
            if tuple(cur.shape) == tuple(shape):
                new_args[name] = cur
            else:
                new_args[name] = nd.zeros(shape, ctx=self._ctx,
                                          dtype=cur.dtype)
        new_grads = {}
        for name, cur in self.grad_dict.items():
            if name not in self._arg_names:
                # a grad buffer for a name the symbol does not take
                # (user-supplied extras) — carry it over untouched
                # instead of crashing on .index()
                new_grads[name] = cur
                continue
            shape = arg_shapes[self._arg_names.index(name)]
            if tuple(cur.shape) == tuple(shape):
                new_grads[name] = cur
            else:
                new_grads[name] = nd.zeros(shape, ctx=self._ctx,
                                           dtype=cur.dtype)
        new_aux = {}
        for name, shape in zip(self._aux_names, aux_shapes):
            cur = self.aux_dict[name]
            if tuple(cur.shape) == tuple(shape):
                new_aux[name] = cur
            else:
                new_aux[name] = nd.zeros(shape, ctx=self._ctx,
                                         dtype=cur.dtype)
        # shared_exec=self: a reshape back to previously-seen shapes
        # resolves in the exec_cache (or directly against this
        # executor) with zero retraces
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self._grad_req, new_aux,
                        group2ctx=self._group2ctx, shared_exec=self)

    def release_arrays(self):
        """Drop all buffer references (args/grads/auxs/outputs), keeping
        only the traced graph. Used by the fused train step, which owns
        its own copies of the training state — without this, parameters
        and gradients would stay resident an extra time."""
        self.arg_dict = {}
        self.grad_dict = {}
        self.aux_dict = {}
        self.arg_arrays = []
        self.grad_arrays = []
        self.aux_arrays = []
        self.outputs = []

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def debug_str(self):
        return self._symbol.debug_str()

"""Image pipeline: decode + augment + iterate.

Analog of python/mxnet/image.py (559 lines — ImageIter over
imdecode/resize_short/random_crop/color_normalize augmenters) and the
C++ ImageRecordIter (src/io/iter_image_recordio_2.cc). Host-side decode
(PIL/cv2) feeds NCHW float batches; on TPU the augmented batch is a
single host->HBM transfer per step, with the PrefetchingIter overlapping
decode and compute like the reference's parser threads.
"""
from __future__ import annotations

import logging
import os

import numpy as np

from . import io as _io
from . import ndarray as nd
from . import recordio
from .base import MXNetError
from .random import np_rng, py_rng


def imdecode(buf, to_rgb=True, flag=1):
    """Decode an image bytestring to an HWC uint8 NDArray (reference
    image.py imdecode over the mx.nd.imdecode op, src/io/image_io.cc)."""
    arr = recordio._imdecode_np(
        buf if isinstance(buf, (bytes, bytearray)) else bytes(buf), flag)
    if to_rgb and arr.ndim == 3:
        arr = arr[:, :, ::-1]
    return nd.array(np.ascontiguousarray(arr), dtype=np.uint8)


def scale_down(src_size, size):
    """Scale target size down to fit in src (reference image.py:33)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def _resize_np(img, w, h, interp=2):
    """Resize HWC numpy image via PIL/cv2."""
    try:
        import cv2

        return cv2.resize(img, (w, h), interpolation=interp)
    except ImportError:
        from PIL import Image

        pil = Image.fromarray(img.astype(np.uint8))
        return np.asarray(pil.resize((w, h), Image.BILINEAR))


def imresize(src, w, h, interp=2):
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    return nd.array(_resize_np(img, w, h, interp), dtype=np.uint8)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge is `size` (reference image.py:44)."""
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    h, w = img.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return nd.array(_resize_np(img, new_w, new_h, interp), dtype=np.uint8)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """(reference image.py:57)"""
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = img[y0: y0 + h, x0: x0 + w]
    if size is not None and (w, h) != size:
        out = _resize_np(out, size[0], size[1], interp)
    return nd.array(out, dtype=np.uint8)


def random_crop(src, size, interp=2):
    """(reference image.py:65)"""
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    h, w = img.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = py_rng().randint(0, w - new_w)
    y0 = py_rng().randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """(reference image.py:77)"""
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    h, w = img.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """(reference image.py:89)"""
    arr = src.asnumpy().astype(np.float32)
    arr -= np.asarray(mean, dtype=np.float32)
    if std is not None:
        arr /= np.asarray(std, dtype=np.float32)
    return nd.array(arr)


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random area+aspect crop (reference image.py:96)."""
    img = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    h, w = img.shape[:2]
    area = w * h
    for _ in range(10):
        new_area = py_rng().uniform(min_area, 1.0) * area
        new_ratio = py_rng().uniform(*ratio)
        new_w = int(np.sqrt(new_area * new_ratio))
        new_h = int(np.sqrt(new_area / new_ratio))
        if py_rng().random() < 0.5:
            new_w, new_h = new_h, new_w
        if new_w <= w and new_h <= h:
            x0 = py_rng().randint(0, w - new_w)
            y0 = py_rng().randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return random_crop(src, size, interp)


# ------------------------------------------------------------ augmenters


def ResizeAug(size, interp=2):
    """(reference image.py:126)"""

    def aug(src):
        return [resize_short(src, size, interp)]

    return aug


def RandomCropAug(size, interp=2):
    def aug(src):
        return [random_crop(src, size, interp)[0]]

    return aug


def RandomSizedCropAug(size, min_area, ratio, interp=2):
    def aug(src):
        return [random_size_crop(src, size, min_area, ratio, interp)[0]]

    return aug


def CenterCropAug(size, interp=2):
    def aug(src):
        return [center_crop(src, size, interp)[0]]

    return aug


def RandomOrderAug(ts):
    """(reference image.py:158)"""

    def aug(src):
        srcs = [src]
        py_rng().shuffle(ts)
        for t in ts:
            srcs = [j for i in srcs for j in t(i)]
        return srcs

    return aug


def ColorJitterAug(brightness, contrast, saturation):
    """(reference image.py:170)"""
    ts = []
    coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)
    if brightness > 0:
        def baug(src):
            alpha = 1.0 + py_rng().uniform(-brightness, brightness)
            arr = src.asnumpy().astype(np.float32) * alpha
            return [nd.array(np.clip(arr, 0, 255))]

        ts.append(baug)
    if contrast > 0:
        def caug(src):
            alpha = 1.0 + py_rng().uniform(-contrast, contrast)
            arr = src.asnumpy().astype(np.float32)
            gray = (arr * coef).sum(axis=2, keepdims=True)
            arr = arr * alpha + gray.mean() * (1.0 - alpha)
            return [nd.array(np.clip(arr, 0, 255))]

        ts.append(caug)
    if saturation > 0:
        def saug(src):
            alpha = 1.0 + py_rng().uniform(-saturation, saturation)
            arr = src.asnumpy().astype(np.float32)
            gray = (arr * coef).sum(axis=2, keepdims=True)
            arr = arr * alpha + gray * (1.0 - alpha)
            return [nd.array(np.clip(arr, 0, 255))]

        ts.append(saug)
    return RandomOrderAug(ts)


def LightingAug(alphastd, eigval, eigvec):
    """PCA lighting noise (reference image.py:204)."""

    def aug(src):
        alpha = np_rng().normal(0, alphastd, size=(3,))
        rgb = np.dot(eigvec * alpha, eigval)
        arr = src.asnumpy().astype(np.float32) + rgb
        return [nd.array(arr)]

    return aug


def ColorNormalizeAug(mean, std):
    def aug(src):
        return [color_normalize(src, mean, std)]

    return aug


def HorizontalFlipAug(p):
    def aug(src):
        if py_rng().random() < p:
            return [nd.array(src.asnumpy()[:, ::-1])]
        return [src]

    return aug


def CastAug():
    def aug(src):
        return [nd.array(src.asnumpy().astype(np.float32))]

    return aug


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Standard augmenter list (reference image.py:246-290)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(
            RandomSizedCropAug(crop_size, 0.3, (3.0 / 4.0, 4.0 / 3.0),
                               inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([
            [-0.5675, 0.7192, 0.4009],
            [-0.5808, -0.0045, -0.8140],
            [-0.5836, -0.6948, 0.4203],
        ])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None:
        assert std is not None
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class _NativePrefetchRecord(object):
    """MXRecordIO-compatible facade over the native prefetching reader."""

    def __init__(self, path, capacity=64):
        from . import native as _native

        self._native = _native
        self._path = path
        self._capacity = capacity
        self._r = _native.NativePrefetchReader(path, capacity)

    def read(self):
        return self._r.read()

    def reset(self):
        self._r.close()
        self._r = self._native.NativePrefetchReader(
            self._path, self._capacity
        )

    def close(self):
        self._r.close()


def _open_sequential_rec(path):
    try:
        from . import native as _native

        if _native.available():
            return _NativePrefetchRecord(path)
    except Exception:
        pass
    return recordio.MXRecordIO(path, "r")


class ImageIter(_io.DataIter):
    """Image iterator over .rec files and/or raw image lists with
    augmenters (reference image.py:293-460 + C++ ImageRecordIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", preprocess_threads=4,
                 data_layout="NCHW", dtype="float32", **kwargs):
        super().__init__(batch_size)
        # uint8 batches carry RAW pixels (reference ImageRecordIter2's
        # uint8 registration, iter_image_recordio_2.cc:579): 1/4 the
        # host->device bytes; normalization then runs on device (the
        # fused step promotes unsigned data to the compute dtype)
        self.dtype = np.dtype(dtype)
        if self.dtype not in (np.dtype(np.float32), np.dtype(np.uint8)):
            raise MXNetError(
                f"dtype must be float32 or uint8, got {dtype!r}")
        if self.dtype == np.uint8 and (
                kwargs.get("mean") is not None
                or kwargs.get("std") is not None):
            raise MXNetError(
                "dtype='uint8' carries raw pixels; drop mean/std and "
                "normalize on device")
        # NHWC emits channel-last batches directly (TPU-native layout;
        # the native decoder writes either layout at identical cost)
        self.data_layout = data_layout.upper()
        if self.data_layout not in ("NCHW", "NHWC"):
            raise MXNetError(f"bad data_layout {data_layout!r}")
        # decode+augment worker pool (the analog of the reference's
        # OMP-parallel ImageRecordIOParser2 threads,
        # src/io/iter_image_recordio_2.cc:28 — PIL/cv2 release the GIL
        # during JPEG decompression, so threads give real parallelism)
        self.preprocess_threads = max(1, int(preprocess_threads))
        self._pool = None
        if self.preprocess_threads > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self.preprocess_threads)
        assert path_imgrec or path_imglist or (isinstance(imglist, list))
        if path_imgrec:
            logging.info("loading recordio %s...", path_imgrec)
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(
                    path_imgidx, path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                # sequential scan: prefer the native C++ prefetching
                # reader (background read-ahead thread, native/
                # recordio_core.cc — the iter_prefetcher.h analog)
                self.imgrec = _open_sequential_rec(path_imgrec)
                self.imgidx = None
        else:
            self.imgrec = None

        if path_imglist:
            logging.info("loading image list %s...", path_imglist)
            with open(path_imglist) as fin:
                imglist = {}
                imgkeys = []
                for line in iter(fin.readline, ""):
                    line = line.strip().split("\t")
                    label = nd.array([float(i) for i in line[1:-1]])
                    key = int(line[0])
                    imglist[key] = (label, line[-1])
                    imgkeys.append(key)
                self.imglist = imglist
        elif isinstance(imglist, list):
            logging.info("loading image list...")
            result = {}
            imgkeys = []
            index = 1
            for img in imglist:
                key = str(index)
                index += 1
                if isinstance(img[0], nd.NDArray):
                    label = img[0]
                else:
                    label = nd.array(img[0] if isinstance(img[0], list)
                                     else [img[0]])
                result[key] = (label, img[1])
                imgkeys.append(str(key))
            self.imglist = result
        else:
            self.imglist = None
            imgkeys = None
        self.path_root = path_root

        self.check_data_shape(data_shape)
        c_, h_, w_ = data_shape
        out_shape = (c_, h_, w_) if self.data_layout == "NCHW" \
            else (h_, w_, c_)
        self.provide_data = [_io.DataDesc(
            data_name, (batch_size,) + out_shape, dtype=self.dtype)]
        if label_width > 1:
            self.provide_label = [
                _io.DataDesc(label_name, (batch_size, label_width))]
        else:
            self.provide_label = [_io.DataDesc(label_name, (batch_size,))]
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.label_width = label_width

        self.shuffle = shuffle
        if self.imgrec is None:
            self.seq = imgkeys
        elif shuffle or num_parts > 1:
            assert self.imgidx is not None, \
                "shuffling or sharding a .rec needs the .idx file"
            self.seq = self.imgidx
        else:
            self.seq = None

        if num_parts > 1 and self.seq is not None:
            assert part_index < num_parts
            N = len(self.seq)
            C = N // num_parts
            self.seq = self.seq[part_index * C: (part_index + 1) * C]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
            # fused native decode path (JPEG -> crop/mirror/normalize
            # -> CHW float32 in C++ worker threads) when the augment
            # set maps onto it; None = python augmenters
            self._native_dec = self._try_native_decoder(
                data_shape, kwargs)
        else:
            self.auglist = aug_list
            self._native_dec = None
        self.cur = 0
        # decoded-but-unbatched (img, label) pairs: augmenters with
        # fan-out > 1 can overshoot a batch; the excess carries over
        self._carry = []
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            py_rng().shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0
        self._carry = []

    def next_sample(self):
        """(reference image.py:398)"""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                if self.imglist is None:
                    return header.label, img
                return self.imglist[idx][0], img
            label, fname = self.imglist[idx]
            return label, self.read_image(fname)
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def _try_native_decoder(self, data_shape, kwargs):
        """NativeImageDecoder covering this iterator's augment set —
        now including the standard ImageNet lighting recipe
        (brightness/contrast/saturation jitter + PCA noise, reference
        src/io/image_aug_default.cc) — or None when the set needs the
        python augmenters (random-sized crop, custom interpolation)."""
        if data_shape[0] != 3:
            return None
        covered = {"resize", "rand_crop", "rand_mirror", "mean", "std",
                   "inter_method", "brightness", "contrast",
                   "saturation", "pca_noise"}
        for k, v in kwargs.items():
            if k in covered:
                continue
            try:
                active = v is not None and bool(np.any(v))
            except Exception:
                active = True  # unknown kwarg shape: keep python path
            if active:
                return None
        if kwargs.get("inter_method", 2) != 2:
            return None
        mean = kwargs.get("mean")
        std = kwargs.get("std")
        if mean is True:
            mean = np.array([123.68, 116.28, 103.53])
        if std is True:
            std = np.array([58.395, 57.12, 57.375])
        try:
            from . import native as _native

            return _native.NativeImageDecoder(
                nthreads=self.preprocess_threads,
                resize_short=int(kwargs.get("resize", 0) or 0),
                rand_crop=bool(kwargs.get("rand_crop", False)),
                rand_mirror=bool(kwargs.get("rand_mirror", False)),
                mean=mean, std=std, layout=self.data_layout,
                brightness=float(kwargs.get("brightness", 0) or 0),
                contrast=float(kwargs.get("contrast", 0) or 0),
                saturation=float(kwargs.get("saturation", 0) or 0),
                pca_noise=float(kwargs.get("pca_noise", 0) or 0))
        except Exception as exc:
            logging.debug("native image decoder unavailable: %s", exc)
            return None

    def _decode_augment(self, raw):
        """Worker: raw bytes -> list of augmented HWC numpy images."""
        data = [imdecode(raw)]
        if len(data[0].shape) == 0:
            return []
        for aug in self.auglist:
            data = [ret for src in data for ret in aug(src)]
        return [d.asnumpy() for d in data]

    def _batch_shape(self):
        c, h, w = self.data_shape
        return ((self.batch_size, c, h, w)
                if self.data_layout == "NCHW"
                else (self.batch_size, h, w, c))

    def _write_label(self, batch_label, i, label):
        lab = label.asnumpy() if isinstance(label, nd.NDArray) \
            else np.asarray(label)
        if self.label_width == 1:
            batch_label[i] = lab.reshape(-1)[0]
        else:
            batch_label[i] = lab.reshape(-1)[: self.label_width]

    def _coerce_pixels(self, img):
        """Augmented float pixels -> the batch dtype. uint8 batches
        need explicit round+clip: a bare cast truncates and WRAPS
        out-of-range values (LightingAug output is unclipped)."""
        if self.dtype == np.uint8 and img.dtype != np.uint8:
            return np.clip(np.round(img), 0, 255)
        return img

    def _write_sample(self, batch_data, batch_label, i, img, label):
        img = self._coerce_pixels(img)
        batch_data[i] = img.transpose(2, 0, 1) \
            if self.data_layout == "NCHW" else img
        self._write_label(batch_label, i, label)

    def _next_native(self):
        """Batch assembly through the fused native decoder: raw JPEG
        bytes go straight to the C++ pool, which writes normalized CHW
        float32 rows into the batch buffer (the reference's OMP threads
        writing into the batch, iter_image_recordio_2.cc:28-490).
        Non-JPEG/corrupt records fall back to the python decoder
        per-image."""
        batch_size = self.batch_size
        batch_data = np.zeros(self._batch_shape(), dtype=self.dtype)
        batch_label = np.zeros(
            (batch_size,) if self.label_width == 1
            else (batch_size, self.label_width), dtype=np.float32)
        i = 0
        exhausted = False
        while i < batch_size and not exhausted:
            raw = []
            try:
                while len(raw) < batch_size - i:
                    raw.append(self.next_sample())
            except StopIteration:
                exhausted = True
            if not raw:
                break
            blobs = [bytes(s) for _, s in raw]
            out_view = batch_data[i:i + len(raw)]
            ok = self._native_dec.decode_batch(
                blobs, out_view, seed=py_rng().getrandbits(63))
            valid = []
            for j, (label, s) in enumerate(raw):
                if not ok[j]:
                    # non-JPEG or corrupt: python path for this image
                    imgs = self._decode_augment(s)
                    if not imgs:
                        logging.debug("Invalid image, skipping.")
                        continue
                    img0 = self._coerce_pixels(imgs[0])
                    out_view[j] = img0.transpose(2, 0, 1) \
                        if self.data_layout == "NCHW" else img0
                valid.append(j)
            for dst, j in enumerate(valid):
                if dst != j:
                    out_view[dst] = out_view[j]
                self._write_label(batch_label, i + dst, raw[j][0])
            i += len(valid)
        if i == 0:
            raise StopIteration
        return _io.DataBatch(
            data=[nd.array(batch_data)], label=[nd.array(batch_label)],
            pad=batch_size - i, index=None,
            provide_data=self.provide_data,
            provide_label=self.provide_label)

    def next(self):
        """Assemble a batch: samples are read sequentially from the
        record stream, then decode+augment fans out over the worker
        pool (reference: OMP threads write straight into the batch,
        iter_image_recordio_2.cc:28-490)."""
        if self._native_dec is not None:
            return self._next_native()
        batch_size = self.batch_size
        batch_data = np.zeros(self._batch_shape(), dtype=self.dtype)
        batch_label = np.zeros(
            (batch_size,) if self.label_width == 1
            else (batch_size, self.label_width), dtype=np.float32)
        i = 0
        exhausted = False
        # drain images an earlier batch over-decoded (augmenter
        # fan-out > 1) before touching the record stream
        while self._carry and i < batch_size:
            img, label = self._carry.pop(0)
            self._write_sample(batch_data, batch_label, i, img, label)
            i += 1
        while i < batch_size and not exhausted:
            # 1. pull up to the remaining quota of raw samples (with
            # fan-out k > 1 this overshoots at most once: the excess
            # goes to _carry and later batches pull less)
            raw = []
            try:
                while len(raw) < batch_size - i:
                    raw.append(self.next_sample())
            except StopIteration:
                exhausted = True
            if not raw:
                break
            # 2. decode+augment (parallel), 3. write in order
            if self._pool is not None:
                decoded = list(self._pool.map(
                    self._decode_augment, [s for _, s in raw]))
            else:
                decoded = [self._decode_augment(s) for _, s in raw]
            for (label, _), imgs in zip(raw, decoded):
                if not imgs:
                    logging.debug("Invalid image, skipping.")
                    continue
                for img in imgs:
                    if i < batch_size:
                        self._write_sample(batch_data, batch_label, i,
                                           img, label)
                        i += 1
                    else:
                        self._carry.append((img, label))
        if i == 0:
            raise StopIteration
        return _io.DataBatch(
            data=[nd.array(batch_data)], label=[nd.array(batch_label)],
            pad=batch_size - i, index=None,
            provide_data=self.provide_data,
            provide_label=self.provide_label)

    def check_data_shape(self, data_shape):
        if not len(data_shape) == 3:
            raise ValueError(
                "data_shape should have length 3, with dimensions CxHxW")
        if not data_shape[0] == 3 and not data_shape[0] == 1:
            raise ValueError(
                "This iterator expects the input image to have 1 or 3 "
                "channels.")

    def read_image(self, fname):
        with open(os.path.join(self.path_root or "", fname), "rb") as fin:
            return fin.read()


def ImageRecordIter(path_imgrec=None, data_shape=(3, 224, 224),
                    batch_size=128, shuffle=False, mean_r=0.0, mean_g=0.0,
                    mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0,
                    rand_crop=False, rand_mirror=False, path_imgidx=None,
                    preprocess_threads=4, prefetch_buffer=4,
                    part_index=0, num_parts=1, label_width=1,
                    data_layout="NCHW", dtype="float32", **kwargs):
    """Compatibility constructor matching the C++ ImageRecordIter params
    (src/io/iter_image_recordio_2.cc:559 registration), returning an
    ImageIter wrapped in a PrefetchingIter (the analog of the fused
    parser + prefetcher pipeline)."""
    mean = None
    std = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b])
    if std_r != 1.0 or std_g != 1.0 or std_b != 1.0:
        std = np.array([std_r, std_g, std_b])
    it = ImageIter(
        batch_size=batch_size, data_shape=data_shape,
        path_imgrec=path_imgrec, path_imgidx=path_imgidx, shuffle=shuffle,
        rand_crop=rand_crop, rand_mirror=rand_mirror, mean=mean, std=std,
        part_index=part_index, num_parts=num_parts,
        label_width=label_width, preprocess_threads=preprocess_threads,
        data_layout=data_layout, dtype=dtype,
    )
    return _io.PrefetchingIter(it)

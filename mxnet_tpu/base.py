"""Base utilities shared across the framework.

TPU-native analog of the reference's ctypes bridge + dmlc helpers
(reference: python/mxnet/base.py, include/mxnet/base.h). There is no C ABI
boundary for the compute path here — jax/XLA is invoked in-process — so
"base" reduces to error types, name managers and small coercion helpers
used by the parameter system (analog of dmlc::Parameter,
reference src/operator/*-inl.h DMLC_DECLARE_PARAMETER blocks).
"""
from __future__ import annotations

import re
import threading


class MXNetError(RuntimeError):
    """Error raised by the framework (analog of reference MXNetError,
    python/mxnet/base.py:34)."""


_name_lock = threading.Lock()
_name_counters: dict[str, int] = {}


def _auto_name(prefix: str) -> str:
    """Generate a unique name like `convolution3` (analog of
    python/mxnet/name.py NameManager)."""
    with _name_lock:
        idx = _name_counters.get(prefix, 0)
        _name_counters[prefix] = idx + 1
    return f"{prefix}{idx}"


_TRUE = frozenset(("1", "true", "True", "TRUE"))
_FALSE = frozenset(("0", "false", "False", "FALSE", "none", "None"))


def coerce_bool(v) -> bool:
    if isinstance(v, str):
        if v in _TRUE:
            return True
        if v in _FALSE:
            return False
        raise MXNetError(f"cannot interpret {v!r} as bool")
    return bool(v)


def coerce_int(v) -> int:
    return int(v)


def coerce_float(v) -> float:
    return float(v)


def coerce_tuple(v, n=None, typ=int):
    """Parse '(2, 2)' / '[2,2]' / 2 / (2,2) into a tuple of `typ`.

    Analog of mshadow::TShape parsing used by dmlc parameter structs so
    symbols deserialized from JSON (string attrs) behave like natively
    constructed ones.
    """
    if isinstance(v, str):
        s = v.strip()
        if s.startswith(("(", "[")):
            s = s[1:-1]
        items = [x for x in re.split(r"[,\s]+", s) if x]
        out = tuple(typ(x) for x in items)
    elif isinstance(v, (tuple, list)):
        out = tuple(typ(x) for x in v)
    else:
        out = (typ(v),) if n is None else (typ(v),) * n
    if n is not None and len(out) == 1 and n > 1:
        out = out * n
    if n is not None and len(out) != n:
        raise MXNetError(f"expected tuple of length {n}, got {v!r}")
    return out


def coerce_str(v) -> str:
    return str(v)

"""Imperative autograd.

Analog of the reference AutogradRuntime (src/ndarray/autograd.{h,cc}):
imperative op calls are recorded on a tape while a training scope is
active; `compute_gradient` replays the tape as a pure jax function of the
marked variables and pulls gradients out with jax.vjp — the TPU-native
version of "build an nnvm graph from AGNodes and run a throwaway
GraphExecutor" (autograd.cc:132-170). Heads get ones as cotangents, so
loss ops' custom_vjp semantics (ops/nn.py) reproduce reference backward
behavior.

User-facing API mirrors python/mxnet/contrib/autograd.py:
`train_section`/`test_section` scopes, `mark_variables`,
`compute_gradient`, `grad_and_loss`, `grad`.
"""
from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

from .base import MXNetError

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []
        _state.marked = {}  # id(chunk) -> (ndarray, grad_ndarray)
    return _state


@dataclass
class TapeEntry:
    opdef: Any
    params: dict
    inputs: list  # NDArray refs
    outputs: list  # NDArray refs
    input_values: list  # jax arrays at record time
    rng: Any = None
    extra_kwargs: dict = field(default_factory=dict)


def is_recording() -> bool:
    return _st().recording


def is_training() -> bool:
    return _st().training


def set_is_training(train: bool) -> bool:
    st = _st()
    prev = st.training
    if train and not prev:
        # entering an outermost train scope: drop any tape left over from
        # a previous scope that never called compute_gradient, so stale
        # entries can't leak memory or corrupt the next replay.
        st.tape = []
    st.training = train
    st.recording = train
    return prev


class _Scope:
    def __init__(self, train):
        self._train = train

    def __enter__(self):
        self._prev = set_is_training(self._train)

    def __exit__(self, *_):
        set_is_training(self._prev)


def train_section():
    return _Scope(True)


def test_section():
    return _Scope(False)


# aliases matching newer mxnet naming
record = train_section
pause = test_section


def mark_variables(variables, gradients, grad_reqs="write"):
    st = _st()
    for var, grad in zip(variables, gradients):
        st.marked[id(var._chunk)] = (var, grad)


def record_op(opdef, params, inputs, outputs, rng=None, extra_kwargs=None,
              input_values=None):
    """Append an executed op to the tape. `input_values` must be the
    inputs *as seen by the op* (pre any aux write-back) — callers pass the
    values they actually fed the kernel."""
    st = _st()
    st.tape.append(
        TapeEntry(
            opdef=opdef,
            params=params,
            inputs=list(inputs),
            outputs=list(outputs),
            input_values=(
                list(input_values)
                if input_values is not None
                else [x._data for x in inputs]
            ),
            rng=rng,
            extra_kwargs=dict(extra_kwargs or {}),
        )
    )


def _replay(tape, heads, var_chunks):
    """Build f(var_values) -> head_values by replaying the tape."""

    head_ids = [id(h._chunk) for h in heads]

    def fn(var_values):
        env = dict(zip(var_chunks, var_values))
        for entry in tape:
            in_vals = [
                env.get(id(x._chunk), rec)
                for x, rec in zip(entry.inputs, entry.input_values)
            ]
            kwargs = dict(entry.params)
            kwargs.update(entry.extra_kwargs)
            if entry.opdef.needs_rng:
                kwargs["rng"] = entry.rng
            if entry.opdef.needs_mode:
                kwargs["is_train"] = True
            res = entry.opdef.fn(*in_vals, **kwargs)
            if not isinstance(res, tuple):
                res = (res,)
            for out_nd, val in zip(entry.outputs, res):
                env[id(out_nd._chunk)] = val
        return [env[hid] for hid in head_ids]

    return fn


def compute_gradient(outputs):
    """Compute gradients of `outputs` w.r.t. marked variables and write
    them into the marked gradient buffers (contrib/autograd.py:109)."""
    st = _st()
    if not st.marked:
        raise MXNetError("no variables marked for gradient")
    var_nds = [v for v, _ in st.marked.values()]
    grad_nds = [g for _, g in st.marked.values()]
    var_chunks = [id(v._chunk) for v in var_nds]
    fn = _replay(st.tape, outputs, var_chunks)
    var_values = [v._data for v in var_nds]
    _, vjp_fn = jax.vjp(fn, var_values)
    ones = [jnp.ones_like(h._data) for h in outputs]
    (grads,) = vjp_fn(ones)
    for g_nd, g_val in zip(grad_nds, grads):
        g_nd._set_data(g_val)
    st.tape = []


def backward(outputs, out_grads=None):
    compute_gradient(outputs)


def grad_and_loss(func, argnum=None):
    """Decorator returning (gradients, loss) of func w.r.t. its ndarray
    inputs (contrib/autograd.py:141)."""

    @functools.wraps(func)
    def wrapped(*args):
        from . import ndarray as nd

        argnums = argnum
        if argnums is None:
            argnums = list(range(len(args)))
        elif isinstance(argnums, int):
            argnums = [argnums]
        variables = [args[i] for i in argnums]
        grads = [nd.zeros_like(v) for v in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        compute_gradient(list(outs))
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    wrapped = grad_and_loss(func, argnum)

    @functools.wraps(func)
    def only_grad(*args):
        return wrapped(*args)[0]

    return only_grad

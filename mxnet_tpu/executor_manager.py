"""DataParallelExecutorManager — the pre-Module multi-device training
helper (reference python/mxnet/executor_manager.py:424). Kept for API
parity; internally an adapter over module.executor_group.
DataParallelExecutorGroup, which is the maintained path (as in the
reference, where Module superseded it)."""
from __future__ import annotations

import logging

from .base import MXNetError
from .io import DataDesc
from .module.executor_group import DataParallelExecutorGroup


def _split_input_slice(batch_size, work_load_list):
    """Slice ranges per device weighted by workload (reference
    executor_manager.py _split_input_slice)."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        if i == len(work_load_list) - 1:
            end = batch_size
        else:
            end = start + int(round(batch_size * w / total))
        slices.append(slice(start, end))
        start = end
    return slices


def _check_arguments(symbol):
    """Reject duplicated argument/aux names (reference
    executor_manager.py _check_arguments)."""
    arg_names = symbol.list_arguments()
    if len(set(arg_names)) != len(arg_names):
        raise MXNetError(
            f"Find duplicated argument name: {arg_names}"
        )
    aux_names = symbol.list_auxiliary_states()
    if len(set(aux_names)) != len(aux_names):
        raise MXNetError(
            f"Find duplicated auxiliary name: {aux_names}"
        )


class DataParallelExecutorManager(object):
    """Helper to manage multi-device executors for data parallelism."""

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        if logger is None:
            logger = logging.getLogger()
        _check_arguments(symbol)
        self.symbol = symbol
        self.ctx = ctx
        self.sym_gen = sym_gen
        num_device = len(ctx)
        logger.info(
            "Start training with %s", [str(c) for c in ctx]
        )
        if work_load_list is None:
            work_load_list = [1] * num_device
        assert len(work_load_list) == num_device
        self.work_load_list = work_load_list

        self.data_shapes = [
            DataDesc(*d) if not isinstance(d, DataDesc) else d
            for d in train_data.provide_data
        ]
        self.label_shapes = [
            DataDesc(*d) if not isinstance(d, DataDesc) else d
            for d in (train_data.provide_label or [])
        ]
        arg_names = arg_names or symbol.list_arguments()
        aux_names = aux_names or symbol.list_auxiliary_states()
        data_names = {d.name for d in self.data_shapes} | {
            d.name for d in self.label_shapes
        }
        if param_names is None:
            param_names = [
                n for n in arg_names if n not in data_names
            ]
        self._arg_names = arg_names
        self._param_names = param_names
        self._aux_names = aux_names

        self.execgrp = DataParallelExecutorGroup(
            symbol, ctx, work_load_list, self.data_shapes,
            self.label_shapes or None, param_names, for_training=True,
            inputs_need_grad=False,
        )

    # ------------------------------------------------------------ params
    @property
    def param_names(self):
        return self._param_names

    @property
    def aux_names(self):
        return self._aux_names

    @property
    def param_arrays(self):
        return self.execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.execgrp.grad_arrays

    @property
    def aux_arrays(self):
        return self.execgrp.aux_arrays

    def set_params(self, arg_params, aux_params):
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        """Average device copies back into host dicts (reference
        executor_manager.py copy_to)."""
        self.execgrp.get_params(arg_params, aux_params)

    def install_monitor(self, monitor):
        for exe in self.execgrp.execs:
            monitor.install(exe)

    # ------------------------------------------------------------ compute
    def load_data_batch(self, data_batch):
        self._cur_batch = data_batch

    def forward(self, is_train=False):
        self.execgrp.forward(self._cur_batch, is_train=is_train)

    def backward(self):
        self.execgrp.backward()

    @property
    def curr_execgrp(self):
        return self.execgrp

    def update_metric(self, metric, labels):
        self.execgrp.update_metric(metric, labels)

"""Optimizer registry + implementations.

Analog of python/mxnet/optimizer.py (755 lines: SGD:279, Adam:451,
RMSProp:536, Updater closure:722). TPU-native design: every optimizer's
`update` routes through a *fused* registered op (ops/optimizer_ops.py) or
a jitted jax closure, so weight+state update is one XLA kernel per
parameter — the analog of the reference's fused `sgd_update`/`adam_update`
mshadow kernels. State arrays live on device; the scalar schedule math
(lr_scheduler, wd multipliers, update counts) stays host-side, exactly
like the reference.

The `get_updater` closure is what KVStore calls per key (reference
optimizer.py:722 `Updater`), so server-side optimizer semantics carry
over unchanged to the KVStore('tpu') facade.
"""
from __future__ import annotations

import contextlib
import math
import pickle

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .ndarray import NDArray

_OPT_REGISTRY: dict[str, type] = {}


def register(klass):
    """Register an optimizer class under its lowercased name (reference
    optimizer.py Optimizer.register)."""
    name = klass.__name__.lower()
    _OPT_REGISTRY[name] = klass
    return klass


class Optimizer:
    """Base optimizer (reference optimizer.py:29-277): tracks per-index
    update counts, lr/wd multipliers resolved from param_idx2name + symbol
    attrs, gradient rescale/clip, and an optional lr_scheduler."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01,
                 lr_scheduler=None, sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        if not isinstance(param_idx2name, dict):
            raise MXNetError("param_idx2name should be a dict of param indexes to names.")
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        self.set_lr_mult({})
        self.set_wd_mult({})

    @staticmethod
    def create_optimizer(name, **kwargs):
        key = name.lower()
        if key not in _OPT_REGISTRY:
            raise MXNetError(f"Cannot find optimizer {name!r}")
        return _OPT_REGISTRY[key](**kwargs)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    # ------------------------------------------------- lr/wd multipliers
    def set_lr_mult(self, args_lr_mult):
        """Per-arg lr multipliers; symbol `__lr_mult__` attrs participate
        (reference optimizer.py:120-140)."""
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """wd defaults to 0 for biases/gammas/betas (reference
        optimizer.py:142-170: every name not ending in _weight/_gamma gets
        wd_mult 0 unless overridden)."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    # -------------------------------------------------------- schedules
    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # ------------------------------------------------- fused (traced) form
    def _wd_for(self, name):
        """Static per-parameter weight decay for the fused train step
        (name-keyed twin of _get_wd)."""
        return self.wd * self.wd_mult.get(name, 1.0)

    def _lr_mult_for(self, name):
        """Static per-parameter lr multiplier for the fused train step."""
        return self.lr_mult.get(name, 1.0)

    @contextlib.contextmanager
    def temp_wd_mult(self, name, value):
        """Install a TEMPORARY wd multiplier (scalar or per-element
        vector) under a synthetic name for one traced apply_dense call
        — removed on exit so no tracer or stale value survives in the
        dict. Used by the flat-bucket update paths (parallel/dp_step,
        module/pipeline_module)."""
        self.wd_mult[name] = value
        try:
            yield name
        finally:
            self.wd_mult.pop(name, None)

    def apply_dense(self, name, weight, grad, state, lr, t):
        """Pure-jax update of one parameter inside the fused train step.

        weight/grad are jnp arrays, state is a pytree shaped like
        create_state's result (jnp leaves), lr is a traced scalar that
        already includes this parameter's lr multiplier, and t is the
        traced update count (for bias correction). Returns
        (new_weight, new_state). Optimizers whose update cannot be
        traced (host-side randomness, delayed-gradient bookkeeping)
        leave this unimplemented and fall back to the per-parameter
        eager path.
        """
        raise NotImplementedError(
            f"{type(self).__name__} has no fused (traced) update"
        )


# `mx.optimizer.Optimizer.create_optimizer` alias (reference keeps both)
create = Optimizer.create_optimizer


def _fused(name, inputs, params, n_state):
    """Run a fused update op; op outputs are (weight', *states'), written
    in place over weight and the trailing state inputs."""
    from .ops import registry as _reg
    from .ndarray import invoke

    opdef = _reg.get(name)
    targets = [inputs[0]] + (inputs[-n_state:] if n_state else [])
    return invoke(opdef, inputs, params, out=targets)


@register
class SGD(Optimizer):
    """SGD with momentum (reference optimizer.py:279: fused via
    sgd_update/sgd_mom_update ops)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = {"lr": lr, "wd": wd, "rescale_grad": self.rescale_grad,
                  "clip_gradient": self.clip_gradient or -1.0}
        if state is not None:
            _fused("sgd_mom_update", [weight, grad, state],
                   dict(kwargs, momentum=self.momentum), 1)
        else:
            _fused("sgd_update", [weight, grad], kwargs, 0)

    def apply_dense(self, name, weight, grad, state, lr, t):
        from .ops.optimizer_ops import sgd_mom_update, sgd_update

        kw = {"lr": lr, "wd": self._wd_for(name),
              "rescale_grad": self.rescale_grad,
              "clip_gradient": self.clip_gradient or -1.0}
        if state is None:
            return sgd_update(weight, grad, **kw), None
        w, m = sgd_mom_update(weight, grad, state,
                              momentum=self.momentum, **kw)
        return w, m


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference optimizer.py:366)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        grad_v = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            import jax.numpy as jnp

            grad_v = jnp.clip(grad_v, -self.clip_gradient, self.clip_gradient)
        if state is not None:
            mom = state._data
            mom = self.momentum * mom + grad_v + wd * weight._data
            grad_v = grad_v + self.momentum * mom
            state._set_data(mom)
            weight._set_data(weight._data - lr * (grad_v + wd * weight._data))
        else:
            weight._set_data(
                weight._data - lr * (grad_v + wd * weight._data)
            )

    def apply_dense(self, name, weight, grad, state, lr, t):
        import jax.numpy as jnp

        wd = self._wd_for(name)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        if state is None:
            return weight - lr * (g + wd * weight), None
        mom = self.momentum * state + g + wd * weight
        look = g + self.momentum * mom
        return weight - lr * (look + wd * weight), mom


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference optimizer.py:408):
    SGD plus gaussian noise scaled by sqrt(lr)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        import jax
        import jax.numpy as jnp

        from . import random as _random

        g = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        noise = jax.random.normal(
            _random.next_key(), weight.shape, weight._data.dtype
        ) * math.sqrt(lr)
        weight._set_data(
            weight._data - lr / 2 * (g + wd * weight._data) + noise
        )


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (
            nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
            weight.copy(),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        import jax.numpy as jnp

        g = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        delta = -lr * (
            g + wd * weight._data
            + self.lamda * g * g * (weight._data - previous_weight._data)
        )
        if mom is not None:
            m = self.momentum * mom._data + delta
            mom._set_data(m)
            delta = m
        previous_weight._set_data(weight._data)
        weight._set_data(weight._data + delta)


@register
class Adam(Optimizer):
    """Adam (reference optimizer.py:451; fused adam_update op). Applies
    the bias-corrected lr on host, like the reference."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
            nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        _fused(
            "adam_update", [weight, grad, mean, var],
            {"lr": lr, "beta1": self.beta1, "beta2": self.beta2,
             "epsilon": self.epsilon, "wd": wd,
             "rescale_grad": self.rescale_grad,
             "clip_gradient": self.clip_gradient or -1.0}, 2,
        )

    def apply_dense(self, name, weight, grad, state, lr, t):
        import jax.numpy as jnp

        from .ops.optimizer_ops import adam_update

        tf = t.astype(jnp.float32)
        lr_t = lr * jnp.sqrt(1.0 - self.beta2 ** tf) / (1.0 - self.beta1 ** tf)
        mean, var = state
        w, m, v = adam_update(
            weight, grad, mean, var, lr=lr_t, beta1=self.beta1,
            beta2=self.beta2, epsilon=self.epsilon, wd=self._wd_for(name),
            rescale_grad=self.rescale_grad,
            clip_gradient=self.clip_gradient or -1.0,
        )
        return w, (m, v)


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference optimizer.py:508)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        import jax.numpy as jnp

        g = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        history = state._data + g * g
        state._set_data(history)
        weight._set_data(
            weight._data
            - lr * (g / jnp.sqrt(history + self.float_stable_eps)
                    + wd * weight._data)
        )

    def apply_dense(self, name, weight, grad, state, lr, t):
        import jax.numpy as jnp

        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        hist = state + g * g
        w = weight - lr * (
            g / jnp.sqrt(hist + self.float_stable_eps)
            + self._wd_for(name) * weight
        )
        return w, hist


@register
class RMSProp(Optimizer):
    """RMSProp (reference optimizer.py:536): centered=False → Tieleman &
    Hinton variant (rmsprop_update); centered=True → Graves variant
    (rmspropalex_update)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
            )
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kwargs = {"lr": lr, "gamma1": self.gamma1, "epsilon": self.epsilon,
                  "wd": wd, "rescale_grad": self.rescale_grad,
                  "clip_gradient": self.clip_gradient or -1.0,
                  "clip_weights": self.clip_weights or -1.0}
        if self.centered:
            n, g, delta = state
            _fused("rmspropalex_update", [weight, grad, n, g, delta],
                   dict(kwargs, gamma2=self.gamma2), 3)
        else:
            _fused("rmsprop_update", [weight, grad, state], kwargs, 1)

    def apply_dense(self, name, weight, grad, state, lr, t):
        from .ops.optimizer_ops import rmsprop_update, rmspropalex_update

        kw = {"lr": lr, "gamma1": self.gamma1, "epsilon": self.epsilon,
              "wd": self._wd_for(name), "rescale_grad": self.rescale_grad,
              "clip_gradient": self.clip_gradient or -1.0,
              "clip_weights": self.clip_weights or -1.0}
        if self.centered:
            n, g, delta = state
            w, n2, g2, d2 = rmspropalex_update(
                weight, grad, n, g, delta, gamma2=self.gamma2, **kw)
            return w, (n2, g2, d2)
        w, n2 = rmsprop_update(weight, grad, state, **kw)
        return w, n2


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference optimizer.py:601)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
            nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        import jax.numpy as jnp

        g = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        new_acc_g = self.rho * acc_g._data + (1.0 - self.rho) * g * g
        current_delta = (
            jnp.sqrt(acc_delta._data + self.epsilon)
            / jnp.sqrt(new_acc_g + self.epsilon) * g
        )
        new_acc_delta = (
            self.rho * acc_delta._data
            + (1.0 - self.rho) * current_delta * current_delta
        )
        acc_g._set_data(new_acc_g)
        acc_delta._set_data(new_acc_delta)
        weight._set_data(
            weight._data - current_delta - wd * weight._data
        )


@register
class Ftrl(Optimizer):
    """FTRL-proximal (reference optimizer.py:648)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (
            nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # z
            nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),  # n
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        import jax.numpy as jnp

        g = grad._data * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        z, n = state
        sigma = -jnp.sqrt(n._data)
        new_n = n._data + g * g
        sigma = (sigma + jnp.sqrt(new_n)) / lr
        new_z = z._data + g - sigma * weight._data
        n._set_data(new_n)
        z._set_data(new_z)
        new_w = (
            (jnp.sign(new_z) * self.lamda1 - new_z)
            / ((self.beta + jnp.sqrt(new_n)) / lr + wd)
            * (jnp.abs(new_z) > self.lamda1)
        )
        weight._set_data(new_w.astype(weight._data.dtype))


@register
class Test(Optimizer):
    """Test optimizer: w -= rescale_grad * grad (reference
    optimizer.py:700)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight._set_data(weight._data - grad._data * self.rescale_grad)


# ccSGD in the reference is SGD with a fused C++ kernel; identical math.
@register
class CcSGD(SGD):
    pass


_OPT_REGISTRY["ccsgd"] = CcSGD


class Updater:
    """Closure applying `optimizer` per (index, grad, weight) — what
    KVStore calls server-side (reference optimizer.py:722-754). Lazily
    creates per-index state and supports state (de)serialization for
    checkpointing optimizer state."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        def _to_nd(x):
            if isinstance(x, np.ndarray):
                return nd.array(x)
            if isinstance(x, (tuple, list)):
                return tuple(_to_nd(i) for i in x)
            return x

        obj = pickle.loads(states)
        if isinstance(obj, dict) and obj.get("format") == \
                "mxnet_tpu/fused_v1":
            # fused-train-step checkpoint ({name: state}): replicate
            # each name's state into EVERY index the eager path uses
            # for it (one per device; _to_nd per slot so device copies
            # never alias one state array)
            name2idxs: dict = {}
            for i, n in self.optimizer.idx2name.items():
                name2idxs.setdefault(n, []).append(i)
            self.states = {
                i: _to_nd(v)
                for n, v in obj["states"].items()
                for i in name2idxs.get(n, ())
            }
            # seed update counters from the fused step count: Adam-style
            # bias correction must continue from t, not restart at 1
            t = int(obj.get("t", 0))
            if t:
                opt = self.optimizer
                opt.num_update = max(opt.num_update, t)
                for i in self.states:
                    opt._index_update_count[i] = max(
                        opt._index_update_count.get(i, 0), t)
            return
        self.states = {k: _to_nd(v) for k, v in obj.items()}

    def get_states(self):
        def _to_np(x):
            if isinstance(x, NDArray):
                return x.asnumpy()
            if isinstance(x, (tuple, list)):
                return tuple(_to_np(i) for i in x)
            return x

        return pickle.dumps({k: _to_np(v) for k, v in self.states.items()})


def get_updater(optimizer):
    return Updater(optimizer)

"""Profiler: Chrome trace-event output + TPU/XLA trace capture.

Capability parity with the reference profiler (src/engine/profiler.{h,cc}
— OprExecStat records per-op begin/end dumped as Chrome trace-event JSON
by DumpProfile, python/mxnet/profiler.py facade). TPU-native twist: the
heavy device-side timeline comes from jax.profiler (XLA trace →
TensorBoard/Perfetto), while host-side framework events (executor
forward/backward, io, kvstore push/pull) are recorded here and dumped in
the same Chrome trace-event JSON format the reference emits, so existing
chrome://tracing workflows keep working.
"""
from __future__ import annotations

import json
import os
import threading
import time

from . import telemetry as _telemetry

_state = {
    "mode": "symbolic",
    "filename": "profile.json",
    "running": False,
    "ever_ran": False,
    "jax_trace_dir": None,
}
_events = []
_lock = threading.Lock()
_t0 = time.perf_counter()

# ---- host<->device sync accounting (hostSyncStats) ----------------
# The pipelined training loop's invariant is "zero per-step blocking
# syncs"; these counters make it measurable (and CI-enforceable, see
# ci/check_no_perstep_sync.py). Incremented from the few chokepoints
# every sync funnels through: NDArray.asnumpy (blocking_fetches),
# NDArray.wait_to_read / engine.wait_for_all / FusedTrainStep.sync
# (blocking_waits), EvalMetric drain (metric_fetches), and the
# dispatch-ahead window in BaseModule.fit (dispatch_stalls /
# steps_in_flight_peak).
_sync_lock = threading.Lock()
_SYNC_KEYS = (
    "blocking_fetches", "blocking_waits", "metric_fetches",
    "dispatch_stalls", "stall_time_us", "steps_in_flight_peak",
)
_sync_stats = {k: 0 for k in _SYNC_KEYS}

# a wait shorter than this was already complete — dispatch kept ahead,
# nothing stalled
_STALL_THRESHOLD_S = 1e-4


def count_host_sync(kind, n=1):
    """Count a host<->device sync point of the given kind
    ('blocking_fetches' | 'blocking_waits' | 'metric_fetches')."""
    with _sync_lock:
        _sync_stats[kind] += n


def note_dispatch_stall(seconds):
    """Record one dispatch-window wait; counts as a stall only when the
    fenced step was genuinely unfinished."""
    with _sync_lock:
        _sync_stats["stall_time_us"] += seconds * 1e6
        if seconds > _STALL_THRESHOLD_S:
            _sync_stats["dispatch_stalls"] += 1


def note_steps_in_flight(n):
    """Track the high-water mark of in-flight dispatched steps."""
    with _sync_lock:
        if n > _sync_stats["steps_in_flight_peak"]:
            _sync_stats["steps_in_flight_peak"] = n


def host_sync_stats():
    """Snapshot of the sync counters (embedded in dump_profile as
    `hostSyncStats` next to execCacheStats/servingStats)."""
    with _sync_lock:
        out = dict(_sync_stats)
    out["stall_time_us"] = round(out["stall_time_us"], 1)
    return out


def reset_host_sync_stats():
    with _sync_lock:
        for k in _SYNC_KEYS:
            _sync_stats[k] = 0


# hostSyncStats is the registry view owned by this module; the other
# four silos register theirs at their own import (exec_cache,
# serving.stats, data.stats, passes.manager)
_telemetry.register_view("hostSyncStats", host_sync_stats,
                         prom_prefix="host_sync")


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Configure profiler output (reference profiler.py:10
    MXSetProfilerConfig). mode: 'symbolic' (executor-level events) or
    'all' (also imperative ops)."""
    _state["mode"] = mode
    _state["filename"] = filename


def profiler_set_state(state="stop"):
    """'run' starts collection, 'stop' ends it and dumps
    (reference profiler.py:25 MXSetProfilerState)."""
    if state == "run":
        _state["running"] = True
        _state["ever_ran"] = True
        trace_dir = os.environ.get("MXNET_TPU_XLA_TRACE_DIR")
        if trace_dir:
            try:
                import jax

                jax.profiler.start_trace(trace_dir)
                _state["jax_trace_dir"] = trace_dir
                # device events are timestamped relative to capture
                # start; remember where that sits on the host timeline
                # so the merge can re-base them (one unified clock)
                _state["trace_t0_us"] = (
                    time.perf_counter() - _t0) * 1e6
            except Exception:
                _state["jax_trace_dir"] = None
    elif state == "stop":
        device_trace = None
        if _state["jax_trace_dir"]:
            try:
                import jax

                jax.profiler.stop_trace()
                device_trace = _state["jax_trace_dir"]
            except Exception:
                pass
            _state["jax_trace_dir"] = None
        _state["running"] = False
        # no collection ever ran in this process: there is nothing to
        # dump, and writing an empty profile.json into the cwd as a
        # side effect of a defensive stop() call is pure pollution
        if not _state["ever_ran"]:
            return None
        return dump_profile(device_trace_dir=device_trace)
    else:
        raise ValueError("state must be 'run' or 'stop'")


def is_running():
    return _state["running"]


def record_event(name, category, begin_s, end_s, force=False):
    """Record one host-side event (seconds since profiler import).
    `force` bypasses the running check for callers that latched the
    record decision earlier (scope)."""
    if not force and not _state["running"]:
        return
    with _lock:
        _events.append((name, category, begin_s, end_s))


class scope:
    """Context manager timing a host-side region into the profile.

    The record decision is latched at __enter__: a region that began
    while the profiler was running is recorded even if collection
    stops before __exit__ (previously the region silently vanished),
    and symmetrically a region that began before 'run' stays out."""

    def __init__(self, name, category="host"):
        self.name = name
        self.category = category

    def __enter__(self):
        self._record = _state["running"]
        self._b = time.perf_counter() - _t0
        return self

    def __exit__(self, *exc):
        if self._record:
            record_event(
                self.name, self.category, self._b,
                time.perf_counter() - _t0, force=True,
            )
        return False


def _collect_device_events(trace_dir):
    """Chrome trace events from the newest jax/XLA capture under
    trace_dir (jax writes plugins/profile/<run>/<host>.trace.json.gz in
    chrome trace-event format — one file PER HOST, several in a
    multi-host/multi-device capture). All files of the newest run
    directory are merged; device pids map into a per-source-file lane
    (file i, source pid p -> 1000*(i+1)+p, bumped past collisions) so
    two devices that both call themselves pid 2 in different files
    stay separate processes next to the host (pid 0) timeline instead
    of silently merging. Single-file captures keep the historical
    pid+1000 mapping exactly."""
    import glob
    import gzip

    paths = glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True)
    if not paths:
        return []
    # the newest RUN, not the newest file: a capture writes sibling
    # per-host files into one run directory
    run_dir = os.path.dirname(max(paths, key=os.path.getmtime))
    run_paths = sorted(p for p in paths
                       if os.path.dirname(p) == run_dir)
    # shift device timestamps onto the host timeline: the capture's ts
    # are relative to its own start, which dump-time recorded as
    # trace_t0_us on the host clock
    base = _state.get("trace_t0_us", 0.0)
    out = []
    pid_map = {}        # (file_idx, src_pid) -> output pid
    taken = set()
    for file_idx, path in enumerate(run_paths):
        try:
            with gzip.open(path, "rt") as f:
                device = json.load(f)
        except Exception:
            continue  # a torn/partial file must not drop the others
        for ev in device.get("traceEvents", []):
            ev = dict(ev)
            pid = ev.get("pid")
            if isinstance(pid, int):
                lane = pid_map.get((file_idx, pid))
                if lane is None:
                    lane = 1000 * (file_idx + 1) + pid
                    while lane in taken:
                        lane += 1000
                    taken.add(lane)
                    pid_map[(file_idx, pid)] = lane
                ev["pid"] = lane
            if isinstance(ev.get("ts"), (int, float)):
                ev["ts"] = ev["ts"] + base
            out.append(ev)
    return out


def _view(key, import_module):
    """Thin read over the telemetry registry: the silo registers its
    snapshot function as a view at ITS import; the lazy import here
    only triggers that registration for callers that never imported
    the silo themselves."""
    if not _telemetry.has_view(key):
        import importlib

        importlib.import_module(import_module, __package__)
    return _telemetry.view_snapshot(key)


def exec_cache_stats():
    """Counters of the process-wide compiled-computation cache
    (exec_cache): hits/misses/traces/evictions + size. A thin read of
    the telemetry registry's `execCacheStats` view; also embedded in
    every dump_profile output."""
    return _view("execCacheStats", ".exec_cache")


def graph_pass_stats():
    """Counters of the graph-optimization pass pipeline
    (mxnet_tpu.passes): pipeline runs / memo hits, nodes in/out/
    eliminated, folds, CSE merges, fusion groups, layout rewrites,
    per-pass wall time — the registry's `graphPassStats` view,
    embedded in every dump_profile output."""
    return _view("graphPassStats", ".passes.manager")


def serving_stats():
    """Per-served-model counters of the serving tier (qps, queue depth,
    batch fill, padding waste, latency percentiles, retrace guard) —
    the registry's `servingStats` view, embedded in every dump_profile
    output."""
    return _view("servingStats", ".serving.stats")


def input_pipeline_stats():
    """Input-pipeline counters (wait-for-data per step, device-prefetch
    queue depth, bytes/s, stall count) — the registry's
    `inputPipelineStats` view, embedded in every dump_profile output.
    The "is my step waiting on input?" answer: stall_count > 0 in
    steady state means the data tier, not the device, bounds
    throughput (docs/faq.md)."""
    return _view("inputPipelineStats", ".data.stats")


def _ensure_silo_views():
    """Trigger registration of any legacy silo view not yet imported
    (each wrapped: an unimportable silo — e.g. jax missing pieces —
    must not break the dump, matching the old per-silo try/except)."""
    for fn in (exec_cache_stats, serving_stats, input_pipeline_stats,
               graph_pass_stats):
        try:
            fn()
        except Exception:
            pass


def dump_profile(device_trace_dir=None):
    """Write collected events as ONE Chrome trace-event JSON (the
    reference emits a single unified trace, src/engine/profiler.cc:134):
    host-side framework events on pid 0, and — when a jax device
    capture ran — the XLA device timeline merged in under offset
    pids. Every subsystem view registered in the telemetry registry is
    embedded top-level under its legacy key (`execCacheStats`,
    `servingStats`, `hostSyncStats`, `inputPipelineStats`,
    `graphPassStats`, in that historical order — chrome://tracing
    ignores unknown keys).

    Durability (round-7 satellite): the event buffer is cleared only
    AFTER the file is durably on disk, and the write goes through
    tmp + os.replace — a failed or interrupted dump neither loses the
    buffered events nor leaves a torn/partial profile behind."""
    with _lock:
        events = list(_events)
    # device events are collected BEFORE the view snapshot: feeding
    # them into the timeline aggregator first means the
    # deviceTimelineStats view embedded in THIS dump already reflects
    # the capture the same file carries (previously the per-op
    # aggregation lagged one dump behind its own events)
    device_events = []
    if device_trace_dir:
        device_events = _collect_device_events(device_trace_dir)
        if device_events:
            try:
                from .profiling import ingest_device_events

                ingest_device_events(device_events)
            except Exception:
                pass  # aggregation is advisory; the dump must land
    trace = {"traceEvents": [], "displayTimeUnit": "ms"}
    _ensure_silo_views()
    for key, snap in _telemetry.view_items():
        trace[key] = snap
    for name, cat, b, e in events:
        trace["traceEvents"].append({
            "name": name, "cat": cat, "ph": "B",
            "ts": b * 1e6, "pid": 0, "tid": 0,
        })
        trace["traceEvents"].append({
            "name": name, "cat": cat, "ph": "E",
            "ts": e * 1e6, "pid": 0, "tid": 0,
        })
    trace["traceEvents"].extend(device_events)
    filename = _state["filename"]
    tmp = f"{filename}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(trace, f)
        os.replace(tmp, filename)
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise  # events stay buffered: nothing was dropped
    # success: drop exactly the events that were written (events that
    # arrived during the dump stay for the next one)
    with _lock:
        del _events[:len(events)]
    return filename

"""Profiler: Chrome trace-event output + TPU/XLA trace capture.

Capability parity with the reference profiler (src/engine/profiler.{h,cc}
— OprExecStat records per-op begin/end dumped as Chrome trace-event JSON
by DumpProfile, python/mxnet/profiler.py facade). TPU-native twist: the
heavy device-side timeline comes from jax.profiler (XLA trace →
TensorBoard/Perfetto), while host-side framework events (executor
forward/backward, io, kvstore push/pull) are recorded here and dumped in
the same Chrome trace-event JSON format the reference emits, so existing
chrome://tracing workflows keep working.
"""
from __future__ import annotations

import json
import os
import threading
import time

_state = {
    "mode": "symbolic",
    "filename": "profile.json",
    "running": False,
    "jax_trace_dir": None,
}
_events = []
_lock = threading.Lock()
_t0 = time.perf_counter()


def profiler_set_config(mode="symbolic", filename="profile.json"):
    """Configure profiler output (reference profiler.py:10
    MXSetProfilerConfig). mode: 'symbolic' (executor-level events) or
    'all' (also imperative ops)."""
    _state["mode"] = mode
    _state["filename"] = filename


def profiler_set_state(state="stop"):
    """'run' starts collection, 'stop' ends it and dumps
    (reference profiler.py:25 MXSetProfilerState)."""
    if state == "run":
        _state["running"] = True
        trace_dir = os.environ.get("MXNET_TPU_XLA_TRACE_DIR")
        if trace_dir:
            try:
                import jax

                jax.profiler.start_trace(trace_dir)
                _state["jax_trace_dir"] = trace_dir
            except Exception:
                _state["jax_trace_dir"] = None
    elif state == "stop":
        if _state["jax_trace_dir"]:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            _state["jax_trace_dir"] = None
        _state["running"] = False
        dump_profile()
    else:
        raise ValueError("state must be 'run' or 'stop'")


def is_running():
    return _state["running"]


def record_event(name, category, begin_s, end_s):
    """Record one host-side event (seconds since profiler import)."""
    if not _state["running"]:
        return
    with _lock:
        _events.append((name, category, begin_s, end_s))


class scope:
    """Context manager timing a host-side region into the profile."""

    def __init__(self, name, category="host"):
        self.name = name
        self.category = category

    def __enter__(self):
        self._b = time.perf_counter() - _t0
        return self

    def __exit__(self, *exc):
        record_event(
            self.name, self.category, self._b,
            time.perf_counter() - _t0,
        )
        return False


def dump_profile():
    """Write collected events as Chrome trace-event JSON (the reference
    DumpProfile format, src/engine/profiler.cc:134)."""
    with _lock:
        events = list(_events)
        _events.clear()
    trace = {"traceEvents": [], "displayTimeUnit": "ms"}
    for name, cat, b, e in events:
        trace["traceEvents"].append({
            "name": name, "cat": cat, "ph": "B",
            "ts": b * 1e6, "pid": 0, "tid": 0,
        })
        trace["traceEvents"].append({
            "name": name, "cat": cat, "ph": "E",
            "ts": e * 1e6, "pid": 0, "tid": 0,
        })
    with open(_state["filename"], "w") as f:
        json.dump(trace, f)
    return _state["filename"]

"""Network visualization (reference python/mxnet/visualization.py):
print_summary (text table of layers, output shapes, param counts) and
plot_network (graphviz digraph, gated on graphviz availability)."""
from __future__ import annotations

from .base import MXNetError
from .symbol import Symbol, _topo


def _node_params(node):
    return {k: str(v) for k, v in (node.attrs or {}).items()}


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer-by-layer summary (reference
    visualization.py print_summary)."""
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")
    if positions is None:
        positions = [0.44, 0.64, 0.74, 1.0]
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise MXNetError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))

    positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #",
                  "Previous Layer"]

    def print_row(fields, pos):
        line = ""
        for field, p in zip(fields, pos):
            line += str(field)
            line = line[: p - 1]
            line += " " * (p - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)

    nodes = _topo(symbol._outputs)
    arg_shape_dict = {}
    if show_shape:
        arg_shapes, _, _ = symbol.infer_shape(**shape)
        if arg_shapes is not None:
            arg_shape_dict = dict(
                zip(symbol.list_arguments(), arg_shapes)
            )
    total_params = 0
    for node in nodes:
        if node.is_variable:
            continue
        op = node.op.name
        name = node.name
        pre_nodes = [src.name for src, _ in node.inputs
                     if not src.is_variable]
        # param count: product of shapes of this node's own variables
        cur_param = 0
        if show_shape:
            for src, _ in node.inputs:
                if src.is_variable and src.name.startswith(name) \
                        and not src.name.endswith("label"):
                    s = arg_shape_dict.get(src.name)
                    if s:
                        p = 1
                        for d in s:
                            p *= d
                        cur_param += p
        out_shape = "?"
        if show_shape:
            key = name + "_output"
            if key in shape_dict:
                out_shape = str(shape_dict[key])
        fields = [
            f"{name}({op})",
            out_shape,
            cur_param,
            ",".join(pre_nodes),
        ]
        print_row(fields, positions)
        total_params += cur_param
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("_" * line_length)
    return total_params


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz digraph of the network (reference
    visualization.py plot_network). Requires the `graphviz` package;
    raises a clear error when unavailable."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise MXNetError(
            "plot_network requires the graphviz python package"
        ) from e
    if not isinstance(symbol, Symbol):
        raise MXNetError("symbol must be a Symbol")

    node_attrs = node_attrs or {}
    node_attr = {
        "shape": "box", "fixedsize": "true", "width": "1.3",
        "height": "0.8034", "style": "filled",
    }
    node_attr.update(node_attrs)
    dot = Digraph(name=title, format=save_format)

    fill_colors = {
        "variable": "#8dd3c7",
        "fc": "#fb8072",
        "conv": "#fb8072",
        "act": "#ffffb3",
        "bn": "#bebada",
        "pool": "#80b1d3",
        "other": "#fccde5",
    }

    nodes = _topo(symbol._outputs)
    for node in nodes:
        name = node.name
        if node.is_variable:
            if hide_weights and not node.name.endswith("data") \
                    and not node.name.endswith("label"):
                continue
            dot.node(
                name=name, label=name,
                fillcolor=fill_colors["variable"], **node_attr
            )
            continue
        op = node.op.name
        key = "other"
        label = f"{op}\n{name}"
        low = op.lower()
        if "fullyconnected" in low:
            key = "fc"
        elif "convolution" in low or "deconvolution" in low:
            key = "conv"
        elif "activation" in low or "relu" in low:
            key = "act"
        elif "batchnorm" in low:
            key = "bn"
        elif "pooling" in low:
            key = "pool"
        dot.node(
            name=name, label=label, fillcolor=fill_colors[key],
            **node_attr
        )
        for src, _ in node.inputs:
            if src.is_variable and hide_weights \
                    and not src.name.endswith("data") \
                    and not src.name.endswith("label"):
                continue
            dot.edge(tail_name=src.name, head_name=name)
    return dot

"""mxnet_tpu.decoding — continuous-batching autoregressive serving
over a paged, ragged KV cache.

The serving tier (mxnet_tpu.serving) batches ONE forward per request;
autoregressive decoding needs hundreds of dependent steps per request,
and naive batching staircases every sequence to the longest one. This
package applies the Ragged Paged Attention recipe (PAPERS.md) instead:

  blocks     free-list page allocator + per-sequence page tables with
             refcounts (prefix sharing, copy-on-write fork)
  attention  page-table attention kernels: gather-based lax reference
             and a scalar-prefetch Pallas flash kernel
             (MXNET_DECODE_KERNEL=lax|pallas)
  model      the decoder contract: reference / prefill / decode-step
             forwards over one flat params dict
  engine     DecodeEngine — owns the device page pool and a pre-traced
             fixed-shape program grid (zero steady-state retraces)
  scheduler  ContinuousScheduler + DecodedModel — per-step admission,
             eviction, priority preemption, streaming DecodeFuture
             (whose TokenStream owns/cancels the request)
  prefix     PrefixCache — radix index over cached prompt KV pages;
             admission maps shared prefixes via the fork path and
             prefills only the tail
  quant      precision-polymorphic page pools (KVPool pytree):
             int8 pages with per-page scale planes, quantized at
             scatter and dequantized in-kernel
             (MXNET_DECODE_KV_DTYPE=float32|bf16|int8)
  sampling   SamplingParams + the (seed, position, salt) counter
             streams: temperature/top-k/top-p inside the jitted step,
             bit-reproducible across preemption
  speculative draft-proposes-K / target-verifies-K+1 forwards over
             the same page tables (distribution-identical output,
             exact under greedy)
  stats      DecodeStats -> `decodingStats` view (profiler dumps,
             /metrics, /statusz)

    from mxnet_tpu import serving
    server = serving.ModelServer()
    dec = server.load_decoder("lm", params, cfg)        # warmed
    fut = server.submit_decode("lm", prompt_tokens)     # DecodeFuture
    for tok in fut.stream(): ...                        # per-step
    toks = server.generate("lm", prompt_tokens)         # sync

Knobs: MXNET_DECODE_* (docs/env_vars.md). Guide: docs/serving.md
("Continuous decoding").
"""
from . import attention, blocks, config, engine, model, prefix, \
    quant, sampling, scheduler, speculative, stats
from .blocks import (SCRATCH_PAGE, BlockAllocator, PageError,
                     PagePoolExhausted, pages_needed)
from .attention import (get_kernel, get_multi_kernel,
                        paged_attention_lax, paged_attention_pallas)
from .engine import DecodeEngine, quant_parity_probe
from .quant import KVPool
from .model import DecoderConfig, init_decoder_params, reference_logits
from .prefix import PrefixCache, page_digests
from .sampling import SamplingParams
from .scheduler import (ContinuousScheduler, DecodeFuture,
                        DecodedModel, RequestHandedOff, TokenStream)
from .stats import DecodeStats, decoding_stats, reset_decoding_stats

__all__ = [
    "BlockAllocator", "ContinuousScheduler", "DecodeEngine",
    "DecodeFuture", "DecodeStats", "DecodedModel", "DecoderConfig",
    "KVPool", "PageError", "PagePoolExhausted", "PrefixCache",
    "RequestHandedOff", "SCRATCH_PAGE", "SamplingParams",
    "TokenStream", "attention", "blocks", "config",
    "decoding_stats", "engine", "get_kernel", "get_multi_kernel",
    "init_decoder_params", "model", "page_digests",
    "paged_attention_lax", "paged_attention_pallas", "pages_needed",
    "prefix", "quant", "quant_parity_probe", "reference_logits",
    "reset_decoding_stats", "sampling", "scheduler", "speculative",
    "stats",
]

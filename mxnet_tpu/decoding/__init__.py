"""mxnet_tpu.decoding — continuous-batching autoregressive serving
over a paged, ragged KV cache.

The serving tier (mxnet_tpu.serving) batches ONE forward per request;
autoregressive decoding needs hundreds of dependent steps per request,
and naive batching staircases every sequence to the longest one. This
package applies the Ragged Paged Attention recipe (PAPERS.md) instead:

  blocks     free-list page allocator + per-sequence page tables with
             refcounts (prefix sharing, copy-on-write fork)
  attention  page-table attention kernels: gather-based lax reference
             and a scalar-prefetch Pallas flash kernel
             (MXNET_DECODE_KERNEL=lax|pallas)
  model      the decoder contract: reference / prefill / decode-step
             forwards over one flat params dict
  engine     DecodeEngine — owns the device page pool and a pre-traced
             fixed-shape program grid (zero steady-state retraces)
  scheduler  ContinuousScheduler + DecodedModel — per-step admission,
             eviction, priority preemption, streaming DecodeFuture
  stats      DecodeStats -> `decodingStats` view (profiler dumps,
             /metrics, /statusz)

    from mxnet_tpu import serving
    server = serving.ModelServer()
    dec = server.load_decoder("lm", params, cfg)        # warmed
    fut = server.submit_decode("lm", prompt_tokens)     # DecodeFuture
    for tok in fut.stream(): ...                        # per-step
    toks = server.generate("lm", prompt_tokens)         # sync

Knobs: MXNET_DECODE_* (docs/env_vars.md). Guide: docs/serving.md
("Continuous decoding").
"""
from . import attention, blocks, config, engine, model, scheduler, \
    stats
from .blocks import (SCRATCH_PAGE, BlockAllocator, PageError,
                     PagePoolExhausted, pages_needed)
from .attention import (get_kernel, paged_attention_lax,
                        paged_attention_pallas)
from .engine import DecodeEngine
from .model import DecoderConfig, init_decoder_params, reference_logits
from .scheduler import ContinuousScheduler, DecodeFuture, DecodedModel
from .stats import DecodeStats, decoding_stats, reset_decoding_stats

__all__ = [
    "BlockAllocator", "ContinuousScheduler", "DecodeEngine",
    "DecodeFuture", "DecodeStats", "DecodedModel", "DecoderConfig",
    "PageError", "PagePoolExhausted", "SCRATCH_PAGE", "attention",
    "blocks", "config", "decoding_stats", "engine", "get_kernel",
    "init_decoder_params", "model", "paged_attention_lax",
    "paged_attention_pallas", "pages_needed", "reference_logits",
    "reset_decoding_stats", "scheduler", "stats",
]

"""Continuous-batching scheduler: the control loop of the decode tier.

One scheduler thread per decoder model drives a fixed-shape
`DecodeEngine` step loop. Unlike the one-shot batcher (which forms a
batch, runs it, and replies), the decode batch is a ROLLING set: every
step the scheduler

  1. resolves per-sequence deadlines (mid-generation, not just at
     admission — a stuck client's sequence frees its pages promptly),
  2. admits waiting requests into free batch rows (prefill: one
     bucket-padded prompt pass that scatters K/V into fresh pages),
  3. grows each live sequence's page table by one page when its next
     token crosses a page boundary — preempting the lowest-priority
     (ties: most recently admitted) sequence when the pool is
     exhausted, never crashing (CI gate iii),
  4. runs ONE fixed-shape decode step over the full (max_batch,
     pages_bucket) grid and streams each live row's token out.

Preemption drops a sequence's pages but keeps its token history; on
readmission the scheduler re-prefills prompt + generated-so-far and
the continuation is bit-identical to the uninterrupted run (the
XLA-level prefix stability tests/test_decoding.py pins) — including
sampled runs, whose randomness is a pure function of (request seed,
position) and so replays exactly.

Two work-avoidance layers ride the same loop (ROADMAP item 1):

  * a `PrefixCache` (prefix.py) lets admission map full prompt pages
    already prefilled by live or recently-finished sequences instead
    of recomputing them — only the tail past the cached prefix is
    prefilled. Cached-but-unreferenced pages are evicted LRU under
    pool pressure BEFORE any live sequence is preempted.
  * with a draft model loaded, `_step` runs the engine's speculative
    propose+verify pair and can emit up to spec_k+1 tokens per target
    step (speculative.py proves output equivalence).

Tokens reach callers through `DecodeFuture`: `result()` is the full
generated list (the serving Future contract), `stream()` returns a
`TokenStream` iterating tokens as steps complete. The stream OWNS the
request: closing it (context-manager exit, `close()`, or GC) cancels
an unfinished request so its pages return to the pool instead of
decoding on to max_tokens for a reader that left.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time

import numpy as np

from ..serving.batcher import (DeadlineExceededError, ServerBusyError,
                               ServerClosedError, ServingError,
                               pick_bucket)
from ..telemetry import trace as _trace
from . import config as _cfg
from .blocks import SCRATCH_PAGE, PagePoolExhausted, pages_needed
from .engine import DecodeEngine
from .prefix import PrefixCache
from .sampling import SamplingParams
from .stats import DecodeStats

_DONE = object()


class RequestHandedOff(ServingError):
    """Raised into a request's future/stream when a draining decoder
    hands the request off instead of finishing it locally. `.state`
    is the JSON-ready resume record (prompt, tokens generated so far,
    sampling seed + position, remaining deadline) that
    `admit_resumed` on any other replica accepts — under counter-based
    sampling the continuation there is bit-identical to the
    uninterrupted run, so a caller (normally the fleet router) loses
    nothing but a little latency."""

    def __init__(self, state):
        super().__init__(
            "request handed off mid-decode; resume elsewhere with "
            "admit_resumed(exc.state)")
        self.state = state


class DecodeFuture:
    """Handle for one decode request: both a future and a stream.

    `result(timeout)` blocks for the COMPLETE generated token list
    (EOS excluded) or raises the request's failure. `stream(timeout)`
    returns a TokenStream iterating tokens as the scheduler emits
    them — the first token arrives right after prefill — and raises
    the failure mid-iteration if one lands. `finish_reason` is
    "eos" | "max_tokens" | "length" | "cancelled" after completion.

    `cancel()` asks the scheduler to stop the request at its next
    sweep: the future resolves with reason "cancelled" holding the
    tokens generated so far, and the sequence's pages go back to the
    pool. No-op once done.
    """

    def __init__(self, trace_id=None):
        self.trace_id = trace_id
        self.finish_reason = None
        self._q = queue.Queue()
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._tokens = None
        self._exc = None

    # ---------------------------------------------- scheduler side
    def _emit(self, tok):
        self._q.put(int(tok))

    def _finish(self, tokens, reason):
        self.finish_reason = reason
        self._tokens = list(tokens)
        self._done.set()
        self._q.put(_DONE)

    def _fail(self, exc):
        self._exc = exc
        self._done.set()
        self._q.put(exc)

    # ------------------------------------------------- caller side
    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("decode request still running")
        if self._exc is not None:
            raise self._exc
        return list(self._tokens)

    def exception(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("decode request still running")
        return self._exc

    def cancel(self):
        """Request cancellation; returns True if the request was still
        running (the scheduler will resolve it with reason
        "cancelled"), False if it had already finished."""
        if self._done.is_set():
            return False
        self._cancel.set()
        return True

    def stream(self, timeout=None):
        """A TokenStream over generated tokens (see class docstring:
        the stream owns the request — close it to cancel)."""
        return TokenStream(self, timeout=timeout)


class TokenStream:
    """Iterator over one request's tokens that OWNS the request.

    Abandoning a stream used to leak the whole tail of the request:
    the scheduler kept decoding to max_new_tokens, holding pages and a
    batch row for a reader that left. TokenStream closes that hole —
    `close()`, `with`-exit, and garbage collection all cancel the
    underlying request if it has not finished. Iterating to the end
    makes close a no-op.
    """

    def __init__(self, future, timeout=None):
        self.future = future
        self._timeout = timeout

    def __iter__(self):
        return self

    def __next__(self):
        item = self.future._q.get(timeout=self._timeout)
        if item is _DONE:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self):
        """Cancel the request unless it already finished."""
        self.future.cancel()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _Sequence:
    """Scheduler-internal state of one in-flight request."""

    __slots__ = ("prompt", "max_new", "priority", "deadline", "future",
                 "trace_id", "order", "sampling", "use_draft",
                 "generated", "table", "length", "last_token",
                 "preempted", "t_submit_pc", "pending_tail",
                 "tail_meta")

    def __init__(self, prompt, max_new, priority, deadline, future,
                 trace_id, order, sampling, use_draft):
        self.prompt = list(prompt)
        self.max_new = max_new
        self.priority = priority
        self.deadline = deadline       # absolute monotonic, or None
        self.future = future
        self.trace_id = trace_id
        self.order = order             # admission tiebreak (FIFO)
        self.sampling = sampling       # SamplingParams (resolved)
        self.use_draft = use_draft     # speculative opt-in for this row
        self.generated = []
        self.table = None              # page ids while active
        self.length = 0                # tokens materialized in cache
        self.last_token = -1
        self.preempted = False
        self.t_submit_pc = _trace.now()
        # merged-step tail prefill (engine.merged_step_enabled): the
        # uncached prompt tail still to be fed through step() rows,
        # and (t0, n_ctx, start, need_total, n_matched) bookkeeping
        # for the note_prefill/span record at completion
        self.pending_tail = None
        self.tail_meta = None

    def context_tokens(self):
        """Tokens the KV cache must hold for this sequence: the prompt
        plus everything generated EXCEPT the newest token (whose K/V
        is appended by the next decode step)."""
        return self.prompt + self.generated[:-1] \
            if self.generated else list(self.prompt)


class ContinuousScheduler:
    """The rolling-batch control loop over one DecodeEngine."""

    def __init__(self, engine, stats, key, queue_cap=None,
                 max_tokens=None, eos_id=None):
        self.engine = engine
        self.stats = stats
        self.key = key
        self.queue_cap = queue_cap if queue_cap is not None \
            else _cfg.queue_cap()
        self.default_max_tokens = max_tokens if max_tokens is not None \
            else _cfg.max_tokens()
        self.eos_id = eos_id if eos_id is not None \
            else engine.cfg.eos_id
        # prompt-prefix page cache: admission-side work avoidance; the
        # cache inherits the engine's kv dtype so its advertised
        # digests can never match pages stored at another precision
        self.cache = PrefixCache(engine.allocator,
                                 kv_dtype=engine.kv_dtype) \
            if engine.prefix_cache_enabled else None
        self._cond = threading.Condition()
        self._waiting = []
        self._rows = [None] * engine.max_batch
        self._tail_plan = []           # (seq, chunk) for this step
        self._order = itertools.count()
        self._closed = False
        self._drain = True
        self._draining = False         # drain(): admission closed
        self._handoff = False          # leftovers hand off, not fail
        self._handoff_states = []      # loop/backstop-thread only
        self._thread = None

    # ------------------------------------------------------ public API
    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name=f"decoding-{self.key}", daemon=True)
        self._thread.start()
        return self

    def depth(self):
        """(waiting, active) — the stats view's queue-depth probe."""
        with self._cond:
            return (len(self._waiting),
                    sum(1 for s in self._rows if s is not None))

    def submit(self, prompt, max_new_tokens=None, priority=0,
               deadline_ms=None, sampling=None, seed=None, draft=None):
        """Enqueue one autoregressive request; returns a DecodeFuture.

        `priority`: higher values survive page-pool pressure longer
        (preemption victims are chosen lowest-priority-first).
        `deadline_ms` is end-to-end and checked EVERY step, not only
        at admission — a mid-generation miss resolves the future with
        DeadlineExceededError and frees the sequence's pages.
        `sampling`/`seed`: a SamplingParams (or None for the env
        defaults; `seed` overrides just the stream seed). Greedy
        (temperature<=0) needs no seed. `draft`: per-request
        speculative opt-in/out; defaults to "on when a draft model is
        loaded".
        """
        prompt = [int(t) for t in prompt]
        sp = SamplingParams.resolve(sampling, seed)
        sp.validate(self.engine.cfg.vocab)
        if draft is None:
            use_draft = self.engine.spec_enabled
        else:
            use_draft = bool(draft)
            if use_draft and not self.engine.spec_enabled:
                raise ServingError(
                    "speculative decoding requested but no draft "
                    "model is loaded")
        if not prompt:
            raise ServingError("empty prompt")
        if any(t < 0 or t >= self.engine.cfg.vocab for t in prompt):
            raise ServingError("prompt token outside vocab")
        if len(prompt) > self.engine.max_context:
            raise ServingError(
                f"prompt of {len(prompt)} tokens exceeds the decode "
                f"context capacity {self.engine.max_context}")
        max_new = int(max_new_tokens) if max_new_tokens is not None \
            else self.default_max_tokens
        if max_new < 1:
            raise ServingError("max_new_tokens must be >= 1")
        tid = _trace.new_trace_id()
        with _trace.span("decoding.submit", trace_id=tid,
                         model=self.key):
            deadline = (time.monotonic() + deadline_ms / 1e3
                        if deadline_ms is not None else None)
            fut = DecodeFuture(tid)
            with self._cond:
                if self._closed or self._draining:
                    raise ServerClosedError("decoder is shut down")
                if len(self._waiting) >= self.queue_cap:
                    self.stats.note_rejected()
                    raise ServerBusyError(
                        f"decode queue full ({self.queue_cap}); "
                        "retry with backoff")
                seq = _Sequence(prompt, max_new, int(priority),
                                deadline, fut, tid, next(self._order),
                                sp, use_draft)
                self._waiting.append(seq)
                self._cond.notify()
        self.stats.note_submitted()
        return fut

    def admit_resumed(self, state):
        """Admit a request handed off by another scheduler's `drain()`
        (or rebuilt by the fleet router from its own token record
        after a replica died). The resumed future's STREAM emits only
        NEW tokens — everything in state["generated"] was already
        delivered by the original replica — while `result()` returns
        the full list. Counter-based sampling (token at position P is
        a pure function of the request seed and P) plus the XLA
        prefix-stability property make the continuation bit-identical
        to the uninterrupted run; internally this rides the exact
        readmission path preemption uses."""
        prompt = [int(t) for t in state["prompt"]]
        generated = [int(t) for t in state.get("generated", ())]
        sp = SamplingParams.resolve(state.get("sampling"), None)
        sp.validate(self.engine.cfg.vocab)
        max_new = int(state["max_new_tokens"])
        if not prompt:
            raise ServingError("empty prompt in resume state")
        if any(t < 0 or t >= self.engine.cfg.vocab
               for t in prompt + generated):
            raise ServingError("resume state token outside vocab")
        if len(generated) >= max_new:
            raise ServingError(
                "resume state is already at max_new_tokens; nothing "
                "left to decode")
        if len(prompt) + len(generated) > self.engine.max_context:
            raise ServingError(
                "resume state exceeds the decode context capacity "
                f"{self.engine.max_context}")
        use_draft = bool(state.get("draft")) and self.engine.spec_enabled
        deadline_ms = state.get("deadline_ms")
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms is not None else None)
        tid = _trace.new_trace_id()
        fut = DecodeFuture(tid)
        with self._cond:
            if self._closed or self._draining:
                raise ServerClosedError("decoder is shut down")
            if len(self._waiting) >= self.queue_cap:
                self.stats.note_rejected()
                raise ServerBusyError(
                    f"decode queue full ({self.queue_cap}); "
                    "retry with backoff")
            seq = _Sequence(prompt, max_new,
                            int(state.get("priority", 0)), deadline,
                            fut, tid, next(self._order), sp, use_draft)
            seq.generated = generated
            if generated:
                # the preemption-readmission contract: _admit restores
                # last_token without re-emitting the replayed token
                seq.preempted = True
                seq.last_token = generated[-1]
            self._waiting.append(seq)
            self._cond.notify()
        self.stats.note_submitted()
        return fut

    def _handoff_state(self, seq, now=None):
        """JSON-ready resume record for one unfinished sequence (the
        payload of RequestHandedOff / input of admit_resumed)."""
        sp = seq.sampling
        st = {
            "prompt": list(seq.prompt),
            "generated": list(seq.generated),
            "max_new_tokens": seq.max_new,
            "priority": seq.priority,
            "position": len(seq.generated),
            "draft": bool(seq.use_draft),
            "sampling": {"temperature": sp.temperature,
                         "top_k": sp.top_k, "top_p": sp.top_p,
                         "seed": sp.seed},
        }
        if seq.deadline is not None:
            if now is None:
                now = time.monotonic()
            st["deadline_ms"] = max(0.0, (seq.deadline - now) * 1e3)
        return st

    def drain(self, timeout=30):
        """Graceful shutdown with zero request loss: stop admitting,
        let live decodes run to completion for up to `timeout`
        seconds, then hand off whatever is still unfinished — each
        leftover future resolves with RequestHandedOff carrying the
        resume record, and the full list of records is returned so a
        control plane (the fleet router) can re-admit them elsewhere.
        With timeout=0 everything in flight hands off immediately."""
        deadline = time.monotonic() + max(0.0, float(timeout))
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        while time.monotonic() < deadline:
            with self._cond:
                busy = bool(self._waiting) or any(
                    s is not None for s in self._rows)
            if not busy:
                break
            time.sleep(0.01)        # poll outside the lock
        with self._cond:
            self._closed = True
            self._drain = False
            self._handoff = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
        self._fail_leftovers()
        if self.cache is not None:
            self.cache.release_all()
        return [dict(st) for st in self._handoff_states]

    def stop(self, drain=True, timeout=30):
        """Close admission; drain=True finishes in-flight sequences,
        drain=False fails them fast with ServerClosedError."""
        with self._cond:
            self._closed = True
            self._drain = drain
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._fail_leftovers()
        if self.cache is not None:
            # the loop is down: flush the cache's page refs so the
            # pool drains to empty (pages_in_use == 0 after close)
            self.cache.release_all()

    def _fail_leftovers(self):
        """Backstop against stranded futures: if the loop thread is
        down (never started, died on a persistent engine error, or
        outlived its join timeout and then exited) any request still
        queued or rowed would otherwise wait forever. Sweep them into
        a terminal state — handoff records when draining, a
        ServerClosedError otherwise. No-op while the loop is alive
        (it owns the sweep then)."""
        if self._thread is not None and self._thread.is_alive():
            return
        with self._cond:
            leftovers = self._waiting[:]
            self._waiting.clear()
            leftovers.extend(s for s in self._rows if s is not None)
            handoff = self._handoff
        for s in leftovers:
            if s.future.done():
                continue
            if handoff:
                st = self._handoff_state(s)
                self._handoff_states.append(st)
                self.stats.note_cancelled()
                self._resolve(s, exc=RequestHandedOff(st))
            else:
                self.stats.note_failed()
                self._resolve(s, exc=ServerClosedError(
                    "decoder stopped"))

    # ---------------------------------------------------- loop helpers
    def _active(self):
        return [s for s in self._rows if s is not None]

    def _resolve(self, seq, *, exc=None, reason=None):
        """Terminal transition: free pages, clear the row, settle the
        future exactly once."""
        if seq.table is not None:
            self.engine.allocator.free(seq.table)
            seq.table = None
        with self._cond:
            # rows are loop-thread-owned but read under the cond by
            # depth(); publish the clear through the same lock
            for row, s in enumerate(self._rows):
                if s is seq:
                    self._rows[row] = None
        if exc is not None:
            seq.future._fail(exc)
        else:
            self.stats.note_completed()
            seq.future._finish(seq.generated, reason)
        _trace.record_span(
            "decoding.reply", seq.trace_id, seq.t_submit_pc,
            _trace.now(),
            {"model": self.key,
             "outcome": reason or type(exc).__name__,
             "tokens": len(seq.generated)})

    def _preempt(self, seq):
        """Evict for pages: drop the sequence's pages but keep its
        token history; it re-prefills on readmission (bit-identical
        continuation — the XLA prefix-stability property)."""
        if seq.table is not None:
            self.engine.allocator.free(seq.table)
            seq.table = None
        seq.preempted = True
        # a merged-step tail in flight dies with the pages: readmission
        # re-plans the whole prompt (possibly re-matching the cache)
        seq.pending_tail = None
        seq.tail_meta = None
        with self._cond:
            for row, s in enumerate(self._rows):
                if s is seq:
                    self._rows[row] = None
            self._waiting.append(seq)
        self.stats.note_preempted()

    def _reclaim_one(self, requester):
        """Free pages by preempting ONE victim: the lowest-priority
        active sequence, ties broken most-recently-admitted-first.
        The requester itself is a candidate (it may BE the lowest
        priority). Returns the victim, or None when nothing is
        preemptible."""
        victims = self._active()
        if requester is not None and requester.table is None:
            # an admission candidate competes at its own priority
            victims = [s for s in victims
                       if (s.priority, -s.order)
                       < (requester.priority, -requester.order)]
        if not victims:
            return None
        victim = min(victims, key=lambda s: (s.priority, -s.order))
        self._preempt(victim)
        return victim

    def _check_deadlines(self, now):
        """Per-step deadline resolution for BOTH queued and active
        sequences (the decode half of the serving deadline fix)."""
        with self._cond:
            expired = [s for s in self._waiting
                       if s.deadline is not None and now > s.deadline]
            for s in expired:
                self._waiting.remove(s)
        for s in self._active():
            if s.deadline is not None and now > s.deadline:
                expired.append(s)
        for s in expired:
            self.stats.note_expired()
            self._resolve(s, exc=DeadlineExceededError(
                f"deadline passed after {len(s.generated)} tokens"))

    def _check_cancelled(self):
        """Resolve requests whose future (or owning TokenStream) was
        cancelled: queued ones never admit, active ones free their
        pages now instead of decoding to max_tokens."""
        with self._cond:
            doomed = [s for s in self._waiting
                      if s.future._cancel.is_set()]
            for s in doomed:
                self._waiting.remove(s)
        for s in self._active():
            if s.future._cancel.is_set():
                doomed.append(s)
        for s in doomed:
            self.stats.note_cancelled()
            self._resolve(s, reason="cancelled")

    def _free_one_page(self, requester):
        """Make at least one page reclaimable, cheapest source first:
        evict a cached-but-idle prefix run before preempting any live
        sequence (the cache must never cause a preemption). Returns
        False when neither source can yield."""
        if self.cache is not None and self.cache.evict_lru():
            return True
        return self._reclaim_one(requester) is not None

    def _handle_token(self, seq, tok):
        """Post-step bookkeeping for one live row's emitted token."""
        if tok == self.eos_id:
            self._resolve(seq, reason="eos")
            return
        seq.generated.append(tok)
        seq.last_token = tok
        seq.future._emit(tok)
        if len(seq.generated) >= seq.max_new:
            self._resolve(seq, reason="max_tokens")
        elif seq.length >= self.engine.max_context:
            # no page can hold the next position: capacity stop
            self._resolve(seq, reason="length")

    def _finish_tail(self, seq, first_tok):
        """Merged-step tail completion: the bookkeeping a dedicated
        tail-prefill dispatch would have done at admission — prefill
        stats, span record, cache publish, first-token handling —
        deferred to the decode step that wrote the final tail token
        (so cached pages are only published once actually filled)."""
        t0, n_ctx, start, need_total, n_matched = seq.tail_meta
        seq.tail_meta = None
        seq.pending_tail = None
        dt = _trace.now() - t0
        self.stats.note_prefill(n_ctx - start, dt,
                                readmission=seq.preempted)
        _trace.record_span(
            "decoding.prefill", seq.trace_id, t0, t0 + dt,
            {"model": self.key, "tokens": n_ctx,
             "cached_tokens": start, "pages": need_total,
             "pages_reused": n_matched,
             "readmission": seq.preempted, "merged": True})
        if self.cache is not None:
            P = self.engine.page_size
            n_full = len(seq.prompt) // P
            if n_full:
                self.cache.insert(seq.prompt[:n_full * P],
                                  seq.table[:n_full])
        was_preempted, seq.preempted = seq.preempted, False
        if was_preempted and seq.generated:
            # tail replay of a preempted run reproduces the token
            # already emitted; restore, don't re-emit (see _admit)
            seq.last_token = seq.generated[-1]
        else:
            self._handle_token(seq, first_tok)

    # -------------------------------------------------------- admission
    def _admit(self):
        """Fill free batch rows from the waiting queue in (priority,
        FIFO) order. Admission prefers free pages but will preempt
        strictly-lower-priority active sequences to make room.

        With the prefix cache on, admission first maps every full
        prompt page already cached for this token prefix (allocator
        `ref`, the fork path — zero compute) and prefills ONLY the
        tail. The match is capped one page short of the prompt so at
        least one tail token always runs (the prefill program needs a
        position to emit from) — which also keeps cached pages out of
        every write range. After prefill the sequence's own full
        prompt pages are inserted, making them reusable by the next
        request while this one is still decoding.
        """
        alloc = self.engine.allocator
        P = self.engine.page_size
        while None in self._rows:
            with self._cond:
                if not self._waiting:
                    return
                seq = min(self._waiting,
                          key=lambda s: (-s.priority, s.order))
                self._waiting.remove(seq)
            tokens = seq.context_tokens()
            need_total = pages_needed(len(tokens), P)
            matched, start = [], 0
            if self.cache is not None:
                matched, start = self.cache.match(
                    tokens, (len(tokens) - 1) // P)
                self.stats.note_prefix_reuse(len(matched))
            need = need_total - len(matched)
            ok = True
            while alloc.free_pages() < need:
                if not self._free_one_page(seq):
                    # nothing reclaimable below this priority: requeue
                    # and stop admitting (pages may free up later)
                    ok = False
                    break
            if not ok:
                if matched:
                    alloc.free(matched)
                with self._cond:
                    self._waiting.append(seq)
                return
            seq.table = matched + alloc.alloc(need)
            with self._cond:
                row = self._rows.index(None)
                self._rows[row] = seq
            t0 = _trace.now()
            if start and self.engine.merged_step_enabled:
                # merged-step deferral: no tail-prefill dispatch here —
                # the uncached tail rides the next decode step(s) as
                # ragged rows (_grow plans the chunks, _step packs
                # them). length stays at the cached prefix until those
                # rows actually write; the cache insert and the
                # note_prefill/first-token bookkeeping happen at tail
                # completion (_finish_tail), when the pages are real.
                seq.pending_tail = list(tokens[start:])
                seq.length = start
                seq.tail_meta = (t0, len(tokens), start, need_total,
                                 len(matched))
                continue
            first = self.engine.prefill(
                tokens, seq.table, start=start,
                seed=seq.sampling.seed,
                temperature=seq.sampling.temperature,
                top_k=seq.sampling.top_k, top_p=seq.sampling.top_p)
            dt = _trace.now() - t0
            self.stats.note_prefill(len(tokens) - start, dt,
                                    readmission=seq.preempted)
            _trace.record_span(
                "decoding.prefill", seq.trace_id, t0, t0 + dt,
                {"model": self.key, "tokens": len(tokens),
                 "cached_tokens": start, "pages": need_total,
                 "pages_reused": len(matched),
                 "readmission": seq.preempted})
            seq.length = len(tokens)
            if self.cache is not None:
                # publish this prompt's full pages (existing runs keep
                # their pages; only the new suffix takes cache refs)
                n_full = len(seq.prompt) // P
                if n_full:
                    self.cache.insert(seq.prompt[:n_full * P],
                                      seq.table[:n_full])
            was_preempted, seq.preempted = seq.preempted, False
            if was_preempted and seq.generated:
                # the re-prefill reproduces the token already emitted
                # (prefix stability — sampled streams are (seed,
                # position)-pure); restore, don't re-emit. A sequence
                # preempted mid-tail (merged-step mode) may have no
                # token yet — its first token is genuinely new.
                seq.last_token = seq.generated[-1]
            else:
                self._handle_token(seq, int(first))

    # ------------------------------------------------------------ growth
    def _grow(self):
        """Before each step, make every live row's WHOLE write range
        backed by exclusively-owned pages: positions length..length+K
        (K = spec_k in speculative mode, else 0). Allocates across
        page boundaries (evicting cached pages, then preempting,
        under pressure) and breaks COW aliases on every page the step
        may write — rejected speculative entries land in owned pages,
        so rollback-by-truncation never corrupts a shared page."""
        alloc = self.engine.allocator
        P = self.engine.page_size
        k = self.engine.spec_k if self.engine.spec_enabled else 0
        for seq in self._active():
            if seq.table is None or seq.pending_tail:
                continue    # tail seqs: write range planned below
            # pages covering the step's write positions (clamped to
            # capacity: the host stops at max_context before any
            # clamped write could be read back)
            cover = min(seq.length + k + 1, self.engine.max_context)
            need = pages_needed(cover, P)
            while seq.table is not None and len(seq.table) < need:
                try:
                    seq.table.extend(alloc.alloc(1))
                except PagePoolExhausted:
                    if self.cache is not None and self.cache.evict_lru():
                        continue
                    victim = self._reclaim_one(None)
                    if victim is None:
                        break
            if seq.table is None or len(seq.table) < need:
                continue    # preempted itself; back in the queue
            first = seq.length // P
            last = min((cover - 1) // P, len(seq.table) - 1)
            for idx in range(first, last + 1):
                page, copy_from = None, None
                while seq.table is not None:
                    try:
                        page, copy_from = alloc.make_writable(
                            seq.table, idx)
                        break
                    except PagePoolExhausted:
                        # COW needs one free page: cheapest first
                        if (self.cache is not None
                                and self.cache.evict_lru()):
                            continue
                        self._preempt(seq)
                if seq.table is None or page is None:
                    break
                if copy_from is not None:
                    self.engine.copy_page(copy_from, page)
        # merged-step tail plan: split this step's tail_budget extra
        # rows across sequences still holding a pending prompt tail,
        # sizing each one's page table for the chunk it will write.
        # Tail pages sit past the cached prefix (the cache matches
        # full pages only), so they are exclusively owned — the
        # make_writable pass below is the same COW discipline as
        # above and never copies in practice.
        self._tail_plan = []
        if not self.engine.merged_step_enabled:
            return
        budget = self.engine.tail_budget
        for seq in self._active():
            if budget <= 0:
                break
            if seq.table is None or not seq.pending_tail:
                continue
            chunk = min(len(seq.pending_tail), budget)
            cover = min(seq.length + chunk, self.engine.max_context)
            need = pages_needed(cover, P)
            while seq.table is not None and len(seq.table) < need:
                try:
                    seq.table.extend(alloc.alloc(1))
                except PagePoolExhausted:
                    if self.cache is not None and self.cache.evict_lru():
                        continue
                    if self._reclaim_one(None) is None:
                        break
            if seq.table is None or len(seq.table) < need:
                continue
            first = seq.length // P
            last = min((cover - 1) // P, len(seq.table) - 1)
            ok = True
            for idx in range(first, last + 1):
                page, copy_from = None, None
                while seq.table is not None:
                    try:
                        page, copy_from = alloc.make_writable(
                            seq.table, idx)
                        break
                    except PagePoolExhausted:
                        if (self.cache is not None
                                and self.cache.evict_lru()):
                            continue
                        self._preempt(seq)
                if seq.table is None or page is None:
                    ok = False
                    break
                if copy_from is not None:
                    self.engine.copy_page(copy_from, page)
            if ok and seq.table is not None:
                self._tail_plan.append((seq, chunk))
                budget -= chunk

    # -------------------------------------------------------------- step
    def _step(self):
        engine = self.engine
        live = [(row, s) for row, s in enumerate(self._rows)
                if s is not None]
        if not live:
            return
        b = engine.max_batch
        r = engine.step_rows        # == b + tail_budget when merged
        spec = engine.spec_enabled
        k = engine.spec_k if spec else 0
        # _grow already sized every table for the full write range;
        # span over table lengths keeps the bucket consistent with it
        span = max(len(s.table) for _, s in live)
        bucket = pick_bucket(span, engine.page_buckets)
        tokens = np.zeros((r,), np.int32)
        table = np.full((r, bucket), SCRATCH_PAGE, np.int32)
        lengths = np.zeros((r,), np.int32)
        active = np.zeros((r,), bool)
        use_draft = np.zeros((r,), bool)
        seeds = np.zeros((r,), np.uint32)
        temps = np.zeros((r,), np.float32)
        top_ks = np.zeros((r,), np.int32)
        top_ps = np.ones((r,), np.float32)
        for row, s in live:
            if s.pending_tail:
                continue    # fed through the ragged tail rows below
            tokens[row] = s.last_token
            table[row, :len(s.table)] = s.table
            lengths[row] = s.length
            active[row] = True
            use_draft[row] = s.use_draft
            seeds[row] = s.sampling.seed & 0xFFFFFFFF
            temps[row] = s.sampling.temperature
            top_ks[row] = s.sampling.top_k
            top_ps[row] = s.sampling.top_p
        # ragged rows b..r-1: planned prompt-tail chunks ride the same
        # fixed-shape step. Row j of a chunk holds prompt token at
        # absolute position lengths[row] (= count of context tokens
        # already written); the kernel's per-row length masking gives
        # intra-chunk causality for free, and the chunk's LAST row
        # samples the sequence's first token at its true position —
        # bit-identical to the dedicated tail-prefill program.
        tail_rows = []
        next_row = b
        for seq, chunk in self._tail_plan:
            if seq.table is None or not seq.pending_tail \
                    or seq.future.done():
                continue    # resolved/preempted after planning
            chunk = min(chunk, len(seq.pending_tail))
            for j in range(chunk):
                row = next_row
                next_row += 1
                tokens[row] = seq.pending_tail[j]
                table[row, :len(seq.table)] = seq.table
                lengths[row] = seq.length + j
                active[row] = True
                seeds[row] = seq.sampling.seed & 0xFFFFFFFF
                temps[row] = seq.sampling.temperature
                top_ks[row] = seq.sampling.top_k
                top_ps[row] = seq.sampling.top_p
            tail_rows.append((seq, next_row - 1, chunk))
        t0 = _trace.now()
        if spec:
            out, n_emit = engine.spec_step(
                tokens, table, lengths, active, use_draft,
                seeds, temps, top_ks, top_ps)
        else:
            out = engine.step(tokens, table, lengths, active,
                              seeds, temps, top_ks, top_ps)
        dt = _trace.now() - t0
        emitted = 0
        if spec:
            for row, s in live:
                n = int(n_emit[row])
                if s.use_draft:
                    self.stats.note_spec(k, n - 1)
                for j in range(n):
                    if s.table is None or s.future.done():
                        break   # resolved mid-run (eos/max_tokens)
                    s.length += 1
                    emitted += 1
                    self._handle_token(s, int(out[row, j]))
        else:
            for row, s in live:
                if s.pending_tail:
                    continue    # decode row was inactive this step
                s.length += 1
                emitted += 1
                self._handle_token(s, int(out[row]))
            for seq, last_row, chunk in tail_rows:
                if seq.table is None or seq.future.done():
                    continue
                seq.length += chunk
                del seq.pending_tail[:chunk]
                if not seq.pending_tail:
                    # tail tokens are prefill work, not emitted tokens:
                    # counted via note_prefill in _finish_tail
                    self._finish_tail(seq, int(out[last_row]))
        self.stats.note_step(emitted, dt)
        _trace.record_span(
            "decoding.step", None, t0, t0 + dt,
            {"trace_ids": tuple(s.trace_id for _, s in live),
             "model": self.key, "live": len(live), "bucket": bucket,
             "tokens": emitted})
        self.stats.note_pool()
        if engine._guard and self.stats.steps % 16 == 0:
            # interval drain of the numerics guard (one fetch per 16
            # steps); nonfinite rows surface in nonfinite_*, dequant-
            # overflow clips in quant_clip_* (decodingStats view)
            for nf, clips in engine.drain_guard():
                if nf:
                    self.stats.note_nonfinite(nf)
                if clips:
                    self.stats.note_quant_clips(clips)

    # -------------------------------------------------------------- loop
    def _loop(self):
        while True:
            with self._cond:
                while (not self._closed and not self._waiting
                       and not any(self._rows)):
                    # bounded wait so queued-only deadline expiry is
                    # still timely under an idle engine
                    self._cond.wait(0.05)
                if self._closed:
                    if not self._drain:
                        doomed = self._waiting[:]
                        self._waiting.clear()
                    elif not self._waiting and not any(self._rows):
                        return
            if self._closed and not self._drain:
                doomed.extend(self._active())
                if self._handoff:
                    # drain() timed out with work in flight: every
                    # leftover resolves with its resume record
                    now = time.monotonic()
                    for s in doomed:
                        st = self._handoff_state(s, now)
                        self._handoff_states.append(st)
                        self.stats.note_cancelled()
                        self._resolve(s, exc=RequestHandedOff(st))
                else:
                    for s in doomed:
                        self.stats.note_failed()
                        self._resolve(s, exc=ServerClosedError(
                            "decoder stopped"))
                return
            try:
                self._check_deadlines(time.monotonic())
                self._check_cancelled()
                self._admit()
                self._grow()
                self._step()
            except Exception as exc:  # never kill the loop silently
                for s in self._active():
                    self.stats.note_failed()
                    self._resolve(s, exc=exc)
                with self._cond:
                    bail = self._closed
                    stranded = self._waiting[:] if bail else []
                    if bail:
                        self._waiting.clear()
                if bail:
                    # shutting down on a persistently-raising engine:
                    # spinning admit->fail forever would outlive the
                    # join timeout and strand the queue — fail it and
                    # exit (stop()/drain() backstops anything admitted
                    # between the sweep above and this return)
                    for s in stranded:
                        self.stats.note_failed()
                        self._resolve(s, exc=exc)
                    return


class DecodedModel:
    """One loaded decoder: engine + scheduler + stats (the decode-tier
    sibling of registry.ServedModel; `ModelServer.load_decoder` is the
    usual way to construct one)."""

    def __init__(self, name, version, params, cfg, *, max_batch=None,
                 page_size=None, num_pages=None, page_buckets=None,
                 kernel=None, ring_prefill=None, queue_cap=None,
                 max_tokens=None, warmup=True, draft=None,
                 draft_cfg=None, spec_k=None, prefix_cache=None,
                 merged_step=None, kv_dtype=None):
        self.name = name
        self.version = int(version)
        self.cfg = cfg
        # draft spec: a params dict (with draft_cfg), the string
        # "self" (self-draft: the target drafts for itself — useful
        # for tests/CI where acceptance is then ~1), or None to read
        # MXNET_DECODE_SPEC_DRAFT
        if draft is None and _cfg.spec_draft():
            draft = _cfg.spec_draft()
        draft_params = None
        if isinstance(draft, str):
            if draft == "self":
                draft_params, draft_cfg = params, cfg
            elif draft:
                raise ServingError(
                    f"unknown draft spec {draft!r} (expected 'self' "
                    "or a params dict)")
        elif draft is not None:
            draft_params = draft
            draft_cfg = draft_cfg if draft_cfg is not None else cfg
        self.engine = DecodeEngine(
            params, cfg, max_batch=max_batch, page_size=page_size,
            num_pages=num_pages, page_buckets=page_buckets,
            kernel=kernel, ring_prefill=ring_prefill,
            draft_params=draft_params, draft_cfg=draft_cfg,
            spec_k=spec_k, prefix_cache=prefix_cache,
            merged_step=merged_step, kv_dtype=kv_dtype)
        self.stats = DecodeStats(
            key=self.key, traces_fn=self.engine.traces,
            pool_fn=self.engine.pool_stats)
        self.scheduler = ContinuousScheduler(
            self.engine, self.stats, self.key, queue_cap=queue_cap,
            max_tokens=max_tokens)
        self.stats._depth_fn = self.scheduler.depth
        if self.scheduler.cache is not None:
            self.stats._prefix_fn = self.scheduler.cache.stats
        self._started = False
        if warmup:
            self.warmup()

    @property
    def key(self):
        return f"{self.name}:{self.version}"

    def warmup(self):
        """Pre-trace the full decode grid and latch the trace floor;
        the scheduler thread starts only once the model is warm (the
        ServedModel readiness contract)."""
        self.engine.warmup()
        self.stats.mark_warmup_done()
        if not self._started:
            self.scheduler.start()
            self._started = True
        return self

    # -------------------------------------------------------- data path
    def submit(self, prompt, max_new_tokens=None, priority=0,
               deadline_ms=None, sampling=None, seed=None, draft=None):
        return self.scheduler.submit(prompt,
                                     max_new_tokens=max_new_tokens,
                                     priority=priority,
                                     deadline_ms=deadline_ms,
                                     sampling=sampling, seed=seed,
                                     draft=draft)

    def generate(self, prompt, max_new_tokens=None, priority=0,
                 deadline_ms=None, timeout=None, sampling=None,
                 seed=None, draft=None):
        """Sync decode: the full generated token list."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           priority=priority, deadline_ms=deadline_ms,
                           sampling=sampling, seed=seed,
                           draft=draft).result(timeout)

    def stream(self, prompt, max_new_tokens=None, priority=0,
               deadline_ms=None, timeout=None, sampling=None,
               seed=None, draft=None):
        """Streaming decode: a TokenStream yielding tokens as steps
        complete. Close it (or exit its `with` block) to cancel an
        unfinished request and free its pages."""
        fut = self.submit(prompt, max_new_tokens=max_new_tokens,
                          priority=priority, deadline_ms=deadline_ms,
                          sampling=sampling, seed=seed, draft=draft)
        return fut.stream(timeout=timeout)

    def admit_resumed(self, state):
        """Admit a handed-off request (see ContinuousScheduler
        .admit_resumed): returns a DecodeFuture whose stream emits
        only the tokens not yet delivered elsewhere."""
        return self.scheduler.admit_resumed(state)

    def drain(self, timeout=30):
        """Stop admitting, finish live decodes (up to `timeout` s),
        hand off the rest; returns the handoff records."""
        return self.scheduler.drain(timeout=timeout)

    def close(self, drain=True, timeout=30):
        self.scheduler.stop(drain=drain, timeout=timeout)

"""Continuous-batching scheduler: the control loop of the decode tier.

One scheduler thread per decoder model drives a fixed-shape
`DecodeEngine` step loop. Unlike the one-shot batcher (which forms a
batch, runs it, and replies), the decode batch is a ROLLING set: every
step the scheduler

  1. resolves per-sequence deadlines (mid-generation, not just at
     admission — a stuck client's sequence frees its pages promptly),
  2. admits waiting requests into free batch rows (prefill: one
     bucket-padded prompt pass that scatters K/V into fresh pages),
  3. grows each live sequence's page table by one page when its next
     token crosses a page boundary — preempting the lowest-priority
     (ties: most recently admitted) sequence when the pool is
     exhausted, never crashing (CI gate iii),
  4. runs ONE fixed-shape decode step over the full (max_batch,
     pages_bucket) grid and streams each live row's token out.

Preemption drops a sequence's pages but keeps its token history; on
readmission the scheduler re-prefills prompt + generated-so-far and
the continuation is bit-identical to the uninterrupted run (the
XLA-level prefix stability tests/test_decoding.py pins).

Tokens reach callers through `DecodeFuture`: `result()` is the full
generated list (the serving Future contract), `stream()` yields tokens
as steps complete — cancellation-free backpressure is the consumer
just not reading; the queue is per-request and bounded by max_tokens.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time

import numpy as np

from ..serving.batcher import (DeadlineExceededError, ServerBusyError,
                               ServerClosedError, ServingError,
                               pick_bucket)
from ..telemetry import trace as _trace
from . import config as _cfg
from .blocks import SCRATCH_PAGE, PagePoolExhausted, pages_needed
from .engine import DecodeEngine
from .stats import DecodeStats

_DONE = object()


class DecodeFuture:
    """Handle for one decode request: both a future and a stream.

    `result(timeout)` blocks for the COMPLETE generated token list
    (EOS excluded) or raises the request's failure. `stream(timeout)`
    iterates tokens as the scheduler emits them — the first token
    arrives right after prefill, the rest one per decode step — and
    raises the failure mid-iteration if one lands. `finish_reason` is
    "eos" | "max_tokens" | "length" after completion.
    """

    def __init__(self, trace_id=None):
        self.trace_id = trace_id
        self.finish_reason = None
        self._q = queue.Queue()
        self._done = threading.Event()
        self._tokens = None
        self._exc = None

    # ---------------------------------------------- scheduler side
    def _emit(self, tok):
        self._q.put(int(tok))

    def _finish(self, tokens, reason):
        self.finish_reason = reason
        self._tokens = list(tokens)
        self._done.set()
        self._q.put(_DONE)

    def _fail(self, exc):
        self._exc = exc
        self._done.set()
        self._q.put(exc)

    # ------------------------------------------------- caller side
    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("decode request still running")
        if self._exc is not None:
            raise self._exc
        return list(self._tokens)

    def exception(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("decode request still running")
        return self._exc

    def stream(self, timeout=None):
        """Yield generated tokens as they are produced."""
        while True:
            item = self._q.get(timeout=timeout)
            if item is _DONE:
                return
            if isinstance(item, BaseException):
                raise item
            yield item


class _Sequence:
    """Scheduler-internal state of one in-flight request."""

    __slots__ = ("prompt", "max_new", "priority", "deadline", "future",
                 "trace_id", "order", "generated", "table", "length",
                 "last_token", "preempted", "t_submit_pc")

    def __init__(self, prompt, max_new, priority, deadline, future,
                 trace_id, order):
        self.prompt = list(prompt)
        self.max_new = max_new
        self.priority = priority
        self.deadline = deadline       # absolute monotonic, or None
        self.future = future
        self.trace_id = trace_id
        self.order = order             # admission tiebreak (FIFO)
        self.generated = []
        self.table = None              # page ids while active
        self.length = 0                # tokens materialized in cache
        self.last_token = -1
        self.preempted = False
        self.t_submit_pc = _trace.now()

    def context_tokens(self):
        """Tokens the KV cache must hold for this sequence: the prompt
        plus everything generated EXCEPT the newest token (whose K/V
        is appended by the next decode step)."""
        return self.prompt + self.generated[:-1] \
            if self.generated else list(self.prompt)


class ContinuousScheduler:
    """The rolling-batch control loop over one DecodeEngine."""

    def __init__(self, engine, stats, key, queue_cap=None,
                 max_tokens=None, eos_id=None):
        self.engine = engine
        self.stats = stats
        self.key = key
        self.queue_cap = queue_cap if queue_cap is not None \
            else _cfg.queue_cap()
        self.default_max_tokens = max_tokens if max_tokens is not None \
            else _cfg.max_tokens()
        self.eos_id = eos_id if eos_id is not None \
            else engine.cfg.eos_id
        self._cond = threading.Condition()
        self._waiting = []
        self._rows = [None] * engine.max_batch
        self._order = itertools.count()
        self._closed = False
        self._drain = True
        self._thread = None

    # ------------------------------------------------------ public API
    def start(self):
        self._thread = threading.Thread(
            target=self._loop, name=f"decoding-{self.key}", daemon=True)
        self._thread.start()
        return self

    def depth(self):
        """(waiting, active) — the stats view's queue-depth probe."""
        with self._cond:
            return (len(self._waiting),
                    sum(1 for s in self._rows if s is not None))

    def submit(self, prompt, max_new_tokens=None, priority=0,
               deadline_ms=None):
        """Enqueue one autoregressive request; returns a DecodeFuture.

        `priority`: higher values survive page-pool pressure longer
        (preemption victims are chosen lowest-priority-first).
        `deadline_ms` is end-to-end and checked EVERY step, not only
        at admission — a mid-generation miss resolves the future with
        DeadlineExceededError and frees the sequence's pages.
        """
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ServingError("empty prompt")
        if any(t < 0 or t >= self.engine.cfg.vocab for t in prompt):
            raise ServingError("prompt token outside vocab")
        if len(prompt) > self.engine.max_context:
            raise ServingError(
                f"prompt of {len(prompt)} tokens exceeds the decode "
                f"context capacity {self.engine.max_context}")
        max_new = int(max_new_tokens) if max_new_tokens is not None \
            else self.default_max_tokens
        if max_new < 1:
            raise ServingError("max_new_tokens must be >= 1")
        tid = _trace.new_trace_id()
        with _trace.span("decoding.submit", trace_id=tid,
                         model=self.key):
            deadline = (time.monotonic() + deadline_ms / 1e3
                        if deadline_ms is not None else None)
            fut = DecodeFuture(tid)
            with self._cond:
                if self._closed:
                    raise ServerClosedError("decoder is shut down")
                if len(self._waiting) >= self.queue_cap:
                    self.stats.note_rejected()
                    raise ServerBusyError(
                        f"decode queue full ({self.queue_cap}); "
                        "retry with backoff")
                seq = _Sequence(prompt, max_new, int(priority),
                                deadline, fut, tid, next(self._order))
                self._waiting.append(seq)
                self._cond.notify()
        self.stats.note_submitted()
        return fut

    def stop(self, drain=True, timeout=30):
        """Close admission; drain=True finishes in-flight sequences,
        drain=False fails them fast with ServerClosedError."""
        with self._cond:
            self._closed = True
            self._drain = drain
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # ---------------------------------------------------- loop helpers
    def _active(self):
        return [s for s in self._rows if s is not None]

    def _resolve(self, seq, *, exc=None, reason=None):
        """Terminal transition: free pages, clear the row, settle the
        future exactly once."""
        if seq.table is not None:
            self.engine.allocator.free(seq.table)
            seq.table = None
        with self._cond:
            # rows are loop-thread-owned but read under the cond by
            # depth(); publish the clear through the same lock
            for row, s in enumerate(self._rows):
                if s is seq:
                    self._rows[row] = None
        if exc is not None:
            seq.future._fail(exc)
        else:
            self.stats.note_completed()
            seq.future._finish(seq.generated, reason)
        _trace.record_span(
            "decoding.reply", seq.trace_id, seq.t_submit_pc,
            _trace.now(),
            {"model": self.key,
             "outcome": reason or type(exc).__name__,
             "tokens": len(seq.generated)})

    def _preempt(self, seq):
        """Evict for pages: drop the sequence's pages but keep its
        token history; it re-prefills on readmission (bit-identical
        continuation — the XLA prefix-stability property)."""
        if seq.table is not None:
            self.engine.allocator.free(seq.table)
            seq.table = None
        seq.preempted = True
        with self._cond:
            for row, s in enumerate(self._rows):
                if s is seq:
                    self._rows[row] = None
            self._waiting.append(seq)
        self.stats.note_preempted()

    def _reclaim_one(self, requester):
        """Free pages by preempting ONE victim: the lowest-priority
        active sequence, ties broken most-recently-admitted-first.
        The requester itself is a candidate (it may BE the lowest
        priority). Returns the victim, or None when nothing is
        preemptible."""
        victims = self._active()
        if requester is not None and requester.table is None:
            # an admission candidate competes at its own priority
            victims = [s for s in victims
                       if (s.priority, -s.order)
                       < (requester.priority, -requester.order)]
        if not victims:
            return None
        victim = min(victims, key=lambda s: (s.priority, -s.order))
        self._preempt(victim)
        return victim

    def _check_deadlines(self, now):
        """Per-step deadline resolution for BOTH queued and active
        sequences (the decode half of the serving deadline fix)."""
        with self._cond:
            expired = [s for s in self._waiting
                       if s.deadline is not None and now > s.deadline]
            for s in expired:
                self._waiting.remove(s)
        for s in self._active():
            if s.deadline is not None and now > s.deadline:
                expired.append(s)
        for s in expired:
            self.stats.note_expired()
            self._resolve(s, exc=DeadlineExceededError(
                f"deadline passed after {len(s.generated)} tokens"))

    def _handle_token(self, seq, tok):
        """Post-step bookkeeping for one live row's emitted token."""
        if tok == self.eos_id:
            self._resolve(seq, reason="eos")
            return
        seq.generated.append(tok)
        seq.last_token = tok
        seq.future._emit(tok)
        if len(seq.generated) >= seq.max_new:
            self._resolve(seq, reason="max_tokens")
        elif seq.length >= self.engine.max_context:
            # no page can hold the next position: capacity stop
            self._resolve(seq, reason="length")

    # -------------------------------------------------------- admission
    def _admit(self):
        """Fill free batch rows from the waiting queue in (priority,
        FIFO) order. Admission prefers free pages but will preempt
        strictly-lower-priority active sequences to make room."""
        alloc = self.engine.allocator
        while None in self._rows:
            with self._cond:
                if not self._waiting:
                    return
                seq = min(self._waiting,
                          key=lambda s: (-s.priority, s.order))
                self._waiting.remove(seq)
            tokens = seq.context_tokens()
            need = pages_needed(len(tokens), self.engine.page_size)
            while alloc.free_pages() < need:
                if self._reclaim_one(seq) is None:
                    # nothing below this priority to evict: requeue
                    # and stop admitting (pages may free up later)
                    with self._cond:
                        self._waiting.append(seq)
                    return
            seq.table = alloc.alloc(need)
            with self._cond:
                row = self._rows.index(None)
                self._rows[row] = seq
            t0 = _trace.now()
            first = self.engine.prefill(tokens, seq.table)
            dt = _trace.now() - t0
            self.stats.note_prefill(len(tokens), dt,
                                    readmission=seq.preempted)
            _trace.record_span(
                "decoding.prefill", seq.trace_id, t0, t0 + dt,
                {"model": self.key, "tokens": len(tokens),
                 "pages": need, "readmission": seq.preempted})
            seq.length = len(tokens)
            if seq.preempted:
                # the re-prefill's argmax reproduces the token already
                # emitted (prefix stability); restore, don't re-emit
                seq.preempted = False
                seq.last_token = seq.generated[-1]
            else:
                self._handle_token(seq, int(first))

    # ------------------------------------------------------------ growth
    def _grow(self):
        """Before each step, make every live row's write position
        backed by an exclusively-owned page: allocate across page
        boundaries (preempting under pressure) and break COW aliases
        on the tail page."""
        alloc = self.engine.allocator
        for seq in self._active():
            if seq.table is None:
                continue
            idx = seq.length // self.engine.page_size
            if idx >= len(seq.table):
                while True:
                    try:
                        seq.table.extend(alloc.alloc(1))
                        break
                    except PagePoolExhausted:
                        victim = self._reclaim_one(None)
                        if victim is None or victim is seq:
                            break
                if seq.table is None or idx >= len(seq.table):
                    continue    # preempted itself; back in the queue
            try:
                page, copy_from = alloc.make_writable(seq.table, idx)
            except PagePoolExhausted:
                self._preempt(seq)
                continue
            if copy_from is not None:
                self.engine.copy_page(copy_from, page)

    # -------------------------------------------------------------- step
    def _step(self):
        engine = self.engine
        live = [(row, s) for row, s in enumerate(self._rows)
                if s is not None]
        if not live:
            return
        b = engine.max_batch
        span = max(pages_needed(s.length + 1, engine.page_size)
                   for _, s in live)
        bucket = pick_bucket(span, engine.page_buckets)
        tokens = np.zeros((b,), np.int32)
        table = np.full((b, bucket), SCRATCH_PAGE, np.int32)
        lengths = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        for row, s in live:
            tokens[row] = s.last_token
            table[row, :len(s.table)] = s.table
            lengths[row] = s.length
            active[row] = True
        t0 = _trace.now()
        out = engine.step(tokens, table, lengths, active)
        dt = _trace.now() - t0
        self.stats.note_step(len(live), dt)
        _trace.record_span(
            "decoding.step", None, t0, t0 + dt,
            {"trace_ids": tuple(s.trace_id for _, s in live),
             "model": self.key, "live": len(live), "bucket": bucket})
        for row, s in live:
            s.length += 1
            self._handle_token(s, int(out[row]))
        self.stats.note_pool()
        if engine._guard and self.stats.steps % 16 == 0:
            # interval drain of the logits guard (one fetch per 16
            # steps); counts surface in decodingStats/nonfinite_*
            for n in engine.drain_guard():
                if n:
                    self.stats.note_nonfinite(n)

    # -------------------------------------------------------------- loop
    def _loop(self):
        while True:
            with self._cond:
                while (not self._closed and not self._waiting
                       and not any(self._rows)):
                    # bounded wait so queued-only deadline expiry is
                    # still timely under an idle engine
                    self._cond.wait(0.05)
                if self._closed:
                    if not self._drain:
                        doomed = self._waiting[:]
                        self._waiting.clear()
                    elif not self._waiting and not any(self._rows):
                        return
            if self._closed and not self._drain:
                doomed.extend(self._active())
                for s in doomed:
                    self.stats.note_failed()
                    self._resolve(s, exc=ServerClosedError(
                        "decoder stopped"))
                return
            try:
                self._check_deadlines(time.monotonic())
                self._admit()
                self._grow()
                self._step()
            except Exception as exc:  # never kill the loop silently
                for s in self._active():
                    self.stats.note_failed()
                    self._resolve(s, exc=exc)


class DecodedModel:
    """One loaded decoder: engine + scheduler + stats (the decode-tier
    sibling of registry.ServedModel; `ModelServer.load_decoder` is the
    usual way to construct one)."""

    def __init__(self, name, version, params, cfg, *, max_batch=None,
                 page_size=None, num_pages=None, page_buckets=None,
                 kernel=None, ring_prefill=None, queue_cap=None,
                 max_tokens=None, warmup=True):
        self.name = name
        self.version = int(version)
        self.cfg = cfg
        self.engine = DecodeEngine(
            params, cfg, max_batch=max_batch, page_size=page_size,
            num_pages=num_pages, page_buckets=page_buckets,
            kernel=kernel, ring_prefill=ring_prefill)
        self.stats = DecodeStats(
            key=self.key, traces_fn=self.engine.traces,
            pool_fn=self.engine.pool_stats)
        self.scheduler = ContinuousScheduler(
            self.engine, self.stats, self.key, queue_cap=queue_cap,
            max_tokens=max_tokens)
        self.stats._depth_fn = self.scheduler.depth
        self._started = False
        if warmup:
            self.warmup()

    @property
    def key(self):
        return f"{self.name}:{self.version}"

    def warmup(self):
        """Pre-trace the full decode grid and latch the trace floor;
        the scheduler thread starts only once the model is warm (the
        ServedModel readiness contract)."""
        self.engine.warmup()
        self.stats.mark_warmup_done()
        if not self._started:
            self.scheduler.start()
            self._started = True
        return self

    # -------------------------------------------------------- data path
    def submit(self, prompt, max_new_tokens=None, priority=0,
               deadline_ms=None):
        return self.scheduler.submit(prompt,
                                     max_new_tokens=max_new_tokens,
                                     priority=priority,
                                     deadline_ms=deadline_ms)

    def generate(self, prompt, max_new_tokens=None, priority=0,
                 deadline_ms=None, timeout=None):
        """Sync decode: the full generated token list."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           priority=priority,
                           deadline_ms=deadline_ms).result(timeout)

    def stream(self, prompt, max_new_tokens=None, priority=0,
               deadline_ms=None, timeout=None):
        """Streaming decode: yields tokens as steps complete."""
        fut = self.submit(prompt, max_new_tokens=max_new_tokens,
                          priority=priority, deadline_ms=deadline_ms)
        return fut.stream(timeout=timeout)

    def close(self, drain=True, timeout=30):
        self.scheduler.stop(drain=drain, timeout=timeout)

"""Env-knob resolution for the decode tier (registered in
mxnet_tpu.utils so `describe_env()`/docs/env_vars.md cover them).

Resolution order everywhere: explicit constructor argument > MXNET_*
env var > built-in default (the serving/config.py convention).
"""
from __future__ import annotations

from .. import utils
from ..serving.batcher import _parse_buckets


def page_size():
    return utils.getenv("MXNET_DECODE_PAGE_SIZE")


def num_pages():
    return utils.getenv("MXNET_DECODE_PAGES")


def max_batch():
    return utils.getenv("MXNET_DECODE_MAX_BATCH")


def page_buckets():
    raw = utils.getenv("MXNET_DECODE_PAGE_BUCKETS")
    return _parse_buckets(raw) if raw else None


def kernel():
    # read through the codegen config: MXNET_DECODE_KERNEL is part of
    # the one kernel-generation switch surface (passes.pallas_codegen)
    from ..passes import codegen_config

    return codegen_config().decode_kernel


def merged_step():
    return bool(utils.getenv("MXNET_DECODE_MERGED_STEP"))


def kv_dtype():
    # KV-page storage precision: float32 | bf16 | int8 (fp8 reserved);
    # validated/normalized by decoding.quant.canonical at engine build
    return str(utils.getenv("MXNET_DECODE_KV_DTYPE") or "float32")


def ring_prefill():
    return utils.getenv("MXNET_DECODE_RING_PREFILL")


def max_tokens():
    return utils.getenv("MXNET_DECODE_MAX_TOKENS")


def queue_cap():
    return utils.getenv("MXNET_DECODE_QUEUE_CAP")


def prefix_cache():
    return bool(utils.getenv("MXNET_DECODE_PREFIX_CACHE"))


def spec_k():
    return utils.getenv("MXNET_DECODE_SPEC_K")


def spec_draft():
    return utils.getenv("MXNET_DECODE_SPEC_DRAFT")


def sampling_temperature():
    return utils.getenv("MXNET_DECODE_SAMPLING_TEMPERATURE")


def sampling_top_k():
    return utils.getenv("MXNET_DECODE_SAMPLING_TOP_K")


def sampling_top_p():
    return utils.getenv("MXNET_DECODE_SAMPLING_TOP_P")


def sampling_seed():
    return utils.getenv("MXNET_DECODE_SAMPLING_SEED")


def default_page_buckets(max_pages_per_seq):
    """Powers of two up to max_pages_per_seq (inclusive): each bucket
    is one compiled decode program, so the grid stays logarithmic."""
    out, b = [], 1
    while b < max_pages_per_seq:
        out.append(b)
        b *= 2
    out.append(max_pages_per_seq)
    return tuple(out)

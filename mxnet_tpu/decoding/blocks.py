"""Block allocator + page tables: the host half of the paged KV cache.

The decode tier's memory problem (Ragged Paged Attention, PAPERS.md):
in-flight sequences have wildly different lengths and grow one token
per step, so a rectangular (batch, max_len) KV buffer wastes most of
its rows and forces the worst-case length on every sequence. Instead
the device holds ONE pool of fixed-size pages (`MXNET_DECODE_PAGE_SIZE`
tokens each) and every sequence owns a *page table* — an ordered list
of page ids covering its context. Allocation quantum = one page, so
per-sequence waste is bounded by page_size-1 tokens regardless of
length mix.

This module is pure host-side bookkeeping (no jax import): a free-list
allocator with reference counts. Ref counts make prefix sharing and
fork cheap: `fork()` returns a table aliasing every page (ref++), and
`make_writable()` implements copy-on-write — the first write to a
shared page allocates a private copy (the caller performs the actual
device page copy; the allocator only decides).

Page 0 is RESERVED as the scratch page: padding page-table entries and
inactive batch rows point at it, so the device kernel can always
gather/scatter a full (max_batch, pages_bucket) grid with no branch —
garbage lands in (or comes from) page 0 and is masked out by sequence
length. Page 0 is never handed to a sequence.
"""
from __future__ import annotations

import threading

from ..base import MXNetError

SCRATCH_PAGE = 0


class PageError(MXNetError):
    """Base class of paged-KV allocator errors."""


class PagePoolExhausted(PageError):
    """No free pages: the caller should preempt or shed load, never
    crash (CI gate iii proves the scheduler does)."""


def pages_needed(num_tokens, page_size):
    """Pages covering `num_tokens` positions (ceil division; 0 -> 0)."""
    return (int(num_tokens) + page_size - 1) // page_size


class BlockAllocator:
    """Free-list allocator over a pool of `num_pages` fixed-size pages.

    Thread-safe; all operations are O(pages touched). Invariants
    (checked by `check()` and tests/test_decoding.py):

      * every page is free XOR has refcount >= 1,
      * page 0 (scratch) is permanently pinned, never allocated,
      * free pages hold refcount 0 and appear exactly once in the
        free list.
    """

    def __init__(self, num_pages, page_size):
        if num_pages < 2:
            raise PageError(
                f"pool needs >= 2 pages (1 is reserved scratch), "
                f"got {num_pages}")
        if page_size < 1:
            raise PageError(f"invalid page_size {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        # LIFO free list: recently-freed pages are reused first, which
        # keeps the working set of touched pages small
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._refs = [0] * self.num_pages
        self._refs[SCRATCH_PAGE] = 1  # pinned forever
        self._low_watermark = len(self._free)
        # cumulative pages ever allocated (alloc + COW copies): the
        # work-avoided evidence of prefix sharing — a cache hit refs
        # instead of allocating, so this counter, not occupancy, is
        # what the decode-gate's shared-prefix arm compares
        self._allocated_total = 0

    # ------------------------------------------------------------ state
    def free_pages(self):
        with self._lock:
            return len(self._free)

    def pages_in_use(self):
        with self._lock:
            return (self.num_pages - 1) - len(self._free)

    def capacity(self):
        """Allocatable pages (pool minus the pinned scratch page)."""
        return self.num_pages - 1

    def occupancy(self):
        """Fraction of allocatable pages currently owned."""
        with self._lock:
            used = (self.num_pages - 1) - len(self._free)
        return used / max(1, self.num_pages - 1)

    def low_watermark(self):
        """Fewest free pages ever observed (capacity-planning signal)."""
        with self._lock:
            return self._low_watermark

    def refcount(self, page):
        with self._lock:
            return self._refs[page]

    # ------------------------------------------------------- operations
    def alloc(self, n=1):
        """n fresh pages with refcount 1, or PagePoolExhausted (the
        allocation is all-or-nothing: no partial grab to roll back)."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                raise PagePoolExhausted(
                    f"need {n} pages, {len(self._free)} free "
                    f"(pool {self.num_pages - 1}); preempt or wait")
            out = [self._free.pop() for _ in range(n)]
            for p in out:
                self._refs[p] = 1
            self._allocated_total += n
            if len(self._free) < self._low_watermark:
                self._low_watermark = len(self._free)
            return out

    def ref(self, pages):
        """Share: refcount++ on each page of an allocated table."""
        with self._lock:
            for p in pages:
                if p == SCRATCH_PAGE:
                    continue
                if self._refs[p] <= 0:
                    raise PageError(f"ref of free page {p}")
                self._refs[p] += 1

    def free(self, pages):
        """Release ownership: refcount--, returning pages whose count
        hit zero to the free list. Scratch entries are ignored, so a
        padded table can be freed wholesale."""
        with self._lock:
            for p in pages:
                if p == SCRATCH_PAGE:
                    continue
                if self._refs[p] <= 0:
                    raise PageError(f"double free of page {p}")
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    self._free.append(p)

    def fork(self, table):
        """Copy-on-write fork: a new table aliasing every page of
        `table` (refcount++ each). Writes through either table must go
        via `make_writable` first."""
        self.ref(table)
        return list(table)

    def make_writable(self, table, idx):
        """Ensure table[idx] is exclusively owned before a write.

        Returns (page, copy_from): `page` is the id now safe to write
        (table is updated in place); `copy_from` is the old page id
        when a copy-on-write allocation happened (the CALLER must copy
        the device page copy_from -> page before writing), else None.
        """
        page = table[idx]
        if page == SCRATCH_PAGE:
            raise PageError("cannot write through a scratch entry")
        with self._lock:
            if self._refs[page] <= 0:
                raise PageError(f"write through freed page {page}")
            if self._refs[page] == 1:
                return page, None
            # shared: break the alias with a private copy
            if not self._free:
                raise PagePoolExhausted(
                    "copy-on-write needs a free page; preempt or wait")
            fresh = self._free.pop()
            self._refs[fresh] = 1
            self._refs[page] -= 1
            self._allocated_total += 1
            if len(self._free) < self._low_watermark:
                self._low_watermark = len(self._free)
        table[idx] = fresh
        return fresh, page

    # ------------------------------------------------------- validation
    def check(self):
        """Raise PageError on any broken invariant (test hook)."""
        with self._lock:
            free = set(self._free)
            if len(free) != len(self._free):
                raise PageError("duplicate pages in free list")
            if SCRATCH_PAGE in free or self._refs[SCRATCH_PAGE] < 1:
                raise PageError("scratch page escaped its pin")
            for p in range(1, self.num_pages):
                if (p in free) == (self._refs[p] > 0):
                    raise PageError(
                        f"page {p}: free={p in free} "
                        f"refs={self._refs[p]}")

    def stats(self):
        with self._lock:
            free = len(self._free)
        return {
            "pages_total": self.num_pages - 1,
            "pages_free": free,
            "pages_in_use": (self.num_pages - 1) - free,
            "free_low_watermark": self._low_watermark,
            "page_size": self.page_size,
            "pages_allocated": self._allocated_total,
        }

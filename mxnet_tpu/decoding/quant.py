"""Precision-polymorphic KV page pool (ROADMAP item 2's decode half).

The paged KV cache is the decode tier's HBM budget: every sequence
costs `2 * n_layers * H * D * itemsize` bytes per token. Storing pages
as int8 with a float32 scale plane cuts that to `D + 4` bytes per
(token, head) against float32's `4 * D` — a `4D / (D + 4)` capacity
multiplier (3.2x at D=16, asymptotically 4x) that compounds with
prefix sharing and speculation because all three trade the SAME pool
bytes.

`KVPool` is a NamedTuple — jax registers those as pytrees — so a
quantized pool threads through every existing jit signature,
`donate_argnums` slot and device-copy exactly like the bare array it
replaces: the fixed-shape program grid is UNCHANGED IN COUNT and the
scale plane rides along wherever its pages go (COW copies, prefix
shares, fleet handoffs).

Quantization scheme (symmetric, zero-point-free):

  scale[l, page, slot, head] = max|K/V[l, page, slot, head, :]| / 127
  data = round(value / scale) in [-127, 127] int8

Per-(slot, head) granularity — "a per-page scale plane" in the
coarse-to-fine sense: the plane is allocated per page, with one scalar
per (slot, head) entry INSIDE the page. Anything coarser would force
re-quantizing already-written slots on every decode append (one token
lands per step), destroying the bit-identical page sharing the prefix
cache and fleet affinity routing depend on. With maxabs scaling the
round-trip error is bounded by scale/2 per element and quantizing a
value twice is idempotent — cached pages stay byte-stable.

Dequantization happens INSIDE the attention paths (the lax gather and
the pallas kernel both upcast per page as they read), so no
full-precision copy of the pool is ever materialized.

Dtype enum (MXNET_DECODE_KV_DTYPE): float32 (default), bf16 (plain
storage cast, no scale plane), int8 (scaled), fp8 — ACCEPTED by the
enum but reserved: fp8 stores need the TPU's native f8 converts to
beat int8, a silicon-backlog item; selecting it raises today so the
knob's surface is already the final one.

Hot paths: `kv_scatter` runs inside every prefill/decode/verify
program and `gather_ctx` inside every lax attention call — both are
pure jax (listed in the mxlint HOT_PATH_MANIFEST; no blocking calls).
"""
from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

from .blocks import PageError

# the knob's full surface; "fp8" is reserved (see module docstring)
KV_DTYPES = ("float32", "bf16", "int8", "fp8")

# scale floor: keeps an all-zero (or denormal) K/V row from dividing
# by zero; 1e-8/127 quantizes everything below float32 noise to 0
_SCALE_FLOOR = 1e-8


def canonical(kv_dtype):
    """Validate + normalize an MXNET_DECODE_KV_DTYPE value."""
    name = str(kv_dtype or "float32").strip().lower()
    if name in ("bfloat16",):
        name = "bf16"
    if name not in KV_DTYPES:
        raise PageError(
            f"unknown kv dtype {kv_dtype!r} "
            f"(MXNET_DECODE_KV_DTYPE choices: {KV_DTYPES})")
    if name == "fp8":
        raise PageError(
            "kv dtype 'fp8' is reserved: fp8 page stores need native "
            "f8 converts (silicon backlog); use 'int8' today")
    return name


def storage_dtype(kv_dtype):
    return {"float32": jnp.float32, "bf16": jnp.bfloat16,
            "int8": jnp.int8}[canonical(kv_dtype)]


class KVPool(NamedTuple):
    """One K (or V) page pool: `data` is (layers, pages, page_size,
    heads, head_dim) in the storage dtype; `scale` is the per-(page,
    slot, head) float32 plane for int8 pools, None otherwise.

    NamedTuple => pytree: jit, donation and device copies treat the
    pair as one value, which is what keeps the trace grid count
    identical across dtypes."""

    data: jnp.ndarray
    scale: Optional[jnp.ndarray]

    @property
    def shape(self):
        return self.data.shape

    @property
    def page_size(self):
        return self.data.shape[2]

    @property
    def kv_dtype(self):
        if self.scale is not None:
            return "int8"
        return "bf16" if self.data.dtype == jnp.bfloat16 else "float32"

    def layer(self, i):
        """The (pages, page_size, heads, head_dim) view of one layer
        — what the attention kernels consume."""
        return KVPool(self.data[i],
                      None if self.scale is None else self.scale[i])


def as_pool(x):
    """Adopt a bare (quantization-naive) pool array as a float KVPool
    so the attention kernels keep accepting raw arrays (tests and the
    parity harness build those directly)."""
    return x if isinstance(x, KVPool) else KVPool(x, None)


def make_pool(shape, kv_dtype):
    """A zeroed pool of `shape` (layers, pages, page_size, heads,
    head_dim) at `kv_dtype`; int8 pools get their scale plane."""
    name = canonical(kv_dtype)
    data = jnp.zeros(shape, storage_dtype(name))
    if name != "int8":
        return KVPool(data, None)
    return KVPool(data, jnp.zeros(shape[:-1], jnp.float32))


def quantize_values(values):
    """Symmetric int8 quantization of K/V rows: values (..., H, D)
    float -> (q int8 (..., H, D), scale f32 (..., H), clips () i32).

    `clips` counts elements that could NOT be represented even after
    scaling — nonfinite inputs, or magnitudes beyond scale*127 when
    the scale saturated. With healthy numerics it is exactly 0 (the
    scale is derived from the row's own maxabs), so a nonzero value is
    a numerics event: MXNET_NUMERICS_DECODE_GUARD surfaces it as the
    dequant-overflow clip counter."""
    v = values.astype(jnp.float32)
    amax = jnp.max(jnp.abs(v), axis=-1)
    amax = jnp.where(jnp.isfinite(amax), amax, 0.0)
    scale = jnp.maximum(amax, _SCALE_FLOOR) / 127.0
    q = v / scale[..., None]
    overflow = ~jnp.isfinite(v) | (jnp.abs(q) > 127.5)
    clips = jnp.sum(overflow.astype(jnp.int32))
    q = jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8)
    return q, scale, clips


def dequantize_values(q, scale):
    """Inverse of `quantize_values` (exact float arithmetic: int8 *
    f32 is lossless)."""
    return q.astype(jnp.float32) * scale[..., None]


def kv_scatter(pool, layer, pages, slots, values):
    """Quantize-at-scatter: write `values` (..., H, D) float at
    [layer, pages, slots] (index arrays shaped like values minus the
    trailing (H, D)), quantizing INTO the pool's storage dtype so a
    full-precision K/V tensor never exists outside the current
    activations. Returns (pool', clips () i32); clips is 0 for
    non-int8 pools."""
    if pool.scale is None:
        data = pool.data.at[layer, pages, slots].set(
            values.astype(pool.data.dtype))
        return KVPool(data, None), jnp.int32(0)
    q, scale, clips = quantize_values(values)
    data = pool.data.at[layer, pages, slots].set(q)
    sc = pool.scale.at[layer, pages, slots].set(scale)
    return KVPool(data, sc), clips


def gather_ctx(layer_pool, page_table):
    """The lax attention paths' read: gather page_table's pages from
    one layer's pool and dequantize them in-flight — (B, Bp) int32 ->
    (B, Bp, P, H, D) float32. Only the gathered pages are ever
    upcast, never the pool."""
    pool = as_pool(layer_pool)
    d = pool.data[page_table]
    if pool.scale is None:
        return d.astype(jnp.float32)
    return d.astype(jnp.float32) * pool.scale[page_table][..., None]


def dequant_page(pool, layer, page):
    """One page, dequantized to float32 (test/debug reads)."""
    d = pool.data[layer, page]
    if pool.scale is None:
        return d.astype(jnp.float32)
    return dequantize_values(d, pool.scale[layer, page])


def pool_nbytes(pool):
    """Device bytes one pool owns (data + scale plane)."""
    n = int(pool.data.size) * pool.data.dtype.itemsize
    if pool.scale is not None:
        n += int(pool.scale.size) * pool.scale.dtype.itemsize
    return n


def kv_bytes_per_token(pool):
    """Measured K-or-V bytes per pooled token position (pool bytes /
    (pages * page_size)); double it for K+V. The float32-vs-int8
    ratio of this number IS the capacity multiplier the bench and CI
    gate report."""
    _, pages, page_size = pool.data.shape[:3]
    return pool_nbytes(pool) / float(pages * page_size)


def capacity_ratio(head_dim):
    """Analytic sequences-per-pool multiplier of int8 over float32:
    4D / (D + 4) for head_dim D (data shrinks 4x, the scale plane
    adds 4 bytes per (slot, head)). >= 1.9 for every D >= 4."""
    return 4.0 * head_dim / (head_dim + 4.0)


def check_capacity(head_dim, floor=1.9):
    if capacity_ratio(head_dim) < floor:
        raise PageError(
            f"int8 pages at head_dim {head_dim} only buy "
            f"{capacity_ratio(head_dim):.2f}x capacity (< {floor}); "
            "quantization is not worth the drift here")
    return True

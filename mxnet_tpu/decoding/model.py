"""A minimal autoregressive decoder with paged-KV decode semantics.

The decode tier needs a model contract, not a model zoo: something
with an embedding, a stack of attention+MLP blocks, and tied-logits
output, expressed as THREE pure functions over one params dict —

  reference_logits   dense causal forward over a whole (1, T) buffer
                     (the unbatched reference arm of the parity gate)
  prefill_forward    dense causal forward over a padded prompt that
                     also SCATTERS per-layer K/V into the paged pool
                     and returns the first generated token
  decode_forward     one fixed-shape decode step: embed the last
                     token of every row, append its K/V to the pool
                     through the page table, attend over the pages,
                     return each row's next greedy token

All three share the same per-row arithmetic (row-invariant matmuls,
length-masked softmax over seq-ordered pages), so a token decoded in
a continuous batch is bit-identical to the same token decoded alone —
the property ci/check_decode.py gates on.

Weights live in a flat {name: array} dict (mx checkpoint idiom);
`init_decoder_params` builds a seeded random one for tests/benches.
Real checkpoints with matching names serve unchanged.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from . import quant as _quant
from . import sampling as _sampling
from .blocks import SCRATCH_PAGE

NEG_INF = -1e30


@dataclass(frozen=True)
class DecoderConfig:
    """Architecture hyperparameters (static under jit)."""

    vocab: int = 64
    d_model: int = 32
    n_layers: int = 2
    n_heads: int = 2
    d_ff: int = 64
    max_len: int = 256
    eos_id: int = 1

    @property
    def head_dim(self):
        if self.d_model % self.n_heads:
            raise ValueError("d_model must divide into n_heads")
        return self.d_model // self.n_heads


def init_decoder_params(cfg, seed=0):
    """Seeded random weights (explicit generator: MX005-clean)."""
    rs = np.random.RandomState(seed)

    def w(*shape):
        scale = 1.0 / math.sqrt(shape[0])
        return (rs.uniform(-scale, scale, shape)).astype(np.float32)

    params = {
        "embed": w(cfg.vocab, cfg.d_model),
        "pos": w(cfg.max_len, cfg.d_model) * 0.1,
        "ln_f": np.ones((cfg.d_model,), np.float32),
    }
    for i in range(cfg.n_layers):
        params[f"l{i}.ln1"] = np.ones((cfg.d_model,), np.float32)
        params[f"l{i}.ln2"] = np.ones((cfg.d_model,), np.float32)
        for nm in ("wq", "wk", "wv", "wo"):
            params[f"l{i}.{nm}"] = w(cfg.d_model, cfg.d_model)
        params[f"l{i}.w1"] = w(cfg.d_model, cfg.d_ff)
        params[f"l{i}.w2"] = w(cfg.d_ff, cfg.d_model)
    return params


def _rms(x, g):
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return x * jax.lax.rsqrt(var + 1e-6) * g


def _qkv(params, i, x, cfg):
    """(..., D) -> q/k/v each (..., H, Dh)."""
    h, dh = cfg.n_heads, cfg.head_dim
    shape = x.shape[:-1] + (h, dh)
    q = (x @ params[f"l{i}.wq"]).reshape(shape)
    k = (x @ params[f"l{i}.wk"]).reshape(shape)
    v = (x @ params[f"l{i}.wv"]).reshape(shape)
    return q, k, v


def _mlp(params, i, x):
    return jax.nn.relu(x @ params[f"l{i}.w1"]) @ params[f"l{i}.w2"]


def _dense_causal_attention(q, k, v, scale):
    """(B, T, H, Dh) causal attention, fp32 softmax."""
    t = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    causal = jnp.arange(t)[:, None] >= jnp.arange(t)[None, :]
    s = jnp.where(causal[None, None], s, NEG_INF)
    m = s.max(axis=-1, keepdims=True)
    e = jnp.exp(s - m)
    w = e / e.sum(axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v,
                      preferred_element_type=jnp.float32)


# ------------------------------------------------------------- reference
def reference_logits(params, tokens, cfg, attn_fn=None):
    """Dense causal forward: tokens (B, T) int32 -> logits (B, T, V).

    `attn_fn(q, k, v)` overrides the attention (the ring-attention
    prefill path routes through here with a sharded implementation);
    default is the in-process dense kernel.
    """
    scale = 1.0 / math.sqrt(cfg.head_dim)
    b, t = tokens.shape
    x = params["embed"][tokens] + params["pos"][:t][None]
    for i in range(cfg.n_layers):
        h1 = _rms(x, params[f"l{i}.ln1"])
        q, k, v = _qkv(params, i, h1, cfg)
        if attn_fn is None:
            o = _dense_causal_attention(q, k, v, scale)
        else:
            o = attn_fn(q, k, v)
        x = x + o.reshape(b, t, cfg.d_model) @ params[f"l{i}.wo"]
        x = x + _mlp(params, i, _rms(x, params[f"l{i}.ln2"]))
    x = _rms(x, params["ln_f"])
    return x @ params["embed"].T


# -------------------------------------------------------------- sampling
def _pick_token(logits, seed, position, temperature, top_k, top_p):
    """Single-position token choice: greedy argmax when no sampling
    params were threaded through (seed None — the PR 8 call shape),
    else the counter-keyed sampler (sampling.sample_token)."""
    if seed is None:
        return jnp.argmax(logits).astype(jnp.int32)
    return _sampling.sample_token(logits, seed, position, temperature,
                                  top_k, top_p)


# --------------------------------------------------------------- prefill
def prefill_forward(params, tokens, length, k_pages, v_pages,
                    page_ids, seed=None, temperature=None, top_k=None,
                    top_p=None, *, cfg, attn_fn=None):
    """Prompt pass: tokens (1, Tb) padded to a length bucket, length
    () int32 the true prompt length, page_ids (ceil(Tb/P),) int32 the
    sequence's allocated pages (padded with scratch 0).

    Scatters every layer's K/V for positions < length into the pool
    (positions >= length land in the scratch page) and returns
    (first_token (), k_pages, v_pages). The first token is sampled on
    the (seed, position=length) stream when sampling params are
    given, greedy argmax otherwise.
    """
    scale = 1.0 / math.sqrt(cfg.head_dim)
    k_pages = _quant.as_pool(k_pages)
    v_pages = _quant.as_pool(v_pages)
    page_size = k_pages.page_size
    _, t = tokens.shape
    pos = jnp.arange(t)
    # per-position scatter targets: (page, slot) through the table,
    # scratch for the padded tail
    tgt_pages = jnp.where(length > pos, page_ids[pos // page_size],
                          SCRATCH_PAGE)
    slots = pos % page_size

    x = params["embed"][tokens] + params["pos"][:t][None]
    for i in range(cfg.n_layers):
        h1 = _rms(x, params[f"l{i}.ln1"])
        q, k, v = _qkv(params, i, h1, cfg)
        k_pages, _ = _quant.kv_scatter(k_pages, i, tgt_pages, slots,
                                       k[0])
        v_pages, _ = _quant.kv_scatter(v_pages, i, tgt_pages, slots,
                                       v[0])
        if attn_fn is None:
            o = _dense_causal_attention(q, k, v, scale)
        else:
            o = attn_fn(q, k, v)
        x = x + o.reshape(1, t, cfg.d_model) @ params[f"l{i}.wo"]
        x = x + _mlp(params, i, _rms(x, params[f"l{i}.ln2"]))
    x = _rms(x, params["ln_f"])
    last = x[0, length - 1]
    logits = last @ params["embed"].T
    tok = _pick_token(logits, seed, length, temperature, top_k, top_p)
    return tok, k_pages, v_pages


# ---------------------------------------------------- prefix-cache tail
def tail_prefill_forward(params, tokens, start, length, k_pages,
                         v_pages, page_ids, seed=None, temperature=None,
                         top_k=None, top_p=None, *, cfg, attn_multi):
    """Tail-only prompt pass for a prefix-cache hit: positions
    [0, start) already live in shared pages (K/V is a pure function of
    the token prefix from position 0, so pages cached for one sequence
    are exact for any sequence with the same prefix); only the tail
    [start, length) is computed here.

    tokens (1, Tb) holds the TAIL tokens padded to a length bucket;
    start/length are () int32 (absolute); page_ids covers the FULL
    table padded to the engine's largest bucket (one static shape per
    tail bucket). Each layer scatters the tail K/V into its pages and
    attends the tail queries over the context gathered from pages
    (shared prefix + just-written tail) with per-query causal masks —
    FLOPs scale with tail x context instead of prompt^2.
    """
    k_pages = _quant.as_pool(k_pages)
    v_pages = _quant.as_pool(v_pages)
    page_size = k_pages.page_size
    _, t = tokens.shape
    cap = page_ids.shape[0] * page_size
    pos = start + jnp.arange(t)                      # absolute
    valid = (pos < length) & (pos < cap)
    tgt_pages = jnp.where(
        valid, page_ids[jnp.clip(pos // page_size, 0,
                                 page_ids.shape[0] - 1)], SCRATCH_PAGE)
    slots = pos % page_size
    pos_safe = jnp.clip(pos, 0, cfg.max_len - 1)

    x = params["embed"][tokens] + params["pos"][pos_safe][None]
    for i in range(cfg.n_layers):
        h1 = _rms(x, params[f"l{i}.ln1"])
        q, k, v = _qkv(params, i, h1, cfg)
        k_pages, _ = _quant.kv_scatter(k_pages, i, tgt_pages, slots,
                                       k[0])
        v_pages, _ = _quant.kv_scatter(v_pages, i, tgt_pages, slots,
                                       v[0])
        o = attn_multi(q, k_pages.layer(i), v_pages.layer(i),
                       page_ids[None], pos_safe[None])
        x = x + o.reshape(1, t, cfg.d_model) @ params[f"l{i}.wo"]
        x = x + _mlp(params, i, _rms(x, params[f"l{i}.ln2"]))
    x = _rms(x, params["ln_f"])
    last = x[0, length - 1 - start]
    logits = last @ params["embed"].T
    tok = _pick_token(logits, seed, length, temperature, top_k, top_p)
    return tok, k_pages, v_pages


# ---------------------------------------------------------------- decode
def decode_logits(params, tokens, k_pages, v_pages, page_table,
                  lengths, active, *, cfg, attn):
    """The shared decode-step body: embed each row's last token, append
    its K/V at index `lengths` through the page table, attend over the
    pages, return (logits (B, V), k_pages, v_pages, clips). `clips` is
    the summed dequant-overflow clip count of this step's quantized
    K/V writes (always 0 for float pools — and for healthy int8 ones;
    see quant.quantize_values). decode_forward and the speculative
    draft proposer both build on this."""
    k_pages = _quant.as_pool(k_pages)
    v_pages = _quant.as_pool(v_pages)
    page_size = k_pages.page_size
    b = tokens.shape[0]
    bp = page_table.shape[1]
    rows = jnp.arange(b)
    in_cap = lengths < bp * page_size
    w_pages = jnp.where(
        active & in_cap,
        page_table[rows, jnp.clip(lengths // page_size, 0, bp - 1)],
        SCRATCH_PAGE)
    slots = lengths % page_size
    ctx_len = jnp.where(active, lengths + 1, 1)

    clips = jnp.int32(0)
    x = params["embed"][tokens] + params["pos"][
        jnp.clip(lengths, 0, cfg.max_len - 1)]
    for i in range(cfg.n_layers):
        h1 = _rms(x, params[f"l{i}.ln1"])
        q, k, v = _qkv(params, i, h1, cfg)
        k_pages, ck = _quant.kv_scatter(k_pages, i, w_pages, slots, k)
        v_pages, cv = _quant.kv_scatter(v_pages, i, w_pages, slots, v)
        clips = clips + ck + cv
        o = attn(q, k_pages.layer(i), v_pages.layer(i), page_table,
                 ctx_len)
        x = x + o.reshape(b, cfg.d_model) @ params[f"l{i}.wo"]
        x = x + _mlp(params, i, _rms(x, params[f"l{i}.ln2"]))
    x = _rms(x, params["ln_f"])
    return x @ params["embed"].T, k_pages, v_pages, clips


def decode_forward(params, tokens, k_pages, v_pages, page_table,
                   lengths, active, seeds=None, temps=None,
                   top_ks=None, top_ps=None, *, cfg, attn,
                   with_stats=False):
    """One decode step over the full fixed-shape batch.

    tokens (B,) int32 last emitted token per row; lengths (B,) tokens
    already in cache; active (B,) bool. Inactive rows write to / read
    from the scratch page and their outputs are ignored by the host.
    With seeds/temps/top_ks/top_ps (B,) arrays the next token is drawn
    per row on its (seed, position=lengths+1) stream (temperature 0 =
    exact greedy); without them it is the argmax (PR 8 behavior).
    Returns (next_tokens (B,), k_pages, v_pages); with_stats=True
    (the MXNET_NUMERICS_DECODE_GUARD path) appends a (2,) int32
    vector [nonfinite_rows, quant_clips]: ACTIVE rows whose logits
    hold any NaN/Inf, and K/V values this step's quantized writes had
    to clip (dequant-overflow events — 0 on float pools). Both are
    computed inside the jit, so the guard adds zero host syncs.
    """
    logits, k_pages, v_pages, clips = decode_logits(
        params, tokens, k_pages, v_pages, page_table, lengths, active,
        cfg=cfg, attn=attn)
    if seeds is None:
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        next_tokens = jax.vmap(
            lambda lg, sd, p, tm, tk, tp: _sampling.sample_token(
                lg, sd, p, tm, tk, tp))(
            logits, seeds, lengths + 1, temps, top_ks, top_ps)
    if with_stats:
        bad_rows = jnp.any(~jnp.isfinite(logits), axis=-1)
        nonfinite = jnp.sum(
            jnp.where(active, bad_rows, False).astype(jnp.int32))
        guard = jnp.stack([nonfinite, clips])
        return next_tokens, k_pages, v_pages, guard
    return next_tokens, k_pages, v_pages

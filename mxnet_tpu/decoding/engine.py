"""DecodeEngine: the device half of continuous batching.

Owns the paged KV pool (one pre-allocated (layers, pages, page_size,
heads, head_dim) buffer per K and V), the block allocator over it, and
a FIXED grid of jitted programs:

  prefill  one program per prompt length bucket (batch 1, dense causal
           attention — optionally ring attention for long buckets —
           that scatters K/V into the sequence's pages)
  tail     one tail-prefill program per length bucket (prefix-cache
           hits: compute only the uncached prompt tail, attending over
           the shared pages — page table padded to the largest bucket
           for one static shape per tail bucket). NOT BUILT in
           merged-step mode (MXNET_DECODE_MERGED_STEP, the default
           with the prefix cache on): tail tokens ride the decode
           step as extra ragged rows instead, one program family
           fewer in the warmup grid
  decode   one program per pages-per-sequence bucket; the step shape
           is a function ONLY of (step_rows, bucket) — step_rows =
           max_batch plus, in merged mode, page_size tail rows —
           never of real lengths or batch composition — so `warmup()`
           pre-traces the full grid and steady-state decode adds zero
           traces. In merged mode the rows mix decode queries and
           tail-prefill prompt tokens through the ragged paged
           attention kernel (decoding/attention.py)
  draft/   with a draft model configured, one K-token draft proposer
  verify   and one K+1-position target verifier per pages bucket —
           the speculative pair joins the same pinned trace grid, and
           the draft keeps parallel K/V pools indexed by the SAME
           page ids (see speculative.py)
  copy     one page-copy program (copy-on-write fork support; traced
           once more for the draft pool shape when it differs)

Trace accounting: every impl body bumps a python-side counter as its
first statement. Python runs at TRACE time only, so the counter counts
traces, not calls — `traces()` after `warmup()` is the decode tier's
`traces_since_warmup` evidence (the PR 2 discipline, extended to a
workload exec_cache never sees because decode jits are raw jax.jit).

The engine is NOT thread-safe: exactly one scheduler thread drives it
(the serving-lane convention — an Executor is single-threaded too).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import profiler as _profiler
from .. import utils as _utils
from ..serving.batcher import pick_bucket
from . import config as _cfg
from . import attention as _attn
from . import model as _model
from . import quant as _quant
from . import speculative as _spec

# warn-once latch for calibration-harvest failures (the serving
# registry's convention: one WARN per process, not one per bucket)
_calibration_warned = False
from .blocks import SCRATCH_PAGE, BlockAllocator, PageError, \
    pages_needed


class DecodeEngine:
    def __init__(self, params, cfg, *, max_batch=None, page_size=None,
                 num_pages=None, page_buckets=None, kernel=None,
                 ring_prefill=None, draft_params=None, draft_cfg=None,
                 spec_k=None, prefix_cache=None, merged_step=None,
                 kv_dtype=None):
        self.cfg = cfg
        # KV storage precision (MXNET_DECODE_KV_DTYPE): the page pools
        # — target AND draft — store at this dtype; int8 pools carry
        # per-(slot, head) scale planes through every pytree hop
        self.kv_dtype = _quant.canonical(
            kv_dtype if kv_dtype is not None else _cfg.kv_dtype())
        self.max_batch = max_batch if max_batch is not None \
            else _cfg.max_batch()
        self.page_size = page_size if page_size is not None \
            else _cfg.page_size()
        self.num_pages = num_pages if num_pages is not None \
            else _cfg.num_pages()
        if page_buckets is None:
            page_buckets = _cfg.page_buckets()
        if page_buckets is None:
            # a sequence can never own more pages than the pool (or
            # than max_len covers) — cap the default grid there
            cap = min(self.num_pages - 1,
                      cfg.max_len // self.page_size)
            page_buckets = _cfg.default_page_buckets(max(1, cap))
        self.page_buckets = tuple(sorted(set(int(b)
                                             for b in page_buckets)))
        self.kernel_name = kernel if kernel is not None \
            else _cfg.kernel()
        self.ring_prefill = ring_prefill if ring_prefill is not None \
            else _cfg.ring_prefill()
        if self.page_buckets[-1] * self.page_size > cfg.max_len:
            raise PageError(
                f"largest page bucket {self.page_buckets[-1]} x "
                f"page_size {self.page_size} exceeds the model's "
                f"max_len {cfg.max_len}")
        if self.page_buckets[-1] > self.num_pages - 1:
            raise PageError(
                f"page bucket {self.page_buckets[-1]} exceeds pool "
                f"capacity {self.num_pages - 1}")

        self.allocator = BlockAllocator(self.num_pages, self.page_size)
        self._attn = _attn.get_kernel(self.kernel_name)
        self._attn_multi = _attn.get_multi_kernel(self.kernel_name)
        self._params = jax.tree_util.tree_map(jnp.asarray, dict(params))
        shape = (cfg.n_layers, self.num_pages, self.page_size,
                 cfg.n_heads, cfg.head_dim)
        self._k = _quant.make_pool(shape, self.kv_dtype)
        self._v = _quant.make_pool(shape, self.kv_dtype)
        self.prefix_cache_enabled = prefix_cache if prefix_cache \
            is not None else _cfg.prefix_cache()
        self.spec_k = int(spec_k) if spec_k is not None \
            else _cfg.spec_k()
        self.draft_cfg = None
        self._draft_params = None
        if draft_params is not None and self.spec_k > 0:
            dcfg = draft_cfg if draft_cfg is not None else cfg
            if dcfg.vocab != cfg.vocab:
                raise PageError(
                    f"draft vocab {dcfg.vocab} != target {cfg.vocab}: "
                    "speculative decoding needs one token space")
            if dcfg.max_len < cfg.max_len:
                raise PageError(
                    f"draft max_len {dcfg.max_len} < target "
                    f"{cfg.max_len}: the draft must cover every "
                    "position the target can reach")
            self.draft_cfg = dcfg
            self._draft_params = jax.tree_util.tree_map(
                jnp.asarray, dict(draft_params))
            dshape = (dcfg.n_layers, self.num_pages, self.page_size,
                      dcfg.n_heads, dcfg.head_dim)
            self._dk = _quant.make_pool(dshape, self.kv_dtype)
            self._dv = _quant.make_pool(dshape, self.kv_dtype)
        # merged ragged step (MXNET_DECODE_MERGED_STEP): prefix-cache
        # tail-prefill tokens ride the decode step as extra rows
        # through the ragged paged kernel — the per-length-bucket tail
        # programs are never built and the warmup grid shrinks by one
        # program per prefill bucket. Requires the prefix cache (the
        # only producer of tails) and no speculation (the verify pair
        # owns its own multi-query shape).
        want_merged = merged_step if merged_step is not None \
            else _cfg.merged_step()
        self.merged_step_enabled = bool(
            want_merged and self.prefix_cache_enabled
            and not self.spec_enabled)
        # extra step rows available for tail tokens each merged step;
        # one page's worth keeps the row overhead bounded while a tail
        # still advances a full page per step
        self.tail_budget = self.page_size if self.merged_step_enabled \
            else 0
        self.step_rows = self.max_batch + self.tail_budget
        # donation lets XLA update the pool in place; CPU falls back
        # with a warning, so only donate where it pays
        self._donate = jax.default_backend() != "cpu"
        self._decode_fns = {}
        self._prefill_fns = {}
        self._tail_fns = {}
        self._draft_prefill_fns = {}
        self._draft_tail_fns = {}
        self._propose_fns = {}
        self._verify_fns = {}
        self._copy_fn = None
        self._trace_counts = {}
        self._warm = False
        # MXNET_NUMERICS_DECODE_GUARD: each decode step also returns a
        # device scalar counting active rows with NaN/Inf logits;
        # scalars accumulate here and drain in one fetch (drain_guard)
        self._guard = bool(_utils.getenv("MXNET_NUMERICS_DECODE_GUARD"))
        self._guard_pending = []
        # executable-accounting key: the decode grid is a function of
        # (model config, batch, paging layout, kernel) — deterministic
        # within a process, which is all deviceStats needs
        import hashlib as _hashlib

        self._digest = _hashlib.sha1(repr(
            (cfg, self.max_batch, self.page_size, self.num_pages,
             self.kernel_name, self.draft_cfg,
             self.spec_k if self.spec_enabled else 0,
             self.step_rows if self.merged_step_enabled else 0,
             self.kv_dtype)
        ).encode()).hexdigest()[:12]

    def _instrument(self, fn, kind):
        """Route one grid program through profiling's executable
        accounting (deviceStats). Transparent: the wrapper dispatches
        through the SAME compiled executable a raw jit would build, so
        trace counts (`_note_trace`) are unchanged."""
        try:
            from .. import profiling as _profiling

            return _profiling.instrument(fn, digest=self._digest,
                                         kind=kind)
        except Exception:
            return fn

    # ------------------------------------------------------ properties
    @property
    def spec_enabled(self):
        """True when a draft model is loaded and K > 0: the scheduler
        routes steps through spec_step instead of step."""
        return self._draft_params is not None and self.spec_k > 0

    @property
    def max_context(self):
        """Tokens the largest bucket covers — the hard length cap."""
        return self.page_buckets[-1] * self.page_size

    @property
    def prefill_buckets(self):
        """Prompt length buckets: one per page bucket (the decode
        extension of the serving tier's MXNET_SERVING_LENGTH_BUCKETS
        grid, derived instead of hand-configured)."""
        return tuple(b * self.page_size for b in self.page_buckets)

    def traces(self):
        """Total prefill/decode/copy traces so far (see docstring)."""
        return sum(self._trace_counts.values())

    def trace_counts(self):
        return dict(self._trace_counts)

    def pool_stats(self):
        st = self.allocator.stats()
        # measured K+V bytes per pooled token position (scale planes
        # included): the float32/int8 ratio of this number is the
        # capacity multiplier BENCH_MODE=decode and quant-check report
        per_tok = (_quant.kv_bytes_per_token(self._k)
                   + _quant.kv_bytes_per_token(self._v))
        return {
            "pages_total": st["pages_total"],
            "pages_free": st["pages_free"],
            "kv_occupancy": round(
                st["pages_in_use"] / max(1, st["pages_total"]), 4),
            "free_low_watermark": st["free_low_watermark"],
            "pages_allocated": st["pages_allocated"],
            "kv_dtype": self.kv_dtype,
            "kv_bytes_per_token": round(per_tok, 2),
            "pool_capacity_tokens": (self.num_pages - 1)
            * self.page_size,
        }

    def _note_trace(self, name):
        # first statement of every impl body: executes under tracing
        # only, so this COUNTS TRACES (see module docstring)
        self._trace_counts[name] = self._trace_counts.get(name, 0) + 1

    # -------------------------------------------------- numerics guard
    _GUARD_CAP = 1024  # device scalars between drains

    def _run_decode(self, fn, *args):
        """Dispatch one decode program; absorb the guard vector (still
        on device — zero sync) when the guard is enabled."""
        res = fn(*args)
        if not self._guard:
            out, self._k, self._v = res
            return out
        out, self._k, self._v, bad = res
        self._guard_pending.append(bad)
        if len(self._guard_pending) > self._GUARD_CAP:
            del self._guard_pending[:-self._GUARD_CAP]
        return out

    def drain_guard(self):
        """Pending guard vectors -> host in ONE blocking fetch
        (counted in hostSyncStats); [] (no fetch) when empty or the
        guard is off. Each entry is an (nonfinite_rows, quant_clips)
        pair per drained step — NaN/Inf logit rows and dequant-
        overflow clip events of the step's quantized K/V writes. The
        scheduler drains on an interval and feeds nonzero counts into
        DecodeStats (`decodingStats` view)."""
        if not self._guard_pending:
            return []
        pending, self._guard_pending = self._guard_pending, []
        host = jax.device_get(pending)
        _profiler.count_host_sync("blocking_fetches")
        _profiler.count_host_sync("metric_fetches")
        return [(int(v[0]), int(v[1])) for v in host]

    # -------------------------------------------------------- builders
    def _build_decode_fn(self, bucket):
        # merged mode routes through the ragged entry: same per-row
        # contract, named for what the mixed batch actually is
        cfg = self.cfg
        attn = (_attn.get_ragged_kernel(self.kernel_name)
                if self.merged_step_enabled else self._attn)
        guard = self._guard

        def impl(params, tokens, k_pages, v_pages, page_table,
                 lengths, active, seeds, temps, top_ks, top_ps):
            self._note_trace(f"decode@{bucket}")
            return _model.decode_forward(
                params, tokens, k_pages, v_pages, page_table,
                lengths, active, seeds, temps, top_ks, top_ps,
                cfg=cfg, attn=attn, with_stats=guard)

        donate = (2, 3) if self._donate else ()
        return self._instrument(jax.jit(impl, donate_argnums=donate),
                                f"decode@{bucket}")

    def _build_prefill_fn(self, length_bucket, name="prefill",
                          cfg=None):
        cfg = cfg if cfg is not None else self.cfg
        attn_fn = None
        if self.ring_prefill and length_bucket >= self.ring_prefill:
            # NOTE: mxnet_tpu.parallel re-exports the ring_attention
            # FUNCTION under the module's name; import the module by
            # its full path
            from ..parallel.ring_attention import (ring_attention,
                                                   seq_mesh_for)

            mesh = seq_mesh_for(length_bucket)

            def attn_fn(q, k, v):
                return ring_attention(q, k, v, mesh=mesh, causal=True)

        def impl(params, tokens, length, k_pages, v_pages, page_ids,
                 seed, temp, top_k, top_p):
            self._note_trace(f"{name}@{length_bucket}")
            return _model.prefill_forward(
                params, tokens, length, k_pages, v_pages, page_ids,
                seed, temp, top_k, top_p, cfg=cfg, attn_fn=attn_fn)

        donate = (3, 4) if self._donate else ()
        return self._instrument(jax.jit(impl, donate_argnums=donate),
                                f"{name}@{length_bucket}")

    def _build_tail_fn(self, length_bucket, name="prefill_tail",
                       cfg=None):
        cfg = cfg if cfg is not None else self.cfg
        attn_multi = self._attn_multi

        def impl(params, tokens, start, length, k_pages, v_pages,
                 page_ids, seed, temp, top_k, top_p):
            self._note_trace(f"{name}@{length_bucket}")
            return _model.tail_prefill_forward(
                params, tokens, start, length, k_pages, v_pages,
                page_ids, seed, temp, top_k, top_p, cfg=cfg,
                attn_multi=attn_multi)

        donate = (4, 5) if self._donate else ()
        return self._instrument(jax.jit(impl, donate_argnums=donate),
                                f"{name}@{length_bucket}")

    def _build_propose_fn(self, bucket):
        cfg, attn, k = self.draft_cfg, self._attn, self.spec_k

        def impl(params, tokens, k_pages, v_pages, page_table,
                 lengths, active, seeds, temps, top_ks, top_ps):
            self._note_trace(f"draft@{bucket}")
            return _spec.draft_propose_forward(
                params, tokens, k_pages, v_pages, page_table, lengths,
                active, seeds, temps, top_ks, top_ps, cfg=cfg,
                attn=attn, k=k)

        donate = (2, 3) if self._donate else ()
        return self._instrument(jax.jit(impl, donate_argnums=donate),
                                f"draft@{bucket}")

    def _build_verify_fn(self, bucket):
        cfg, attn_multi, k = self.cfg, self._attn_multi, self.spec_k

        def impl(params, tokens, drafts, q_dists, k_pages, v_pages,
                 page_table, lengths, active, use_draft, seeds, temps,
                 top_ks, top_ps):
            self._note_trace(f"verify@{bucket}")
            return _spec.verify_forward(
                params, tokens, drafts, q_dists, k_pages, v_pages,
                page_table, lengths, active, use_draft, seeds, temps,
                top_ks, top_ps, cfg=cfg, attn_multi=attn_multi, k=k)

        donate = (4, 5) if self._donate else ()
        return self._instrument(jax.jit(impl, donate_argnums=donate),
                                f"verify@{bucket}")

    def _build_copy_fn(self):
        # the pool argument is a quant.KVPool pytree: ONE traced
        # program moves data AND scale plane together, so COW copies
        # can never split a page from its scales. K and V share the
        # pytree structure — still a single trace, like the bare-array
        # version this replaces.
        def impl(pool, src, dst):
            self._note_trace("copy_page")
            data = pool.data.at[:, dst].set(pool.data[:, src])
            if pool.scale is None:
                return _quant.KVPool(data, None)
            scale = pool.scale.at[:, dst].set(pool.scale[:, src])
            return _quant.KVPool(data, scale)

        donate = (0,) if self._donate else ()
        return self._instrument(jax.jit(impl, donate_argnums=donate),
                                "copy_page")

    # --------------------------------------------- fixed-dtype packing
    @staticmethod
    def _samp_scalars(seed=0, temperature=0.0, top_k=0, top_p=1.0):
        """Sampling params as fixed-dtype scalars: one traced
        signature regardless of host value types."""
        return (np.uint32(int(seed) & 0xFFFFFFFF),
                np.float32(temperature), np.int32(top_k),
                np.float32(top_p))

    def _samp_arrays(self, seeds, temps, top_ks, top_ps, rows=None):
        """(rows,) sampling arrays, defaulting to greedy, fixed
        dtypes, padded up to `rows` (default step_rows — the merged
        step's width; the speculative pair passes max_batch)."""
        b = rows if rows is not None else self.step_rows

        def _fill(arr, dtype, fill):
            if arr is None:
                return np.full((b,), fill, dtype)
            arr = np.asarray(arr, dtype)
            if len(arr) < b:
                arr = np.concatenate(
                    [arr, np.full((b - len(arr),), fill, dtype)])
            return arr

        return (_fill(seeds, np.uint32, 0),
                _fill(temps, np.float32, 0.0),
                _fill(top_ks, np.int32, 0),
                _fill(top_ps, np.float32, 1.0))

    # ---------------------------------------------------------- warmup
    def warmup(self):
        """Pre-trace the full program grid: every prefill length
        bucket (full + tail when the prefix cache is on, for the
        draft too when speculation is on), every decode pages bucket
        (plus the draft/verify pair), and the page copy. All writes of
        the dry runs land in the scratch page (lengths 0, tables
        all-scratch), so the pool state is untouched except for
        scratch garbage — which is never read unmasked. Idempotent."""
        if self._warm:
            return self
        self._copy_fn = self._build_copy_fn()
        self.copy_page(SCRATCH_PAGE, SCRATCH_PAGE)
        sargs = self._samp_scalars()
        max_pages = pages_needed(self.max_context, self.page_size)
        for lb in self.prefill_buckets:
            tokens = np.zeros((1, lb), np.int32)
            page_ids = np.zeros((pages_needed(lb, self.page_size),),
                                np.int32)
            full_ids = np.zeros((max_pages,), np.int32)
            self._prefill_fns[lb] = self._build_prefill_fn(lb)
            tok, self._k, self._v = self._prefill_fns[lb](
                self._params, tokens, jnp.int32(0), self._k, self._v,
                page_ids, *sargs)
            tok.block_until_ready()
            if self.prefix_cache_enabled \
                    and not self.merged_step_enabled:
                # merged mode NEVER builds the per-length tail
                # programs: tail tokens ride the decode step below —
                # this is the warmup-grid shrink the merged step buys
                self._tail_fns[lb] = self._build_tail_fn(lb)
                tok, self._k, self._v = self._tail_fns[lb](
                    self._params, tokens, jnp.int32(0), jnp.int32(0),
                    self._k, self._v, full_ids, *sargs)
                tok.block_until_ready()
            if self.spec_enabled:
                self._draft_prefill_fns[lb] = self._build_prefill_fn(
                    lb, name="draft_prefill", cfg=self.draft_cfg)
                tok, self._dk, self._dv = self._draft_prefill_fns[lb](
                    self._draft_params, tokens, jnp.int32(0),
                    self._dk, self._dv, page_ids, *sargs)
                tok.block_until_ready()
                if self.prefix_cache_enabled:
                    self._draft_tail_fns[lb] = self._build_tail_fn(
                        lb, name="draft_tail", cfg=self.draft_cfg)
                    tok, self._dk, self._dv = self._draft_tail_fns[lb](
                        self._draft_params, tokens, jnp.int32(0),
                        jnp.int32(0), self._dk, self._dv, full_ids,
                        *sargs)
                    tok.block_until_ready()
        r = self.step_rows
        b = self.max_batch
        dry = (np.zeros((r,), np.int32), np.zeros((r,), np.int32),
               np.zeros((r,), bool))
        sarr = self._samp_arrays(None, None, None, None)
        for bucket in self.page_buckets:
            table = np.zeros((r, bucket), np.int32)
            self._decode_fns[bucket] = self._build_decode_fn(bucket)
            out = self._run_decode(
                self._decode_fns[bucket], self._params, dry[0],
                self._k, self._v, table, dry[1], dry[2], *sarr)
            out.block_until_ready()
            if self.spec_enabled:
                self._propose_fns[bucket] = self._build_propose_fn(
                    bucket)
                self._verify_fns[bucket] = self._build_verify_fn(
                    bucket)
                self.spec_step(
                    np.zeros((b,), np.int32), np.zeros((b, bucket),
                                                       np.int32),
                    np.zeros((b,), np.int32), np.zeros((b,), bool),
                    np.zeros((b,), bool))
        self._harvest_calibration()
        self._guard_pending = []  # warmup rows are all-masked noise
        self._warm = True
        return self

    def _harvest_calibration(self):
        """One TIMED warm decode step per bucket into the profiling
        CalibrationStore (programs are warm — real steady-state
        seconds, one extra masked step per bucket at warmup time; the
        grid stays cold-path only)."""
        import time as _time

        try:
            from .. import profiling as _profiling

            if not _profiling.profiling_enabled():
                return
            store = _profiling.calibration_store()
            platform = jax.default_backend()
            b = self.step_rows
            sarr = self._samp_arrays(None, None, None, None)
            for bucket in self.page_buckets:
                t0 = _time.perf_counter()
                out = self._run_decode(
                    self._decode_fns[bucket], self._params,
                    np.zeros((b,), np.int32), self._k, self._v,
                    np.zeros((b, bucket), np.int32),
                    np.zeros((b,), np.int32),
                    np.zeros((b,), bool), *sarr)
                out.block_until_ready()
                seconds = _time.perf_counter() - t0
                store.record(self._digest, platform,
                             f"decode_step[{bucket}]", seconds)
                if bucket == self.page_buckets[-1]:
                    store.record(self._digest, platform, "decode_step",
                                 seconds)
        except Exception as e:
            # calibration is advisory; warmup must never fail — but
            # don't lose the evidence either (serving.registry's
            # warn-once convention)
            import logging

            global _calibration_warned
            if not _calibration_warned:
                _calibration_warned = True
                logging.getLogger(__name__).warning(
                    "decode calibration harvest failed for engine %s: "
                    "%s — continuing without measured-cost records",
                    self._digest, e)

    # -------------------------------------------------------- hot path
    def prefill(self, token_ids, table, *, start=0, seed=0,
                temperature=0.0, top_k=0, top_p=1.0):
        """Fill `table`'s pages with the prompt's K/V; returns the
        first generated token (host int). `table` must already cover
        pages_needed(len(token_ids)).

        `start > 0` is the prefix-cache hit path: positions < start
        already live in (shared) pages, so only the tail runs —
        through the tail program family, whose page table is padded to
        the largest bucket for a static shape. With a draft model
        loaded, the same prompt also prefills the draft pools (same
        pages, draft-shaped K/V)."""
        n = len(token_ids)
        sargs = self._samp_scalars(seed, temperature, top_k, top_p)
        zargs = self._samp_scalars()  # draft prefill output is unused
        if start and self.merged_step_enabled:
            raise PageError(
                "tail prefill has no dedicated program in merged-step "
                "mode: the scheduler feeds tail tokens through step() "
                "rows (MXNET_DECODE_MERGED_STEP=0 restores the split "
                "tail-prefill grid)")
        if start:
            tail = token_ids[start:]
            lb = pick_bucket(len(tail), self.prefill_buckets)
            tokens = np.zeros((1, lb), np.int32)
            tokens[0, :len(tail)] = tail
            max_pages = pages_needed(self.max_context, self.page_size)
            page_ids = np.full((max_pages,), SCRATCH_PAGE, np.int32)
            page_ids[:len(table)] = table
            tok, self._k, self._v = self._tail_fns[lb](
                self._params, tokens, jnp.int32(start), jnp.int32(n),
                self._k, self._v, page_ids, *sargs)
            if self.spec_enabled:
                _, self._dk, self._dv = self._draft_tail_fns[lb](
                    self._draft_params, tokens, jnp.int32(start),
                    jnp.int32(n), self._dk, self._dv, page_ids, *zargs)
        else:
            lb = pick_bucket(n, self.prefill_buckets)
            tokens = np.zeros((1, lb), np.int32)
            tokens[0, :n] = token_ids
            page_ids = np.full((pages_needed(lb, self.page_size),),
                               SCRATCH_PAGE, np.int32)
            page_ids[:len(table)] = table
            tok, self._k, self._v = self._prefill_fns[lb](
                self._params, tokens, jnp.int32(n), self._k, self._v,
                page_ids, *sargs)
            if self.spec_enabled:
                _, self._dk, self._dv = self._draft_prefill_fns[lb](
                    self._draft_params, tokens, jnp.int32(n),
                    self._dk, self._dv, page_ids, *zargs)
        # the sampled token must reach the host to stream/EOS-check —
        # the one deliberate sync of the prefill path
        return int(np.asarray(tok))

    def step(self, tokens, page_table, lengths, active, seeds=None,
             temps=None, top_ks=None, top_ps=None):
        """One continuous-decode step. Row arrays are the fixed
        (step_rows, ...) shapes — max_batch decode rows plus, in
        merged mode, tail_budget ragged tail-prefill rows;
        `page_table.shape[1]` must be a configured bucket. Narrower
        (e.g. legacy (max_batch,)) inputs are padded with masked rows
        so every dispatch replays the one warmed shape, and the
        return is sliced back to the caller's width. Per-row sampling
        params default to greedy. Returns next tokens as a host array
        (the stream/EOS sync — one fetch per step, by design)."""
        bucket = page_table.shape[1]
        b_in = len(tokens)
        r = self.step_rows
        tokens = self._pad_rows(tokens, np.int32, 0)
        lengths = self._pad_rows(lengths, np.int32, 0)
        active = self._pad_rows(active, bool, False)
        if page_table.shape[0] < r:
            page_table = np.concatenate(
                [np.asarray(page_table, np.int32),
                 np.full((r - page_table.shape[0], bucket),
                         SCRATCH_PAGE, np.int32)])
        sarr = self._samp_arrays(seeds, temps, top_ks, top_ps)
        out = self._run_decode(
            self._decode_fns[bucket], self._params, tokens,
            self._k, self._v, page_table, lengths, active, *sarr)
        return np.asarray(out)[:b_in]

    def _pad_rows(self, arr, dtype, fill):
        arr = np.asarray(arr, dtype)
        if len(arr) < self.step_rows:
            arr = np.concatenate(
                [arr, np.full((self.step_rows - len(arr),), fill,
                              dtype)])
        return arr

    def spec_step(self, tokens, page_table, lengths, active,
                  use_draft, seeds=None, temps=None, top_ks=None,
                  top_ps=None):
        """One speculative step: draft proposes K tokens (one
        dispatch), target verifies K+1 positions (one dispatch); the
        drafts and their distributions stay on device between the two.
        Returns (tokens_out (B, K+1), n_emit (B,)) as host arrays in
        ONE fetch — row b emits tokens_out[b, :n_emit[b]]."""
        bucket = page_table.shape[1]
        sarr = self._samp_arrays(seeds, temps, top_ks, top_ps)
        use_draft = np.asarray(use_draft, bool)
        drafts, q_dists, self._dk, self._dv = self._propose_fns[
            bucket](self._draft_params, tokens, self._dk, self._dv,
                    page_table, lengths, active, *sarr)
        tokens_out, n_emit, self._k, self._v = self._verify_fns[
            bucket](self._params, tokens, drafts, q_dists, self._k,
                    self._v, page_table, lengths, active, use_draft,
                    *sarr)
        host_toks, host_n = jax.device_get((tokens_out, n_emit))
        return np.asarray(host_toks), np.asarray(host_n)

    def copy_page(self, src, dst):
        """Device copy of one page (all pools — the draft pools track
        the target's COW decisions): the COW half of
        `BlockAllocator.make_writable`."""
        src = jnp.int32(src)
        dst = jnp.int32(dst)
        self._k = self._copy_fn(self._k, src, dst)
        self._v = self._copy_fn(self._v, src, dst)
        if self._draft_params is not None:
            self._dk = self._copy_fn(self._dk, src, dst)
            self._dv = self._copy_fn(self._dv, src, dst)

    # ----------------------------------------------------- test hooks
    def read_page(self, layer, page):
        """Host copy of one page's (K, V), dequantized to float32 —
        test/debug only (the hot paths never materialize this)."""
        return (np.asarray(_quant.dequant_page(self._k, layer, page)),
                np.asarray(_quant.dequant_page(self._v, layer, page)))

    def read_page_raw(self, layer, page):
        """Host copy of one page's stored (K, V, k_scale, v_scale) —
        the bit-level view quantization tests compare (scale entries
        are None on non-int8 pools)."""
        k, v = self._k, self._v
        return (np.asarray(k.data[layer, page]),
                np.asarray(v.data[layer, page]),
                None if k.scale is None
                else np.asarray(k.scale[layer, page]),
                None if v.scale is None
                else np.asarray(v.scale[layer, page]))

    def probe_logits(self, tokens, page_table, lengths, active):
        """Eager (un-jitted) logits of one decode step over the
        CURRENT pool state, discarding the step's K/V writes — the
        drift oracle bench/CI use to compare kv dtypes position by
        position under teacher forcing. Adds zero traces (nothing is
        jitted) and never mutates the pools."""
        attn = (_attn.get_ragged_kernel(self.kernel_name)
                if self.merged_step_enabled else self._attn)
        logits, _k, _v, _c = _model.decode_logits(
            self._params, jnp.asarray(tokens, jnp.int32), self._k,
            self._v, jnp.asarray(page_table, jnp.int32),
            jnp.asarray(lengths, jnp.int32), jnp.asarray(active, bool),
            cfg=self.cfg, attn=attn)
        return np.asarray(logits, np.float32)


def quant_parity_probe(params, cfg, prompt, max_new=16, *,
                       kv_dtype="int8", page_size=None, num_pages=None,
                       page_buckets=None, kernel=None):
    """Teacher-forced A/B of one greedy decode at float32 vs
    `kv_dtype`: the float32 arm's token stream is replayed through
    BOTH engines token by token, so every step compares the two
    precisions over IDENTICAL context (a free-running comparison
    would stop counting at the first divergence, understating
    agreement). The drift/agreement oracle behind BENCH_MODE=decode's
    quantization keys, ci/check_quant.py, and tests/test_quant.py.

    Returns a dict: `top1_agreement` (fraction of positions where the
    quantized argmax matches float32's), `logit_drift_max` /
    `logit_drift_mean` (abs logit gap via `probe_logits`),
    `kv_pool_capacity_ratio` (measured bytes-per-token ratio),
    `retraces` (quantized arm's post-warmup traces — must be 0), and
    `tokens` (the float32 greedy stream)."""
    names = ("float32", kv_dtype)
    engines, tables, firsts = {}, {}, {}
    for name in names:
        engines[name] = DecodeEngine(
            params, cfg, max_batch=1, page_size=page_size,
            num_pages=num_pages, page_buckets=page_buckets,
            kernel=kernel, prefix_cache=False, merged_step=False,
            kv_dtype=name).warmup()
    ref, alt = engines["float32"], engines[kv_dtype]
    prompt = [int(t) for t in prompt]
    total = len(prompt) + int(max_new)
    if total > ref.max_context:
        raise PageError(
            f"probe needs {total} tokens > context capacity "
            f"{ref.max_context}")
    need = pages_needed(total, ref.page_size)
    bucket = pick_bucket(need, ref.page_buckets)
    p_need = pages_needed(len(prompt), ref.page_size)
    for name in names:
        tables[name] = engines[name].allocator.alloc(need)
        # prefill sees only the prompt-covering prefix of the table
        # (its program sizes page slots by the prompt length bucket);
        # decode steps use the full `need`-page table below
        firsts[name] = engines[name].prefill(
            prompt, tables[name][:p_need])
    floor = {name: engines[name].traces() for name in names}
    agree = 1 if firsts[kv_dtype] == firsts["float32"] else 0
    n_cmp = 1
    drift_max, drift_sum = 0.0, 0.0
    tok = firsts["float32"]
    tokens_out = [tok]
    for t in range(int(max_new) - 1):
        length = len(prompt) + t
        lg, out = {}, {}
        for name in names:
            tbl = np.full((1, bucket), SCRATCH_PAGE, np.int32)
            tbl[0, :need] = tables[name]
            lg[name] = engines[name].probe_logits(
                np.array([tok], np.int32), tbl,
                np.array([length], np.int32),
                np.array([True], bool))[0]
            out[name] = int(engines[name].step(
                [tok], tbl, [length], [True])[0])
        gap = np.abs(lg[kv_dtype] - lg["float32"])
        drift_max = max(drift_max, float(gap.max()))
        drift_sum += float(gap.mean())
        agree += 1 if out[kv_dtype] == out["float32"] else 0
        n_cmp += 1
        tok = out["float32"]
        tokens_out.append(tok)
    ref_bpt = ref.pool_stats()["kv_bytes_per_token"]
    alt_bpt = alt.pool_stats()["kv_bytes_per_token"]
    return {
        "kv_dtype": kv_dtype,
        "top1_agreement": round(agree / n_cmp, 4),
        "positions_compared": n_cmp,
        "logit_drift_max": round(drift_max, 6),
        "logit_drift_mean": round(drift_sum / max(1, n_cmp - 1), 6),
        "kv_pool_capacity_ratio": round(ref_bpt / alt_bpt, 4),
        "kv_bytes_per_token_float32": ref_bpt,
        "kv_bytes_per_token_quant": alt_bpt,
        "retraces": alt.traces() - floor[kv_dtype],
        "tokens": tokens_out,
    }
